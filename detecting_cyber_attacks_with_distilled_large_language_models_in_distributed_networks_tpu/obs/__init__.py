"""Cross-tier observability: tracing, metrics, timelines, fleet health.

Seven pieces (see each module's docstring):

* :mod:`.trace` — round-scoped trace contexts with span ids propagated
  across the TCP wire protocols via an optional meta field; every
  process appends spans to a unified events-JSONL.
* :mod:`.metrics` — in-process counters/gauges/histograms exposed over a
  stdlib-HTTP ``/metrics`` endpoint in Prometheus text format, plus the
  machine-readable ``/metrics.json`` twin.
* :mod:`.timeline` — the ``fedtpu obs`` merge/analysis layer: per-round
  timeline tables and Chrome trace-event export.
* :mod:`.slo` — declarative SLOs evaluated as multi-window burn rates
  over metric-snapshot deltas, with fire/clear alert state machines.
* :mod:`.fleet` — the scrape hub behind ``fedtpu obs health|watch``:
  poll every daemon, merge into fleet snapshots, judge the SLOs.
* :mod:`.flight` — the failure flight recorder: bounded in-memory rings
  dumped as postmortem bundles on round failure / eject storm / SLO page.
* :mod:`.profile` — the device performance plane: XLA compile ledger
  (per-site compile/recompile accounting), strided fenced step-time
  attribution, device-memory watermarks, and the analytic-vs-XLA FLOPs
  cross-check behind ``fedtpu obs profile`` / ``BENCH_MODE=profile``.
* :mod:`.sentinel` — the sentinel watch daemon behind ``fedtpu obs
  sentinel``: known-truth canary probes through the live serving chain,
  continuous journal-tailing supervised drift between gates, and a
  long-horizon retention ring with pinned-baseline regression verdicts.
"""

from .flight import (  # noqa: F401
    FlightRecorder,
    get_global_recorder,
    list_bundles,
    load_bundle,
    set_global_recorder,
)
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    MetricsServer,
    default_registry,
    maybe_start_metrics_server,
)
from .profile import (  # noqa: F401
    FLOPS_RATIO_TOLERANCE,
    CompileLedger,
    StepProfiler,
    default_ledger,
    device_memory_stats,
    memory_report,
    note_memory,
    profile_stride,
    render_profile_report,
    run_profile_session,
    set_profile_stride,
    xla_cost_flops,
)
from .slo import (  # noqa: F401
    SLO,
    AlertManager,
    default_slos,
    slos_from_spec,
)
from .fleet import (  # noqa: F401
    ScrapeHub,
    Target,
    health_verdict,
    parse_target,
)
from .sentinel import (  # noqa: F401
    CanaryFlow,
    CanaryProber,
    JournalTail,
    RetentionRing,
    Sentinel,
    load_canary_flows,
)
from .timeline import (  # noqa: F401
    chrome_trace,
    export_chrome_trace,
    group_rounds,
    load_spans,
    round_breakdown,
    round_summaries,
    tail_spans,
    timeline_table,
)
from .trace import (  # noqa: F401
    SCHEMA,
    SPAN_NAMES,
    TRACE_META_KEY,
    Tracer,
    get_global_tracer,
    get_run_id,
    maybe_span,
    new_trace_id,
    set_global_tracer,
)
