"""Device performance plane: compile ledger, step attribution, watermarks.

The fleet health plane (obs/slo.py + obs/fleet.py) watches processes;
nothing watched the *device* layer — a silent XLA recompile storm, a
host-sync stall, or device-memory creep was invisible until it surfaced
as a worse MFU headline with no attribution. The reference's entire
profiling story is timestamped prints plus tqdm rates (SURVEY.md §5).
This module is the device-side judgment layer, four pieces:

* :class:`CompileLedger` — the serving tier's trace-hook discipline
  (serving/engine.py pioneered it: the Python body of a jitted function
  runs once per traced shape, so a counter inside the body IS a compile
  hook) generalized repo-wide. Every jitted program registers a trace
  hook under a **site** name; the ledger records compiles per
  (site, shape-signature) with trace wall seconds, exports
  ``fedtpu_xla_compiles_total`` / ``fedtpu_xla_recompiles_total`` /
  ``fedtpu_xla_trace_seconds`` on /metrics, emits an ``xla-compile``
  span into the closed vocabulary, and — after :meth:`mark_warm` —
  flags any NEW signature at a known site as a **recompile** event that
  can trip the PR-10 flight recorder (``xla-recompile`` bundles).
* :class:`StepProfiler` — deterministically-strided fenced step timers:
  every Nth step is split into host batch-prep / dispatch /
  device-execute with ``jax.block_until_ready`` fences, observed into
  ``fedtpu_train_step_seconds`` / ``fedtpu_score_step_seconds``
  histograms and stamped as attrs on the existing train-phase spans so
  the PR-4 timeline can render a device-vs-host row. Stride 0 (the
  default) is the zero-overhead path: one attribute check per step,
  no fences, no timer reads, no metric registration.
* **Memory watermarks** — :func:`note_memory` snapshots
  ``device.memory_stats()`` at phase boundaries (post-restore,
  post-first-step, post-round, post-aggregate) into peak-bytes gauges,
  degrading gracefully to "unavailable" on backends that return None
  (the CPU tier-1 lane).
* **Cost-analysis cross-check** — :func:`xla_cost_flops` pulls
  ``compiled.cost_analysis()`` FLOPs for a jitted program so the
  analytic ``train_step_flops`` behind the MFU headline can be pinned
  against what XLA actually built (:data:`FLOPS_RATIO_TOLERANCE`).

``run_profile_session`` drives all four end-to-end (the single
implementation behind ``fedtpu obs profile`` and ``BENCH_MODE=profile``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterator, Mapping

from .metrics import MetricsRegistry, default_registry

#: XLA-vs-analytic FLOPs ratio bounds the bench pins (documented in the
#: README "Device profiling" section). XLA's cost model counts the same
#: 2·M·N·K per matmul the analytic model does, but additionally counts
#: elementwise/softmax/optimizer FLOPs the analytic model deliberately
#: excludes, while fusion can eliminate work the analytic model keeps —
#: so the ratio hovers near 1 and [0.5, 2.0] flags a real divergence
#: (wrong model config, a broken backward path, a cost model reading a
#: different program) without flaking on backend differences.
FLOPS_RATIO_TOLERANCE = (0.5, 2.0)

#: Trace/compile wall-time histogram edges: compiles run 10 ms (tiny
#: CPU programs) to minutes (BERT-large on a cold TPU).
TRACE_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 300.0)

#: Step-phase histogram edges: 100 µs host prep to multi-second steps.
STEP_BUCKETS = (
    1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
)

#: StepProfiler site -> /metrics histogram family (one literal per
#: family, registered from this module only — the obs-metric-once
#: contract). The "wire" site covers the TCP tier's pack/unpack hot
#: loops (comm/client.py streamed-upload leaf encode + streamed-reply
#: leaf decode) — the PR-12 device-plane residual.
_STEP_FAMILIES = {
    "train": "fedtpu_train_step_seconds",
    "score": "fedtpu_score_step_seconds",
    "wire": "fedtpu_wire_step_seconds",
}

#: Per-site phase vocabulary: the train/score sites split a step into
#: host/dispatch/device; the wire site times one leaf's encode or
#: decode as a single "wire" phase (direction comes from the span the
#: attrs land on: wire-upload = pack, wire-reply = unpack).
_SITE_PHASES = {
    "wire": ("wire",),
}


# ------------------------------------------------------------ compile ledger
class _Site:
    """Per-site ledger state (guarded by the owning ledger's lock)."""

    __slots__ = (
        "name", "sigs", "trace_s", "warm", "timed", "fresh", "gen",
        "inflight",
    )

    def __init__(self, name: str):
        self.name = name
        self.sigs: dict[Any, int] = {}  # signature -> trace count
        self.trace_s: dict[Any, float] = {}  # signature -> wall seconds
        self.warm = False
        self.timed = False  # a timed() wrapper owns span emission
        self.fresh: list[tuple[Any, bool]] = []  # (sig, recompile) in-flight
        self.gen = 0  # bumps per note — the timed wrapper's cheap check
        self.inflight = 0  # wrapper calls currently executing


class CompileLedger:
    """Compiles per (site, shape-signature), with recompile flagging.

    Two touch points per jitted program:

    * ``note = ledger.hook("tier.step")`` returns the trace-time
      callable; the jitted body calls ``note(signature)`` — it executes
      once per traced shape and never at dispatch time, so the hot path
      pays nothing.
    * ``fn = ledger.timed("tier.step", jax.jit(body))`` wraps the
      jitted callable so the wall seconds of any call during which a
      trace fired are attributed to that compile (trace+compile happen
      inside the first dispatch). The wrapper costs two monotonic reads
      and one plain int compare per call; it exposes the jitted
      original as ``__wrapped__`` (``xla_cost_flops`` needs ``lower``).

    ``mark_warm()`` freezes the signature set: a NEW signature at a
    warm site afterwards is a *recompile* — counted, logged, listed in
    :meth:`recompiles`, and offered to the installed flight recorder
    (``maybe_dump("xla-recompile")``, rate-limited by the recorder).
    This is serving/engine.py's compile-count-asserted discipline made
    repo-wide.

    Thread-safe; the default process-wide instance is
    :func:`default_ledger` (serving engines hold private instances so
    per-engine ``compile_counts`` stay per-engine while the /metrics
    families — get-or-create on the shared registry — stay process
    totals).
    """

    def __init__(self, *, registry: MetricsRegistry | None = None):
        self._lock = threading.Lock()
        self._sites: dict[str, _Site] = {}
        self._reg = registry or default_registry()
        self._events: list[dict] = []  # recompile events, oldest first

    # ------------------------------------------------------------- plumbing
    def _site(self, name: str) -> _Site:
        site = self._sites.get(name)
        if site is None:
            site = self._sites.setdefault(name, _Site(str(name)))
        return site

    def _metrics(self, site: str):
        return (
            self._reg.counter(
                "fedtpu_xla_compiles_total",
                help="XLA traces/compiles per jitted site",
                labels={"site": site},
            ),
            self._reg.counter(
                "fedtpu_xla_recompiles_total",
                help="new shape signatures traced at a warm site",
                labels={"site": site},
            ),
            self._reg.histogram(
                "fedtpu_xla_trace_seconds",
                help="wall seconds of calls that traced+compiled",
                labels={"site": site},
                buckets=TRACE_BUCKETS,
            ),
        )

    # ------------------------------------------------------------ recording
    def hook(self, site: str) -> Callable[[Any], None]:
        """The trace-time callable for ``site`` — call it inside the
        jitted body with a hashable shape signature."""
        name = str(site)

        def note(signature: Any) -> None:
            self.note(name, signature)

        return note

    def note(self, site: str, signature: Any) -> None:
        """Record one trace of ``signature`` at ``site`` (called from
        inside a traced body — i.e. exactly once per compilation)."""
        emit_span = False
        recompile = False
        with self._lock:
            s = self._site(site)
            count = s.sigs.get(signature, 0) + 1
            s.sigs[signature] = count
            s.gen += 1
            recompile = s.warm and count == 1
            if recompile:
                self._events.append(
                    {
                        "site": site,
                        "signature": signature,
                        "ts": time.time(),
                    }
                )
            # Defer span/time attribution to the timed wrapper ONLY
            # when one is actually in flight: a trace fired outside it
            # (xla_cost_flops lowering the unwrapped jit, a direct AOT
            # path) would otherwise sit stale in `fresh` and corrupt
            # the NEXT attributed compile's wall-second share.
            deferred = s.timed and s.inflight > 0
            if deferred:
                s.fresh.append((signature, recompile))
            emit_span = not deferred
        compiles, recompiles, _hist = self._metrics(site)
        compiles.inc()
        if recompile:
            recompiles.inc()
            self._flag_recompile(site, signature)
        if emit_span:
            # Untimed site: the span still lands (dur unknowable from
            # trace time alone); a timed() wrapper emits it instead,
            # with the measured wall seconds.
            self._emit_span(site, signature, 0.0, recompile)

    def _flag_recompile(self, site: str, signature: Any) -> None:
        from ..utils.logging import get_logger

        get_logger().warning(
            f"[XLA] recompile at warm site {site!r}: new shape "
            f"signature {signature!r} — a shape leak on a hot path "
            "(bucket the input, or mark_warm later)"
        )
        # Flight recorder (obs/flight.py): a recompile storm mid-traffic
        # is exactly the moment whose surrounding spans an operator
        # wants preserved. maybe_dump rate-limits per reason; a dump
        # failure must never break the training/serving path.
        from .flight import get_global_recorder

        recorder = get_global_recorder()
        if recorder is not None:
            try:
                recorder.maybe_dump(
                    "xla-recompile",
                    extra={"site": site, "signature": repr(signature)},
                )
            except OSError:
                pass

    def _emit_span(
        self, site: str, signature: Any, dur_s: float, recompile: bool
    ) -> None:
        from .trace import get_global_tracer

        tracer = get_global_tracer()
        if tracer is None:
            return
        tracer.record(
            "xla-compile",
            t_start=time.time() - dur_s,
            dur_s=dur_s,
            site=site,
            signature=repr(signature),
            recompile=True if recompile else None,
        )

    def timed(self, site: str, fn: Callable) -> Callable:
        """Wrap a jitted callable: wall seconds of any call during which
        ``site`` traced are attributed as that compile's trace time."""
        name = str(site)
        with self._lock:
            self._site(name).timed = True

        def wrapper(*args, **kwargs):
            s = self._sites[name]
            gen0 = s.gen
            # Plain GIL-atomic counter (no lock on the hot path): note()
            # only defers to the wrapper while a call is in flight.
            s.inflight += 1
            t0 = time.monotonic()
            try:
                out = fn(*args, **kwargs)
            finally:
                s.inflight -= 1
            if s.gen != gen0:  # a trace fired during this call
                self._attribute(s, time.monotonic() - t0)
            return out

        wrapper.__wrapped__ = fn
        return wrapper

    def _attribute(self, s: _Site, dt: float) -> None:
        with self._lock:
            fresh, s.fresh = s.fresh, []
        if not fresh:
            return
        share = dt / len(fresh)
        _c, _r, hist = self._metrics(s.name)
        with self._lock:
            for sig, _rec in fresh:
                s.trace_s[sig] = s.trace_s.get(sig, 0.0) + share
        for sig, rec in fresh:
            hist.observe(share)
            self._emit_span(s.name, sig, share, rec)

    # ------------------------------------------------------------- lifecycle
    def mark_warm(self, site: str | None = None) -> None:
        """Freeze the signature set (all sites, or one): any new
        signature afterwards is flagged as a recompile. Call after the
        warmup phase — the serving engine does it from ``warmup()``."""
        with self._lock:
            targets = (
                [self._site(site)] if site is not None
                else list(self._sites.values())
            )
            for s in targets:
                s.warm = True

    # ------------------------------------------------------------- reporting
    def compile_counts(self, site: str) -> dict[Any, int]:
        """signature -> trace count for one site (the serving engine's
        ``compile_counts`` contract rides this verbatim)."""
        with self._lock:
            s = self._sites.get(site)
            return dict(s.sigs) if s is not None else {}

    def recompiles(self, site: str | None = None) -> list[dict]:
        """Flagged recompile events, oldest first — exactly one per new
        signature at a warm site."""
        with self._lock:
            return [
                dict(e)
                for e in self._events
                if site is None or e["site"] == site
            ]

    def report(self) -> dict:
        """``{site: {compiles, signatures, trace_s, warm}}`` + events."""
        with self._lock:
            sites = {
                name: {
                    "compiles": sum(s.sigs.values()),
                    "signatures": len(s.sigs),
                    "trace_s": round(sum(s.trace_s.values()), 4),
                    "warm": s.warm,
                }
                for name, s in sorted(self._sites.items())
            }
            return {
                "sites": sites,
                "compile_count": sum(
                    s["compiles"] for s in sites.values()
                ),
                "recompiles": [dict(e) for e in self._events],
            }


_LEDGER_LOCK = threading.Lock()
_LEDGER: CompileLedger | None = None


def default_ledger() -> CompileLedger:
    """The process-wide ledger every jitted tier notes into (the
    default-registry pattern: no plumbing to share one /metrics view)."""
    global _LEDGER
    with _LEDGER_LOCK:
        if _LEDGER is None:
            _LEDGER = CompileLedger()
        return _LEDGER


# --------------------------------------------------------- step attribution
def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


class StepProfiler:
    """Deterministically-strided fenced step timers.

    ``tick()`` advances the step counter and answers "is this step
    sampled" (step k is sampled iff ``k % stride == 0`` — a plain
    counter stride, no RNG, so reruns sample identically and the
    `fedtpu check` determinism discipline is untouched). On sampled
    steps the caller brackets the three phases:

    * ``note_host(dt)`` — input-pipeline work (batch gather/pad),
    * ``note_dispatch(dt)`` — the jitted call's Python return time,
    * ``fence(value)`` — ``jax.block_until_ready`` + the wait recorded
      as device-execute time (``drain(value)`` first empties the async
      queue so the sampled step measures itself, not its backlog).

    Unsampled steps — and every step at stride 0, the default — pay one
    attribute read. Stride 0 additionally registers nothing on the
    metrics registry.
    """

    PHASES = ("host", "dispatch", "device")

    def __init__(
        self,
        stride: int,
        *,
        site: str = "train",
        registry: MetricsRegistry | None = None,
        max_samples: int = 4096,
    ):
        self.stride = int(stride)
        self.enabled = self.stride > 0
        self.site = str(site)
        # Per-site phase vocabulary (the wire site has one phase; the
        # step sites keep the host/dispatch/device split).
        self.phases: tuple[str, ...] = _SITE_PHASES.get(self.site, self.PHASES)
        self._n = 0
        self._lock = threading.Lock()
        self._samples: dict[str, list[float]] = {p: [] for p in self.phases}
        self._max_samples = int(max_samples)
        self._hists = None
        if self.enabled:
            family = _STEP_FAMILIES.get(self.site)
            if family is not None:
                reg = registry or default_registry()
                self._hists = {
                    p: reg.histogram(
                        family,
                        help="sampled step seconds by phase",
                        labels={"phase": p},
                        buckets=STEP_BUCKETS,
                    )
                    for p in self.phases
                }

    # ------------------------------------------------------------- sampling
    def tick(self) -> bool:
        """Advance the step counter; True when THIS step is sampled."""
        if not self.enabled:
            return False
        n = self._n
        self._n = n + 1
        return n % self.stride == 0

    def clock(self) -> float:
        return time.monotonic()

    def drain(self, value: Any) -> None:
        """Fence the async dispatch queue BEFORE timing a sampled step,
        so the device-execute measurement is this step's own work and
        not the backlog of the unsampled steps before it."""
        if value is not None:
            import jax

            jax.block_until_ready(value)

    def _note(self, phase: str, dt: float) -> None:
        with self._lock:
            vals = self._samples[phase]
            if len(vals) < self._max_samples:
                vals.append(float(dt))
        if self._hists is not None:
            self._hists[phase].observe(float(dt))

    def note(self, phase: str, dt: float) -> None:
        """Record one sampled duration for a named phase — the generic
        entry for sites whose phases aren't the host/dispatch/device
        split (the wire pack/unpack loops note ``"wire"``)."""
        if phase not in self._samples:
            raise ValueError(
                f"unknown phase {phase!r} for site {self.site!r} "
                f"(have {self.phases})"
            )
        self._note(phase, dt)

    def note_host(self, dt: float) -> None:
        self._note("host", dt)

    def note_dispatch(self, dt: float) -> None:
        self._note("dispatch", dt)

    def fence(self, value: Any) -> None:
        """Block until ``value`` is ready; the wait is device time."""
        import jax

        t0 = time.monotonic()
        jax.block_until_ready(value)
        self._note("device", time.monotonic() - t0)

    # ------------------------------------------------------------ reporting
    def begin_window(self) -> None:
        """Start a fresh reporting window (one fit/round): the sample
        lists are CLEARED, so summary/span_attrs always describe the
        current window and a long-lived daemon can never fill the
        sample bound once and silently stop reporting (the histograms
        above carry the cumulative record)."""
        with self._lock:
            for p in self.phases:
                self._samples[p].clear()

    def _phase_stats(self, vals: list[float]) -> dict | None:
        if not vals:
            return None
        v = sorted(vals)
        return {
            "n": len(v),
            "p50": _percentile(v, 0.50),
            "p95": _percentile(v, 0.95),
        }

    def summary(self) -> dict:
        """{phase: {n, p50, p95}} in seconds over the current window
        (empty when no samples)."""
        with self._lock:
            out = {}
            for p in self.phases:
                st = self._phase_stats(self._samples[p])
                if st is not None:
                    out[p] = st
            return out

    def span_attrs(self) -> dict:
        """Flat span attrs (milliseconds) for stamping on the existing
        train-phase spans — the timeline's device-vs-host row."""
        s = self.summary()
        out: dict[str, Any] = {}
        for p, st in s.items():
            out[f"step_{p}_ms_p50"] = round(st["p50"] * 1e3, 3)
            out[f"step_{p}_ms_p95"] = round(st["p95"] * 1e3, 3)
        if s:
            out["step_sampled"] = max(st["n"] for st in s.values())
        return out


_STRIDE_LOCK = threading.Lock()
_PROFILE_STRIDE = 0


def set_profile_stride(stride: int) -> None:
    """Install the process-wide step-profiling stride (0 = off, the
    default). The CLI calls this from ``--profile-stride`` /
    ObsConfig.profile_stride BEFORE trainers/engines are built — they
    read it once at construction."""
    global _PROFILE_STRIDE
    with _STRIDE_LOCK:
        _PROFILE_STRIDE = max(0, int(stride))


def profile_stride() -> int:
    # Lock-free read (a GIL-atomic int load): the scoring hot path asks
    # per call and must not pay a lock acquire for "off".
    return _PROFILE_STRIDE


def maybe_step_profiler(site: str) -> StepProfiler | None:
    """A StepProfiler when profiling is armed process-wide, else None —
    the construction-time hook trainers and engines call. None keeps
    the hot loops on the literal pre-profiling code path."""
    stride = profile_stride()
    if stride <= 0:
        return None
    return StepProfiler(stride, site=site)


# ---------------------------------------------------------- memory watermarks
_MEM_LOCK = threading.Lock()
_MEM_REPORT: dict[str, dict] = {}


def device_memory_stats(device: Any = None) -> dict | None:
    """``device.memory_stats()`` with every backend quirk absorbed:
    returns a plain dict, or None when the backend has no stats (CPU),
    returns None, or raises — the graceful-"unavailable" contract the
    CPU tier-1 lane depends on. Never IMPORTS jax: a host-only daemon
    (the TCP aggregation server) that calls :func:`note_memory` at a
    phase boundary must not pay a backend init for an unavailable
    answer — no jax in ``sys.modules`` means no device work happened
    in this process, so "unavailable" is already correct."""
    try:
        if device is None:
            import sys

            jax = sys.modules.get("jax")
            if jax is None:
                return None
            device = jax.local_devices()[0]
        stats_fn = getattr(device, "memory_stats", None)
        if stats_fn is None:
            return None
        stats = stats_fn()
    except Exception:
        return None
    if not stats:
        return None
    return dict(stats)


def note_memory(
    phase: str,
    *,
    device: Any = None,
    registry: MetricsRegistry | None = None,
) -> dict | None:
    """Snapshot device memory at a phase boundary (post-restore /
    post-first-step / post-round / post-aggregate). Returns the
    snapshot, or None when the backend exposes no stats — the phase is
    still recorded as unavailable so ``memory_report`` shows it was
    visited."""
    stats = device_memory_stats(device)
    phase = str(phase)
    if stats is None:
        with _MEM_LOCK:
            _MEM_REPORT.setdefault(phase, {"available": False})
        return None
    in_use = float(stats.get("bytes_in_use", 0.0))
    peak = float(stats.get("peak_bytes_in_use", in_use))
    snap = {
        "available": True,
        "bytes_in_use": in_use,
        "peak_bytes": peak,
        "ts": time.time(),
    }
    with _MEM_LOCK:
        prev = _MEM_REPORT.get(phase)
        if prev is not None and prev.get("available"):
            # Watermark semantics: keep the high-water peak across
            # repeated visits (every round hits post-round).
            snap["peak_bytes"] = max(peak, prev["peak_bytes"])
        _MEM_REPORT[phase] = snap
    reg = registry or default_registry()
    reg.gauge(
        "fedtpu_device_bytes_in_use",
        help="device bytes in use at the last phase-boundary snapshot",
        labels={"phase": phase},
    ).set(in_use)
    reg.gauge(
        "fedtpu_device_peak_bytes",
        help="high-water device bytes across phase-boundary snapshots",
        labels={"phase": phase},
    ).set(snap["peak_bytes"])
    return snap


def memory_report() -> dict[str, dict]:
    """phase -> last snapshot (``{"available": False}`` for phases
    visited on stats-less backends)."""
    with _MEM_LOCK:
        return {k: dict(v) for k, v in _MEM_REPORT.items()}


def peak_device_bytes() -> float:
    """The process high-water mark over every recorded phase (0.0 when
    no backend stats were ever available)."""
    with _MEM_LOCK:
        return max(
            (
                v["peak_bytes"]
                for v in _MEM_REPORT.values()
                if v.get("available")
            ),
            default=0.0,
        )


# ------------------------------------------------------ cost-analysis check
def xla_cost_flops(fn: Callable, *args: Any, **kwargs: Any) -> float | None:
    """FLOPs of the program XLA actually built for ``fn(*args)``, via
    ``lowered.compile().cost_analysis()`` — or None when the callable
    is not lowerable or the backend exposes no cost model. ``fn`` may
    be a :meth:`CompileLedger.timed` wrapper (unwrapped here)."""
    fn = getattr(fn, "__wrapped__", fn)
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    try:
        cost = lower(*args, **kwargs).compile().cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, Mapping):
        return None
    flops = cost.get("flops")
    try:
        flops = float(flops)
    except (TypeError, ValueError):
        return None
    return flops if flops > 0.0 else None


def flops_ratio_ok(ratio: float | None) -> bool:
    """None (no cost model on this backend) is not a failure; a number
    outside :data:`FLOPS_RATIO_TOLERANCE` is."""
    if ratio is None:
        return True
    lo, hi = FLOPS_RATIO_TOLERANCE
    return lo <= ratio <= hi


# ------------------------------------------------------------- full session
def run_profile_session(
    model_cfg=None,
    train_cfg=None,
    *,
    steps: int = 8,
    batch_size: int = 16,
    stride: int = 1,
    warmup: int = 2,
    capture_dir: str | None = None,
    serving: bool = True,
    seed: int = 0,
) -> dict:
    """One end-to-end pass over the device performance plane: train
    ``steps`` real engine steps with the step profiler armed, snapshot
    memory at the phase boundaries, cross-check analytic vs XLA FLOPs,
    and storm the bucketed serving path asserting zero recompiles.
    The single implementation behind ``fedtpu obs profile`` and
    ``BENCH_MODE=profile``; ``capture_dir`` wraps ``jax.profiler``
    around the profiled steps (utils/profiling.trace)."""
    import jax
    import numpy as np

    from ..config import ModelConfig, TrainConfig
    from ..train.engine import Trainer
    from ..utils.profiling import trace, train_step_flops

    model_cfg = model_cfg or ModelConfig()
    train_cfg = train_cfg or TrainConfig()
    ledger = default_ledger()
    before = ledger.report()
    events_before = len(before["recompiles"])

    trainer = Trainer(model_cfg, train_cfg)
    # The session drives its own manual step loop below (tick/drain/
    # fence directly) rather than trainer.fit — the fit-loop
    # integration has its own tests.
    prof = StepProfiler(stride, site="train")
    rng = np.random.default_rng(seed)
    L = model_cfg.max_len
    # The batch stays HOST-side: each sampled step times its device_put
    # as the host batch-prep phase (what an input pipeline pays per
    # step), so the session reports all three phases like the fit loops.
    batch = {
        "input_ids": rng.integers(
            0, model_cfg.vocab_size, (batch_size, L)
        ).astype(np.int32),
        "attention_mask": np.ones((batch_size, L), np.int32),
        "labels": rng.integers(0, 2, batch_size).astype(np.int32),
    }
    state = trainer.init_state(seed=seed)
    loss = None
    # Warmup FIRST, through the timed wrapper, so the compile's wall
    # seconds are attributed to the ledger (the cost-analysis lowering
    # below then rides the already-populated trace cache).
    for _ in range(max(1, warmup)):
        state, loss = trainer.train_step(state, batch)
    jax.block_until_ready(loss)
    note_memory("post-first-step")
    # XLA's own FLOPs for the step just compiled (lower+compile never
    # executes, and a donated-buffer state is still lowerable — only
    # avals are read). Before mark_warm: a backend that re-traces here
    # must count a compile, not flag a recompile.
    flops_xla = xla_cost_flops(trainer.train_step, state, batch)
    flops_analytic = train_step_flops(model_cfg, batch_size)
    ratio = (
        flops_xla / flops_analytic
        if flops_xla is not None and flops_analytic > 0
        else None
    )
    # Warm ONLY the site this session just exercised: a blanket
    # mark_warm would freeze sibling sites with zero or partial
    # signature sets and misflag their next legitimate first compile
    # (e.g. the headline bench tracing a different batch size right
    # after BENCH_MODE=profile) as a shape leak.
    ledger.mark_warm("engine.train_step")

    with trace(capture_dir):
        for _ in range(max(1, steps)):
            if prof.tick():
                prof.drain(loss)
                t_h = prof.clock()
                placed = {k: jax.device_put(v) for k, v in batch.items()}
                prof.note_host(prof.clock() - t_h)
                t_d = prof.clock()
                state, loss = trainer.train_step(state, placed)
                prof.note_dispatch(prof.clock() - t_d)
                prof.fence(loss)
            else:
                state, loss = trainer.train_step(state, batch)
    jax.block_until_ready(loss)
    note_memory("post-round")

    serving_report = None
    if serving:
        serving_report = _serving_bucket_storm(seed=seed)

    after = ledger.report()
    sites = {}
    for name, rec in after["sites"].items():
        prev = before["sites"].get(name)
        compiles = rec["compiles"] - (prev["compiles"] if prev else 0)
        if compiles > 0:
            sites[name] = {
                "compiles": compiles,
                "signatures": rec["signatures"],
                "trace_s": round(
                    rec["trace_s"] - (prev["trace_s"] if prev else 0.0), 4
                ),
            }
    recompiles = after["recompiles"][events_before:]
    report = {
        "sites": sites,
        "compile_count": sum(s["compiles"] for s in sites.values()),
        "recompiles": recompiles,
        "step": prof.summary(),
        "stride": stride,
        "memory": memory_report(),
        "peak_device_bytes": peak_device_bytes(),
        "flops_analytic": flops_analytic,
        "flops_xla": flops_xla,
        "flops_ratio": round(ratio, 4) if ratio is not None else None,
        "flops_ratio_ok": flops_ratio_ok(ratio),
        "flops_tolerance": list(FLOPS_RATIO_TOLERANCE),
        "capture_dir": capture_dir,
    }
    if serving_report is not None:
        report["serving"] = serving_report
    return report


def _serving_bucket_storm(*, seed: int = 0) -> dict:
    """Warm a tiny bucketed ScoreEngine, then storm mixed batch sizes:
    the bucket ladder must absorb every size into an already-compiled
    shape — recompiles asserted 0 (the compile-count discipline the
    serving tests pin, exercised live)."""
    import jax
    import numpy as np

    from ..config import ModelConfig
    from ..models.distilbert import DDoSClassifier, init_params
    from ..serving.engine import ScoreEngine

    cfg = ModelConfig.tiny()
    eng = ScoreEngine(
        cfg,
        init_params(DDoSClassifier(cfg), cfg, jax.random.key(seed)),
        buckets=(1, 4),
    )
    eng.warmup()  # pays both bucket compiles, then marks the site warm
    rng = np.random.default_rng(seed)
    L = cfg.max_len
    for n in (1, 2, 3, 4, 1, 4, 2):
        ids = rng.integers(0, cfg.vocab_size, (n, L)).astype(np.int32)
        mask = np.ones((n, L), np.int32)
        eng.score(ids, mask)
    counts = eng.compile_counts
    return {
        "compiles": sum(counts.values()),
        "signatures": len(counts),
        "recompiles": len(eng.ledger.recompiles()),
        "buckets": list(eng.buckets),
    }


def render_profile_report(report: dict) -> str:
    """The ``fedtpu obs profile`` human rendering of a session report."""
    out: list[str] = []
    out.append("compile ledger (this session):")
    sites = report.get("sites") or {}
    if sites:
        out.append(
            f"  {'site':<24} {'compiles':>9} {'signatures':>11} "
            f"{'trace_s':>9}"
        )
        for name, s in sorted(sites.items()):
            out.append(
                f"  {name:<24} {s['compiles']:>9} {s['signatures']:>11} "
                f"{s['trace_s']:>9.3f}"
            )
    else:
        out.append("  (no compiles — every program was already warm)")
    rec = report.get("recompiles") or []
    if rec:
        out.append(f"recompiles: {len(rec)} FLAGGED")
        for e in rec:
            out.append(f"  {e['site']}  signature {e['signature']!r}")
    else:
        out.append("recompiles: none")
    step = report.get("step") or {}
    if step:
        out.append(f"step time (stride {report.get('stride')}, sampled):")
        for phase in StepProfiler.PHASES:
            st = step.get(phase)
            if st:
                out.append(
                    f"  {phase:<9} p50 {st['p50'] * 1e3:8.2f}ms  "
                    f"p95 {st['p95'] * 1e3:8.2f}ms  ({st['n']} samples)"
                )
    mem = report.get("memory") or {}
    out.append("memory watermarks:")
    if mem:
        for phase, snap in mem.items():
            if snap.get("available"):
                out.append(
                    f"  {phase:<16} {snap['bytes_in_use'] / 1e6:9.1f} MB "
                    f"in use, peak {snap['peak_bytes'] / 1e6:9.1f} MB"
                )
            else:
                out.append(
                    f"  {phase:<16} unavailable (backend exposes no "
                    "memory_stats)"
                )
    else:
        out.append("  (no snapshots)")
    lo, hi = report.get("flops_tolerance", FLOPS_RATIO_TOLERANCE)
    ratio = report.get("flops_ratio")
    out.append(
        "flops cross-check: analytic "
        f"{report.get('flops_analytic', 0.0):.3g}, xla "
        + (
            f"{report['flops_xla']:.3g}, ratio {ratio}"
            f" (tolerance {lo}-{hi}"
            + (", OK)" if report.get("flops_ratio_ok") else ", BROKEN)")
            if report.get("flops_xla") is not None
            else "unavailable (no cost model on this backend)"
        )
    )
    srv = report.get("serving")
    if srv:
        out.append(
            f"serving bucketed path: {srv['compiles']} compiles over "
            f"buckets {srv['buckets']}, {srv['recompiles']} recompiles"
            + (" (OK)" if srv["recompiles"] == 0 else " (BROKEN)")
        )
    if report.get("capture_dir"):
        out.append(
            f"jax.profiler capture: {report['capture_dir']} "
            "(view with xprof/tensorboard)"
        )
    return "\n".join(out) + "\n"


def profiled_step_iter(
    profiler: "StepProfiler | None", batches: Iterator
) -> Iterator[tuple[Any, bool]]:
    """Yield ``(batch, sampled)`` pairs, timing host batch-prep on the
    sampled steps — the shared loop shim for the engine and federated
    fit loops (profiling off = the bare iterator, zero overhead)."""
    it = iter(batches)
    if profiler is None or not profiler.enabled:
        for batch in it:
            yield batch, False
        return
    while True:
        sampled = profiler.tick()
        t0 = profiler.clock() if sampled else 0.0
        try:
            batch = next(it)
        except StopIteration:
            return
        if sampled:
            profiler.note_host(profiler.clock() - t0)
        yield batch, sampled
