"""Declarative SLOs evaluated as multi-window burn rates over /metrics.

The fleet's daemons export Prometheus counters and histograms (PR 4),
but nothing WATCHES them — a scoring tier whose p99 quietly doubles, a
round cadence that rots under stragglers, or a replica eject storm all
scroll past as numbers nobody reads (the silent-regression failure mode
the FL-communication surveys call out). This module is the judgment
layer: the operator declares what "healthy" means once, and the scrape
hub (obs/fleet.py) evaluates it continuously.

The evaluation model is the SRE-workbook **multi-window burn rate**:

* An :class:`SLO` promises that at least ``objective`` of events are
  good — good = a histogram observation at or under a latency bound
  (``kind="latency"``), or membership in the complement of a bad-event
  counter over a total counter (``kind="ratio"``). The error budget is
  ``1 - objective``.
* The **burn rate** over a window is ``bad_fraction / budget`` computed
  on DELTAS of the cumulative snapshots (the same ``increase()``
  arithmetic a Prometheus alert would run). Burn 1.0 = spending budget
  exactly as fast as the objective allows; 14.4 = the classic
  page-worthy pace (2% of a 30-day budget in one hour).
* An alert **fires** only when EVERY configured window breaches its
  factor — the long window keeps one blip from paging, the short window
  proves the problem is still happening — and **clears** when the
  shortest window's burn drops back under its factor (no fresh bad
  events = the budget stops burning; a trafficless window burns
  nothing by definition).

Everything here is pure arithmetic over ``(now, snapshot)`` pairs the
caller supplies — no wall-clock reads, no sleeps — so the burn state
machine is unit-testable from synthetic histogram deltas and the
`fedtpu check` determinism discipline stays trivially intact. Fired and
cleared events append to an alerts-JSONL (one atomic line each, the
obs/trace.py writer) and optionally trip the failure flight recorder
(obs/flight.py) on page-severity fires.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Mapping

from .trace import append_jsonl_line

#: Schema tag on every alert-JSONL record.
ALERT_SCHEMA = "fedtpu-alert-v1"

#: The classic SRE-workbook page pace: 2% of a 30-day budget in 1 hour.
PAGE_BURN_FACTOR = 14.4


@dataclass(frozen=True)
class SLO:
    """One service-level objective over an exported metric family.

    ``kind="latency"``: ``metric`` names a histogram family; an event is
    good when its observation is <= ``le`` seconds (``le`` should sit on
    a bucket edge — the evaluator uses the largest edge <= ``le``).

    ``kind="ratio"``: ``metric`` names the BAD-event counter and
    ``total`` the denominator counter (e.g. stream fallbacks over
    uploads, ejects over forwards).

    ``windows`` is ``((window_s, burn_factor), ...)`` ordered however;
    the evaluator fires on ALL breaching and clears on the shortest.
    """

    name: str
    metric: str
    kind: str = "latency"
    le: float | None = None
    total: str | None = None
    objective: float = 0.99
    windows: tuple[tuple[float, float], ...] = (
        (3600.0, PAGE_BURN_FACTOR),
        (300.0, PAGE_BURN_FACTOR),
    )
    severity: str = "page"
    #: Optional label filter: only samples carrying every (k, v) count.
    labels: tuple[tuple[str, str], ...] = ()

    def __post_init__(self) -> None:
        if self.kind not in ("latency", "ratio"):
            raise ValueError(f"SLO kind={self.kind!r} must be latency|ratio")
        if self.kind == "latency" and self.le is None:
            raise ValueError(f"latency SLO {self.name!r} needs le=<bound>")
        if self.kind == "ratio" and not self.total:
            raise ValueError(f"ratio SLO {self.name!r} needs total=<family>")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective={self.objective} must be in (0, 1)"
            )
        if not self.windows:
            raise ValueError(f"SLO {self.name!r} needs at least one window")
        for w, f in self.windows:
            if w <= 0.0 or f <= 0.0:
                raise ValueError(
                    f"SLO {self.name!r} window ({w}, {f}) must be positive"
                )
        if self.severity not in ("page", "ticket"):
            raise ValueError(
                f"severity={self.severity!r} must be page|ticket"
            )

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    @property
    def shortest_window(self) -> tuple[float, float]:
        return min(self.windows, key=lambda wf: wf[0])


def default_slos() -> tuple[SLO, ...]:
    """The fleet's stock objectives over the families the daemons already
    export — the ``fedtpu obs health`` defaults when no --slo file names
    others. Windows are deliberately short (minutes, not the workbook's
    hours): loopback fleets and CI campaigns live on that timescale, and
    an operator file overrides them for real deployments."""
    return (
        # Scoring latency: 99% of requests wait <= 50 ms in the
        # micro-batcher queue (the serving tier's own histogram).
        SLO(
            name="scoring-queue-p99",
            metric="fedtpu_serve_queue_wait_seconds",
            kind="latency",
            le=0.05,
            objective=0.99,
            windows=((300.0, PAGE_BURN_FACTOR), (60.0, PAGE_BURN_FACTOR)),
        ),
        # Round cadence: 90% of aggregation rounds finish within a
        # minute (straggler rot shows up here first).
        SLO(
            name="round-duration",
            metric="fedtpu_server_round_seconds",
            kind="latency",
            le=60.0,
            objective=0.9,
            windows=((600.0, 6.0), (120.0, 6.0)),
        ),
        # Stream health: dense fallbacks while streaming is advertised
        # stay under 10% of uploads.
        SLO(
            name="stream-fallback-ratio",
            metric="fedtpu_server_stream_fallbacks_total",
            kind="ratio",
            total="fedtpu_server_uploads_total",
            objective=0.9,
            windows=((600.0, 6.0), (120.0, 6.0)),
            severity="ticket",
        ),
        # Replica fleet: ejects stay under one per thousand forwards.
        SLO(
            name="replica-eject-rate",
            metric="fedtpu_router_ejects_total",
            kind="ratio",
            total="fedtpu_router_forwarded_total",
            objective=0.999,
            windows=((600.0, PAGE_BURN_FACTOR), (60.0, PAGE_BURN_FACTOR)),
        ),
        # Canary end-to-end: 99% of sentinel canary probes score through
        # the full router->replica chain within 250 ms (obs/sentinel.py
        # feeds the histogram — the known-truth proof of the live path).
        SLO(
            name="canary-latency-p99",
            metric="fedtpu_canary_latency_seconds",
            kind="latency",
            le=0.25,
            objective=0.99,
            windows=((300.0, PAGE_BURN_FACTOR), (60.0, PAGE_BURN_FACTOR)),
        ),
    )


def slos_from_spec(spec: Iterable[Mapping[str, Any]]) -> tuple[SLO, ...]:
    """Operator SLO file (a JSON list of SLO-field objects) -> SLO
    tuple. Windows round-trip from JSON lists; unknown keys fail loudly
    (a typo'd field must not silently weaken an objective)."""
    out = []
    for d in spec:
        kw = dict(d)
        if "windows" in kw:
            kw["windows"] = tuple(
                (float(w), float(f)) for w, f in kw["windows"]
            )
        if "labels" in kw:
            kw["labels"] = tuple(
                (str(k), str(v)) for k, v in kw["labels"]
            )
        out.append(SLO(**kw))
    return tuple(out)


# --------------------------------------------------------- event extraction
def _labels_match(sample: Mapping, labels: tuple) -> bool:
    got = sample.get("labels") or {}
    return all(got.get(k) == v for k, v in labels)


def _hist_good_total(
    families: Mapping, metric: str, le: float, labels: tuple
) -> tuple[float, float] | None:
    fam = families.get(metric)
    if not fam or fam.get("type") != "histogram":
        return None
    good = total = 0.0
    seen = False
    for s in fam.get("samples", ()):
        if not _labels_match(s, labels):
            continue
        seen = True
        total += float(s.get("count", 0))
        best = 0.0
        for edge_str, cum in s.get("buckets", ()):
            try:
                edge = float(edge_str)
            except ValueError:  # garbage edge in a foreign snapshot
                continue
            # float("+Inf") parses fine; inf <= le is simply never
            # true, so the +Inf bucket (== count) can't claim "good".
            if edge <= le:
                best = float(cum)
        good += best
    return (good, total) if seen else None


def _counter_sum(
    families: Mapping, metric: str, labels: tuple
) -> float | None:
    fam = families.get(metric)
    if not fam:
        return None
    vals = [
        float(s.get("value", 0.0))
        for s in fam.get("samples", ())
        if _labels_match(s, labels)
    ]
    return sum(vals) if vals else None


def extract_bad_total(
    slo: SLO, families: Mapping
) -> tuple[float, float] | None:
    """Cumulative (bad_events, total_events) for one SLO out of one
    metrics snapshot's ``families`` dict, or None when the family is not
    exported (that tier isn't running here — not an error)."""
    if slo.kind == "latency":
        gt = _hist_good_total(families, slo.metric, slo.le, slo.labels)
        if gt is None:
            return None
        good, total = gt
        return max(total - good, 0.0), total
    bad = _counter_sum(families, slo.metric, slo.labels)
    total = _counter_sum(families, slo.total, slo.labels)
    if bad is None or total is None:
        return None
    return bad, total


# ----------------------------------------------------------- burn windows
class _BurnSeries:
    """Timestamped cumulative (bad, total) points for one (SLO, instance);
    answers "burn rate over the trailing W seconds" by the increase()
    delta between now and the last point at or before now - W."""

    def __init__(self, max_window_s: float):
        self.max_window_s = float(max_window_s)
        self.points: deque[tuple[float, float, float]] = deque()

    def add(self, now: float, bad: float, total: float) -> None:
        last = self.points[-1] if self.points else None
        if last is not None and (bad < last[1] or total < last[2]):
            # Counter reset (daemon restart): drop history — deltas
            # across a reset would go negative or phantom-burn.
            self.points.clear()
        self.points.append((float(now), float(bad), float(total)))
        horizon = now - self.max_window_s - 1.0
        while len(self.points) > 2 and self.points[1][0] <= horizon:
            self.points.popleft()

    def burn(self, now: float, window_s: float, budget: float) -> dict:
        """{"burn": rate, "bad": d_bad, "total": d_total} over the
        trailing window; no-traffic windows burn 0.0 by definition."""
        if not self.points:
            return {"burn": 0.0, "bad": 0.0, "total": 0.0}
        cutoff = now - window_s
        base = self.points[0]
        for p in self.points:
            if p[0] <= cutoff:
                base = p
            else:
                break
        head = self.points[-1]
        d_bad = max(head[1] - base[1], 0.0)
        d_total = max(head[2] - base[2], 0.0)
        if d_total <= 0.0:
            return {"burn": 0.0, "bad": 0.0, "total": 0.0}
        return {
            "burn": (d_bad / d_total) / budget,
            "bad": d_bad,
            "total": d_total,
        }


class AlertManager:
    """Fire/clear state machines for a set of SLOs across fleet
    instances, with a JSONL alert sink.

    ``ingest(families, now=..., instance=...)`` pushes one metrics
    snapshot; ``evaluate(now=...)`` advances every state machine and
    returns the fire/clear events of this pass (also appended to
    ``sink_path`` and handed to ``on_event``). Page-severity fires trip
    the installed flight recorder, so an SLO page leaves a postmortem
    bundle behind without any daemon-side wiring.

    ``alert_cmd`` is the notification fan-out (``--alert-cmd``): a user
    shell command spawned once per page-severity fire with the alert
    event JSON on stdin — the alerts-JSONL stops being the only
    consumer. Rate-limited to one spawn per ``alert_cmd_interval_s``
    on the EVENT clock (the same injectable timeline the burn windows
    ride, so tests drive it synthetically), and OSError-guarded: a
    broken pager never kills the poll loop.

    Thread-safe: the scrape hub's watch loop and a test driving
    synthetic snapshots both funnel through one lock.
    """

    def __init__(
        self,
        slos: Iterable[SLO] | None = None,
        *,
        sink_path: str | None = None,
        on_event: Callable[[dict], None] | None = None,
        recorder=None,
        alert_cmd: str | None = None,
        alert_cmd_interval_s: float = 30.0,
    ):
        self.slos = tuple(slos if slos is not None else default_slos())
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names in {names}")
        self.sink_path = sink_path
        self.on_event = on_event
        self._recorder = recorder
        self.alert_cmd = alert_cmd
        self.alert_cmd_interval_s = float(alert_cmd_interval_s)
        self._lock = threading.Lock()
        self._last_notify_ts: float | None = None
        # (slo.name, instance) -> {"series": _BurnSeries, "firing": bool,
        #                          "since": ts, "last_burn": {...}}
        self._state: dict[tuple[str, str], dict] = {}
        self.fired_total = 0
        self.cleared_total = 0
        self.notified_total = 0
        self.notify_suppressed_total = 0

    # ------------------------------------------------------------- ingest
    def ingest(
        self, families: Mapping, *, now: float, instance: str = "local"
    ) -> None:
        with self._lock:
            for slo in self.slos:
                bt = extract_bad_total(slo, families)
                if bt is None:
                    continue
                key = (slo.name, instance)
                st = self._state.get(key)
                if st is None:
                    st = {
                        "series": _BurnSeries(
                            max(w for w, _ in slo.windows)
                        ),
                        "firing": False,
                        "since": None,
                        "last_burn": {},
                    }
                    self._state[key] = st
                st["series"].add(now, *bt)

    # ----------------------------------------------------------- evaluate
    def evaluate(self, *, now: float) -> list[dict]:
        events: list[dict] = []
        with self._lock:
            by_name = {s.name: s for s in self.slos}
            for (name, instance), st in sorted(self._state.items()):
                slo = by_name[name]
                # One burn computation per window, reused by the breach
                # and clear decisions below; keyed by the EXACT window
                # ("%g" — int(w) would collapse 90.0 and 90.5 into one
                # reported key while the decisions still saw both).
                per_window = {
                    (w, f): st["series"].burn(now, w, slo.budget)
                    for w, f in slo.windows
                }
                burns = {
                    f"{w:g}s": b for (w, _f), b in per_window.items()
                }
                st["last_burn"] = burns
                breach_all = all(
                    b["burn"] >= f for (_w, f), b in per_window.items()
                )
                w_short, f_short = slo.shortest_window
                short_ok = (
                    per_window[(w_short, f_short)]["burn"] < f_short
                )
                if not st["firing"] and breach_all:
                    st["firing"] = True
                    st["since"] = now
                    self.fired_total += 1
                    events.append(
                        self._event("fire", slo, instance, now, burns)
                    )
                elif st["firing"] and short_ok:
                    st["firing"] = False
                    st["since"] = None
                    self.cleared_total += 1
                    events.append(
                        self._event("clear", slo, instance, now, burns)
                    )
        for ev in events:
            self._sink(ev)
        return events

    def _event(
        self, kind: str, slo: SLO, instance: str, now: float, burns: dict
    ) -> dict:
        return {
            "schema": ALERT_SCHEMA,
            "ts": float(now),
            "event": kind,
            "slo": slo.name,
            "instance": instance,
            "severity": slo.severity,
            "objective": slo.objective,
            "burn": {
                k: round(v["burn"], 4) for k, v in burns.items()
            },
            "bad": {k: v["bad"] for k, v in burns.items()},
        }

    def _sink(self, ev: dict) -> None:
        if self.sink_path:
            import json

            try:
                append_jsonl_line(self.sink_path, json.dumps(ev))
            except OSError:
                # A full disk must not crash the poll loop at the exact
                # moment the fleet went unhealthy; the event still
                # reaches on_event/recorder below and the in-memory
                # state machine stays correct.
                pass
        if self.on_event is not None:
            self.on_event(ev)
        self._notify(ev)
        rec = self._recorder
        if rec is None:
            from .flight import get_global_recorder

            rec = get_global_recorder()
        if rec is None:
            return
        # EVERY event reaches the ring (a bundle whose alert history
        # shows a fire with no matching clear misleads the postmortem
        # reader); only page-severity fires additionally dump. A dump
        # failure (full disk, unwritable dir) must not crash the poll
        # loop at the precise moment the fleet went unhealthy.
        rec.note_alert(ev)
        if ev["event"] == "fire" and ev["severity"] == "page":
            try:
                rec.maybe_dump("slo-page", extra=ev)
            except OSError:
                pass

    def _notify(self, ev: dict) -> None:
        """Spawn ``alert_cmd`` for one page-severity fire (event JSON on
        stdin, fire-and-forget). Rate limit rides the event's own ``ts``
        — the injectable clock every burn decision already uses."""
        if (
            self.alert_cmd is None
            or ev["event"] != "fire"
            or ev["severity"] != "page"
        ):
            return
        now = float(ev["ts"])
        with self._lock:
            last = self._last_notify_ts
            if last is not None and now - last < self.alert_cmd_interval_s:
                self.notify_suppressed_total += 1
                return
            self._last_notify_ts = now
        import json
        import subprocess

        try:
            proc = subprocess.Popen(
                self.alert_cmd,
                shell=True,
                stdin=subprocess.PIPE,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        except (OSError, ValueError):
            # A broken pager (missing shell, bad fd) must never kill
            # the poll loop at the moment the fleet paged. The rate-
            # limit slot stays claimed: a persistently broken command
            # retries once per interval, not once per event.
            return
        try:
            if proc.stdin is not None:
                proc.stdin.write((json.dumps(ev) + "\n").encode())
                proc.stdin.close()
        except OSError:
            # The command spawned but exited before reading stdin
            # (BrokenPipe) — that IS a delivered notification; a pager
            # is free to ignore its input.
            pass
        with self._lock:
            self.notified_total += 1

    # ------------------------------------------------------------- render
    def states(self) -> list[dict]:
        """Current per-(slo, instance) state for the health screen."""
        out = []
        with self._lock:
            by_name = {s.name: s for s in self.slos}
            for (name, instance), st in sorted(self._state.items()):
                slo = by_name[name]
                out.append(
                    {
                        "slo": name,
                        "instance": instance,
                        "severity": slo.severity,
                        "firing": st["firing"],
                        "since": st["since"],
                        "burn": {
                            k: round(v["burn"], 4)
                            for k, v in st["last_burn"].items()
                        },
                    }
                )
        return out
