"""In-process metrics registry + stdlib-HTTP ``/metrics`` endpoint.

Counters, gauges, and histograms that server, controller, and infer-serve
update on their hot paths (queue depth, bytes on wire, retries, per-phase
seconds, gate rejections) and expose in Prometheus text exposition format
over a lightweight ``http.server`` endpoint (``--metrics-port``, off by
default). Pure stdlib + a lock — no client library, no background
scrape-state, nothing on the hot path beyond an int/float update under a
lock.

Naming follows Prometheus conventions: ``*_total`` for counters,
``*_seconds``/``_bytes`` units in the name, labels for low-cardinality
partitions (reject kind, round phase). One process-wide
:func:`default_registry` mirrors the Prometheus client-library pattern so
the tiers need no plumbing to share an endpoint; tests build private
:class:`MetricsRegistry` instances.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable, Mapping

_INF = float("inf")

#: Schema tag on every ``/metrics.json`` body, so the scrape hub can
#: reject (or version-switch on) foreign JSON documents.
SNAPSHOT_SCHEMA = "fedtpu-metrics-v1"


def _parse_label_str(label_str: str) -> dict[str, str]:
    """Invert :func:`_label_str` for snapshot(): the registry memoizes
    children on the rendered label string, so the machine-readable twin
    recovers the mapping from it (values never contain quotes here — the
    registry's own call sites use plain identifiers)."""
    if not label_str:
        return {}
    out: dict[str, str] = {}
    for part in label_str[1:-1].split(","):
        k, _, v = part.partition("=")
        out[k] = v.strip('"')
    return out


def _fmt(v: float) -> str:
    """Prometheus float formatting: integers without the trailing .0."""
    f = float(v)
    if f == _INF:
        return "+Inf"
    if f.is_integer():
        return str(int(f))
    return repr(f)


def _label_str(labels: Mapping[str, str] | None) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing value (`*_total`)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment {amount} must be >= 0")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Instantaneous value (queue depth, serving round, ...)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram (cumulative ``le`` buckets + sum + count)."""

    DEFAULT_BUCKETS = (
        0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0,
    )

    def __init__(self, buckets: Iterable[float] | None = None) -> None:
        edges = tuple(sorted(buckets or self.DEFAULT_BUCKETS))
        if not edges:
            raise ValueError("histogram needs at least one bucket edge")
        self._edges = edges
        self._lock = threading.Lock()
        self._counts = [0] * (len(edges) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._n = 0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._sum += v
            self._n += 1
            for i, edge in enumerate(self._edges):
                if v <= edge:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> tuple[tuple[float, ...], list[int], float, int]:
        with self._lock:
            return self._edges, list(self._counts), self._sum, self._n


class MetricsRegistry:
    """Name -> metric family store with Prometheus text rendering.

    ``counter``/``gauge``/``histogram`` are get-or-create (memoized on
    (name, labels)), so hot paths hold direct metric references and
    re-registration from a second server instance in one process simply
    shares the family — standard client-library semantics."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> {"type": ..., "help": ..., "children": {label_str: metric}}
        self._families: dict[str, dict] = {}

    def _get(
        self,
        name: str,
        kind: str,
        help: str,
        labels: Mapping[str, str] | None,
        factory,
    ):
        key = _label_str(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = {"type": kind, "help": help, "children": {}}
                self._families[name] = fam
            elif fam["type"] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam['type']}"
                )
            child = fam["children"].get(key)
            if child is None:
                child = factory()
                fam["children"][key] = child
            return child

    def counter(
        self,
        name: str,
        *,
        help: str = "",
        labels: Mapping[str, str] | None = None,
    ) -> Counter:
        return self._get(name, "counter", help, labels, Counter)

    def gauge(
        self,
        name: str,
        *,
        help: str = "",
        labels: Mapping[str, str] | None = None,
    ) -> Gauge:
        return self._get(name, "gauge", help, labels, Gauge)

    def histogram(
        self,
        name: str,
        *,
        help: str = "",
        labels: Mapping[str, str] | None = None,
        buckets: Iterable[float] | None = None,
    ) -> Histogram:
        return self._get(
            name, "histogram", help, labels, lambda: Histogram(buckets)
        )

    # ------------------------------------------------------------- rendering
    def snapshot(self) -> dict:
        """Machine-readable registry state (the ``/metrics.json`` body and
        the scrape hub's input): one JSON-able dict, no text-format parser
        needed on the consuming side. Histogram buckets are CUMULATIVE
        ``[edge_str, count]`` pairs ending at ``"+Inf"`` — the same
        numbers the Prometheus rendering exposes, so the two endpoints
        can never disagree."""
        with self._lock:
            families = {
                name: (
                    fam["type"],
                    fam["help"],
                    dict(fam["children"]),
                )
                for name, fam in sorted(self._families.items())
            }
        out: dict[str, dict] = {}
        for name, (kind, help_text, children) in families.items():
            samples: list[dict] = []
            for label_str, metric in sorted(children.items()):
                labels = _parse_label_str(label_str)
                if kind == "histogram":
                    edges, counts, total, n = metric.snapshot()
                    cum = 0
                    buckets: list[list] = []
                    for edge, c in zip(edges + (_INF,), counts):
                        cum += c
                        buckets.append([_fmt(edge), cum])
                    samples.append(
                        {
                            "labels": labels,
                            "buckets": buckets,
                            "sum": total,
                            "count": n,
                        }
                    )
                else:
                    samples.append(
                        {"labels": labels, "value": metric.value}
                    )
            out[name] = {"type": kind, "help": help_text, "samples": samples}
        return {"schema": SNAPSHOT_SCHEMA, "families": out}

    def render_json(self) -> str:
        import json

        return json.dumps(self.snapshot())

    def render(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: list[str] = []
        with self._lock:
            families = {
                name: (
                    fam["type"],
                    fam["help"],
                    dict(fam["children"]),
                )
                for name, fam in sorted(self._families.items())
            }
        for name, (kind, help_text, children) in families.items():
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for label_str, metric in sorted(children.items()):
                if kind == "histogram":
                    edges, counts, total, n = metric.snapshot()
                    base = label_str[1:-1] if label_str else ""
                    cum = 0
                    for edge, c in zip(edges + (_INF,), counts):
                        cum += c
                        le = f'le="{_fmt(edge)}"'
                        inner = f"{base},{le}" if base else le
                        lines.append(
                            f"{name}_bucket{{{inner}}} {cum}"
                        )
                    lines.append(f"{name}_sum{label_str} {_fmt(total)}")
                    lines.append(f"{name}_count{label_str} {n}")
                else:
                    lines.append(
                        f"{name}{label_str} {_fmt(metric.value)}"
                    )
        return "\n".join(lines) + "\n"


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry the tiers record into (the Prometheus
    client-library pattern: no plumbing needed to share one endpoint)."""
    return _DEFAULT


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry  # set per server class below

    def do_GET(self) -> None:  # noqa: N802 (stdlib API name)
        path = self.path.split("?", 1)[0]
        if path == "/metrics.json":
            # The machine-readable twin (obs/fleet.py scrape hub, tests):
            # same numbers as the text rendering, no exposition-format
            # parser needed on the consuming side.
            body = self.registry.render_json().encode()
            ctype = "application/json"
        elif path in ("/metrics", "/"):
            body = self.registry.render().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args) -> None:  # scrapes stay off stdout
        pass


class MetricsServer:
    """``/metrics`` over stdlib ``ThreadingHTTPServer`` on its own daemon
    thread. ``port=0`` binds an ephemeral port (tests); the CLI flag's
    0-means-off convention lives at the call sites, not here."""

    def __init__(
        self,
        port: int,
        *,
        host: str = "0.0.0.0",
        registry: MetricsRegistry | None = None,
    ):
        reg = registry or default_registry()
        handler = type("BoundHandler", (_Handler,), {"registry": reg})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="fedtpu-metrics",
            daemon=True,
        )

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def maybe_start_metrics_server(
    port: int | None, *, host: str = "127.0.0.1"
) -> MetricsServer | None:
    """CLI-facing helper: 0/None = off (the default), else bind + start
    on the default registry. The endpoint is unauthenticated, so the
    default bind is LOOPBACK — call sites that serve a network-facing
    tier pass that tier's explicit --host so the operator's bind choice
    covers the metrics port too, never wider."""
    if not port:
        return None
    return MetricsServer(int(port), host=host).start()
