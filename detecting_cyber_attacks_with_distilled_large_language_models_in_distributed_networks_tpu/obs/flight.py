"""Failure flight recorder: postmortem bundles from an in-memory ring.

When a round fails at 3 a.m., the spans that explain it are scattered
across per-process JSONLs (if tracing was even on) and the /metrics
counters have already moved past the moment. This module keeps the last
N spans + the most recent metric snapshots + the last alerts in a
bounded in-memory ring inside EVERY daemon, and on a trigger — round
failure (comm/server.py), replica eject storm (router/core.py), or an
SLO page (obs/slo.py) — dumps one self-contained postmortem bundle to
disk: ring + current /metrics snapshot + config + trigger context.
``fedtpu obs postmortem`` lists and inspects the bundles.

Design constraints:

* **Zero hot-path cost when off.** Nothing records unless a
  :class:`FlightRecorder` is installed (``set_global_recorder`` — the
  CLI does it from ``--flight-dir`` / ObsConfig.flight_dir). When on,
  a span costs one deque append under a lock.
* **No daemon-side capture wiring.** obs/trace.py feeds every span a
  Tracer writes into the installed recorder, so any process that
  already traces records flight data for free; metric state is pulled
  from the process default registry at dump time (plus whatever
  periodic snapshots the owner pushed via :meth:`note_metrics`).
* **Storm-safe.** ``maybe_dump`` rate-limits per reason
  (``min_interval_s``) and the directory is bounded (``max_bundles``,
  oldest pruned) — an eject storm writes one bundle, not hundreds.
* **Atomic bundles.** Each bundle is one JSON file written to a temp
  name and renamed, so ``fedtpu obs postmortem`` never reads a torn
  half-dump.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from collections import deque
from typing import Any

#: Schema tag inside every bundle file.
BUNDLE_SCHEMA = "fedtpu-postmortem-v1"

#: Bundle filename shape: postmortem-<proc>-<seq>-<reason>.json
_BUNDLE_GLOB = "postmortem-*.json"


class FlightRecorder:
    """Bounded ring of recent observability state for ONE process."""

    def __init__(
        self,
        out_dir: str,
        *,
        proc: str,
        ring: int = 256,
        snapshots: int = 8,
        alerts: int = 32,
        max_bundles: int = 16,
        min_interval_s: float = 30.0,
        config: dict | None = None,
        tracer=None,
    ):
        if ring < 1:
            raise ValueError(f"ring={ring} must be >= 1")
        if max_bundles < 1:
            raise ValueError(f"max_bundles={max_bundles} must be >= 1")
        self.out_dir = out_dir
        self.proc = str(proc)
        self.min_interval_s = float(min_interval_s)
        self.max_bundles = int(max_bundles)
        self.config = dict(config or {})
        #: Optional span writer: a dump also emits a ``postmortem-dump``
        #: span so the timeline shows WHEN the recorder fired relative
        #: to the round that tripped it.
        self.tracer = tracer
        self._lock = threading.Lock()
        self._spans: deque[dict] = deque(maxlen=int(ring))
        self._snapshots: deque[dict] = deque(maxlen=int(snapshots))
        self._alerts: deque[dict] = deque(maxlen=int(alerts))
        self._last_dump: dict[str, float] = {}
        # Seed the sequence past any bundles a PREVIOUS run of this
        # proc left behind: a daemon restart (exactly what follows a
        # failure) starting back at 1 would silently os.replace() the
        # prior run's evidence — the one thing the recorder exists to
        # preserve.
        self._seq = self._existing_max_seq()
        self.bundles_written = 0

    def _my_bundles(self) -> list[tuple[int, str]]:
        """(seq, path) for THIS proc's bundles on disk. The filename is
        ``postmortem-<proc>-<seq>-<reason>.json`` with seq always
        ``%04d``-formatted; requiring a >=4-digit segment right after
        the exact proc prefix keeps a proc whose name is a dash-prefix
        of another's ("relay-1" vs "relay-12") from claiming — or
        later pruning — the sibling's files in a shared directory."""
        prefix = f"postmortem-{self.proc}-"
        out: list[tuple[int, str]] = []
        for path in glob.glob(os.path.join(self.out_dir, prefix + "*.json")):
            seq_part = os.path.basename(path)[len(prefix):].split("-", 1)[0]
            if len(seq_part) >= 4 and seq_part.isdigit():
                out.append((int(seq_part), path))
        return out

    def _existing_max_seq(self) -> int:
        return max((seq for seq, _ in self._my_bundles()), default=0)

    # ------------------------------------------------------------- capture
    def note_span(self, rec: dict) -> None:
        """Called by obs/trace.py for every span the process writes."""
        with self._lock:
            self._spans.append(rec)

    def note_metrics(self, snapshot: dict, *, now: float) -> None:
        """Optional periodic metric snapshots (the scrape hub pushes its
        own polls; daemons rely on the dump-time pull instead)."""
        with self._lock:
            self._snapshots.append({"ts": float(now), **snapshot})

    def note_alert(self, event: dict) -> None:
        with self._lock:
            self._alerts.append(event)

    # --------------------------------------------------------------- dump
    def maybe_dump(self, reason: str, *, extra: dict | None = None) -> str | None:
        """Rate-limited :meth:`dump`: at most one bundle per ``reason``
        per ``min_interval_s`` — the storm guard. Returns the bundle
        path or None when suppressed. The limiter stamps only AFTER a
        successful write: a transient dump failure (ENOSPC — the
        callers catch OSError and log) must not suppress the retry
        that would have preserved the evidence."""
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if last is not None and now - last < self.min_interval_s:
                return None
            # Provisional claim inside the SAME lock section as the
            # check: two near-simultaneous triggers (racing router
            # reader threads) must produce one bundle, not two.
            self._last_dump[reason] = now
        try:
            path = self.dump(reason, extra=extra)
        except BaseException:
            with self._lock:
                # Roll the claim back so a transient failure (ENOSPC)
                # doesn't suppress the retry that would have preserved
                # the evidence.
                if self._last_dump.get(reason) == now:
                    del self._last_dump[reason]
            raise
        with self._lock:
            self._last_dump[reason] = time.monotonic()
        return path

    def dump(self, reason: str, *, extra: dict | None = None) -> str:
        """Write one postmortem bundle NOW (no rate limit): the span
        ring, retained metric snapshots, a fresh pull of the process
        default registry, the last alerts, and the trigger context."""
        from .metrics import default_registry

        t_unix = time.time()
        t0 = time.monotonic()
        with self._lock:
            spans = list(self._spans)
            snapshots = list(self._snapshots)
            alerts = list(self._alerts)
            self._seq += 1
            seq = self._seq
        try:
            current_metrics = default_registry().snapshot()
        except Exception:  # a torn registry must not lose the spans
            current_metrics = None
        bundle = {
            "schema": BUNDLE_SCHEMA,
            "ts": t_unix,
            "proc": self.proc,
            "reason": str(reason),
            "seq": seq,
            "config": self.config,
            "extra": extra or {},
            "alerts": alerts,
            "metric_snapshots": snapshots,
            "metrics_now": current_metrics,
            "spans": spans,
        }
        os.makedirs(self.out_dir, exist_ok=True)
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in str(reason)
        )
        name = f"postmortem-{self.proc}-{seq:04d}-{safe_reason}.json"
        path = os.path.join(self.out_dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f)
        os.replace(tmp, path)
        with self._lock:
            self.bundles_written += 1
        self._prune()
        if self.tracer is not None:
            self.tracer.record(
                "postmortem-dump",
                t_start=t_unix,
                dur_s=time.monotonic() - t0,
                reason=str(reason),
                bundle=name,
                spans=len(spans),
            )
        return path

    def _prune(self) -> None:
        """Oldest-first prune beyond ``max_bundles`` (mtime order; this
        process's bundles ONLY — :meth:`_my_bundles` — because fleets
        may share one directory: a sibling's evidence must never be
        counted against this proc's budget or deleted, and a sibling
        removing files between glob and stat must not raise)."""

        def _mtime(p: str) -> float:
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0

        mine = sorted(
            (p for _seq, p in self._my_bundles()),
            key=lambda p: (_mtime(p), p),
        )
        for path in mine[: max(0, len(mine) - self.max_bundles)]:
            try:
                os.remove(path)
            except OSError:
                pass


# ------------------------------------------------------- global install
_GLOBAL_LOCK = threading.Lock()
_GLOBAL: FlightRecorder | None = None


def set_global_recorder(rec: FlightRecorder | None) -> None:
    """Install the process flight recorder (the CLI does this once at
    startup from --flight-dir / ObsConfig.flight_dir; None disarms —
    required between in-process CLI invocations in tests)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = rec


def get_global_recorder() -> FlightRecorder | None:
    with _GLOBAL_LOCK:
        return _GLOBAL


# ----------------------------------------------------------- inspection
def list_bundles(out_dir: str) -> list[dict]:
    """Bundle summaries (path, proc, reason, ts, span/alert counts) for
    ``fedtpu obs postmortem``, newest first. Torn or foreign files are
    skipped, not fatal."""
    out: list[dict] = []
    for path in glob.glob(os.path.join(out_dir, _BUNDLE_GLOB)):
        b = load_bundle(path)
        if b is None:
            continue
        out.append(
            {
                "path": path,
                "name": os.path.basename(path),
                "ts": b.get("ts"),
                "proc": b.get("proc"),
                "reason": b.get("reason"),
                "spans": len(b.get("spans") or ()),
                "alerts": len(b.get("alerts") or ()),
            }
        )
    out.sort(key=lambda r: (r["ts"] or 0.0), reverse=True)
    return out


def load_bundle(path: str) -> dict | None:
    try:
        with open(path) as f:
            b = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(b, dict) or b.get("schema") != BUNDLE_SCHEMA:
        return None
    return b
