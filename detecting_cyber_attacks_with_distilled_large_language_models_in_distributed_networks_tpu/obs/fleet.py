"""Fleet scrape hub: one process that watches every daemon's health.

fedtpu grew into a six-daemon fleet (serve/relay/controller/infer-serve/
route/fleet) where each process exports its own ``/metrics`` and span
JSONL but nothing merges them — the operator's view of "is the fleet
healthy" was N browser tabs. The hub is that missing process:

* **Scrape.** :class:`ScrapeHub` polls every target's ``/metrics.json``
  (the machine-readable twin obs/metrics.py serves next to the
  Prometheus text format) and incrementally tails its events-JSONL
  (byte-offset resume, complete lines only — the DriftMonitor tail
  pattern). A scrape failure marks the target down; it never raises.
* **Merge.** Each poll appends ONE fleet snapshot record to a JSONL
  keyed by (tier, instance): per-target up/down, scrape lag, a compact
  counter/gauge summary, round cadence (rounds_total deltas between
  polls), and the SLO burn states — the file a dashboard or a later
  ``fedtpu obs`` analysis reads back.
* **Judge.** Every poll feeds the snapshots into an
  :class:`~.slo.AlertManager`; burn-rate fires/clears land on the
  alerts-JSONL and page-severity fires trip the flight recorder.
* **Render.** :meth:`ScrapeHub.render_status` is the one-screen fleet
  view behind ``fedtpu obs health`` / ``watch``: per-tier state, SLO
  burn, round cadence, replica in-flight/ejects, controller drift
  state, recent postmortems.

The hub is deliberately a READER of the fleet — it holds no locks any
daemon shares, and a hub crash costs dashboards, never rounds.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from dataclasses import dataclass
from typing import Iterable, Mapping

from . import metrics as obs_metrics
from .slo import SLO, AlertManager
from .timeline import read_new_jsonl_lines
from .trace import SCHEMA as TRACE_SCHEMA
from .trace import append_jsonl_line

#: Schema tag on every fleet snapshot record.
FLEET_SCHEMA = "fedtpu-fleet-v1"

#: Schema tag on the ``obs health --json`` machine-readable verdict.
HEALTH_SCHEMA = "fedtpu-health-v1"

#: The daemon tiers the hub knows how to summarize (anything else still
#: scrapes — it just renders the generic counter summary).
KNOWN_TIERS = (
    "serve", "relay", "controller", "infer-serve", "route", "fleet",
)

#: Counter families whose per-poll delta is worth keeping in the
#: snapshot summary (the health screen's cadence/ratio columns).
_SUMMARY_COUNTERS = (
    "fedtpu_server_rounds_total",
    "fedtpu_server_round_failures_total",
    "fedtpu_server_uploads_total",
    "fedtpu_server_stream_fallbacks_total",
    "fedtpu_controller_rounds_total",
    "fedtpu_controller_promotions_total",
    "fedtpu_controller_gate_rejections_total",
    "fedtpu_controller_drift_triggers_total",
    "fedtpu_serve_scored_total",
    "fedtpu_serve_rejects_total",
    "fedtpu_router_forwarded_total",
    "fedtpu_router_ejects_total",
    "fedtpu_router_rejects_total",
)

_SUMMARY_GAUGES = (
    "fedtpu_serve_queue_depth",
    "fedtpu_serve_model_round",
    "fedtpu_server_stream_inflight",
    "fedtpu_router_inflight",
)


@dataclass(frozen=True)
class Target:
    """One scrape target: a daemon's tier + its /metrics.json address,
    plus (optionally) its events-JSONL path for span-level state."""

    tier: str
    host: str
    port: int
    events_jsonl: str | None = None

    @property
    def instance(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def key(self) -> str:
        return f"{self.tier}/{self.instance}"

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics.json"


def parse_target(spec: str) -> Target:
    """``TIER=HOST:PORT[,events=PATH]`` -> :class:`Target` (the --target
    flag's shape). The tier names a lane on the health screen; unknown
    tiers scrape fine but get the generic rendering."""
    head, _, rest = spec.partition(",")
    tier, sep, addr = head.partition("=")
    if not sep or ":" not in addr:
        raise ValueError(
            f"--target {spec!r}: expected TIER=HOST:PORT[,events=PATH]"
        )
    host, _, port_s = addr.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(f"--target {spec!r}: bad port {port_s!r}") from None
    events = None
    if rest:
        k, _, v = rest.partition("=")
        if k != "events" or not v:
            raise ValueError(
                f"--target {spec!r}: unknown option {rest!r} "
                "(only events=PATH)"
            )
        events = v
    return Target(tier=tier.strip(), host=host, port=port, events_jsonl=events)


def summarize_families(families: Mapping) -> dict:
    """Compact per-target summary out of a /metrics.json body: total
    value per known counter family, per-label values for the known
    gauges (replica in-flight wants the per-replica split)."""
    counters: dict[str, float] = {}
    gauges: dict[str, dict[str, float]] = {}
    for name in _SUMMARY_COUNTERS:
        fam = families.get(name)
        if fam:
            counters[name] = sum(
                float(s.get("value", 0.0)) for s in fam.get("samples", ())
            )
    for name in _SUMMARY_GAUGES:
        fam = families.get(name)
        if fam:
            gauges[name] = {
                ",".join(
                    f"{k}={v}" for k, v in sorted(
                        (s.get("labels") or {}).items()
                    )
                ): float(s.get("value", 0.0))
                for s in fam.get("samples", ())
            }
    return {"counters": counters, "gauges": gauges}


class ScrapeHub:
    """Poll -> merge -> judge -> render, one instance per operator
    console (or per cron tick). All clocks are injectable for tests:
    ``poll(now=...)`` threads one timestamp through scrape records,
    burn windows, and the snapshot JSONL."""

    def __init__(
        self,
        targets: Iterable[Target],
        *,
        slos: Iterable[SLO] | None = None,
        alerts_jsonl: str | None = None,
        snapshot_jsonl: str | None = None,
        snapshot_max_mb: float | None = None,
        scrape_timeout_s: float = 2.0,
        tracer=None,
        recorder=None,
        alert_cmd: str | None = None,
        alert_cmd_interval_s: float = 30.0,
    ):
        self.targets = list(targets)
        if not self.targets:
            raise ValueError("scrape hub needs at least one target")
        keys = [t.key for t in self.targets]
        if len(set(keys)) != len(keys):
            raise ValueError(f"duplicate scrape targets: {keys}")
        self.snapshot_jsonl = snapshot_jsonl
        if snapshot_max_mb is not None and float(snapshot_max_mb) <= 0:
            raise ValueError(
                f"snapshot_max_mb={snapshot_max_mb} must be > 0"
            )
        self.snapshot_max_mb = (
            float(snapshot_max_mb) if snapshot_max_mb is not None else None
        )
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.tracer = tracer
        self.alerts = AlertManager(
            slos,
            sink_path=alerts_jsonl,
            recorder=recorder,
            alert_cmd=alert_cmd,
            alert_cmd_interval_s=alert_cmd_interval_s,
        )
        self._lock = threading.Lock()
        # target.key -> scrape state: last summary, cadence base, events
        # tail offset, recent notable spans.
        self._state: dict[str, dict] = {
            t.key: {
                "up": False,
                "summary": None,
                "prev": None,  # (now, counters) for cadence deltas
                "cadence": {},
                "events_offset": 0,
                "last_drift": None,
                "postmortems": 0,
                "last_postmortem": None,
                "last_round_failed": False,
                "scrape_lag_ms": None,
                "error": None,
            }
            for t in self.targets
        }
        self.polls = 0
        self.last_scrape_lag_ms: float | None = None
        # The hub's own exported health (it may itself be scraped).
        m = obs_metrics.default_registry()
        self._m_polls = m.counter(
            "fedtpu_obs_polls_total",
            help="fleet scrape-hub poll passes",
        )
        self._m_scrape_errors = m.counter(
            "fedtpu_obs_scrape_errors_total",
            help="failed target scrapes (marked down, never fatal)",
        )
        self._g_scrape_lag = m.gauge(
            "fedtpu_obs_scrape_lag_ms",
            help="worst per-target scrape latency of the last poll",
        )
        self._g_targets_up = m.gauge(
            "fedtpu_obs_targets_up",
            help="targets answering /metrics.json on the last poll",
        )

    # --------------------------------------------------------------- scrape
    def _scrape(self, target: Target) -> tuple[dict | None, float, str | None]:
        """(families | None, lag_ms, error)."""
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(
                target.url, timeout=self.scrape_timeout_s
            ) as resp:
                doc = json.loads(resp.read())
        except Exception as e:  # connection refused, timeout, bad JSON
            return None, (time.monotonic() - t0) * 1e3, f"{type(e).__name__}: {e}"
        lag_ms = (time.monotonic() - t0) * 1e3
        if (
            not isinstance(doc, dict)
            or doc.get("schema") != obs_metrics.SNAPSHOT_SCHEMA
        ):
            return None, lag_ms, "foreign document (not a fedtpu metrics snapshot)"
        return doc.get("families") or {}, lag_ms, None

    def _tail_events(self, target: Target, st: dict) -> None:
        """Incremental events-JSONL tail (read_new_jsonl_lines): keep
        the spans that matter to the health screen — drift verdicts,
        postmortem dumps, failed rounds."""
        path = target.events_jsonl
        if not path:
            return
        st["events_offset"], lines = read_new_jsonl_lines(
            path, st["events_offset"]
        )
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or rec.get("schema") != TRACE_SCHEMA:
                continue
            span = rec.get("span")
            if span == "drift-trigger":
                st["last_drift"] = {
                    k: rec.get(k)
                    for k in ("ts", "drift", "method", "top_bins")
                }
            elif span == "postmortem-dump":
                st["postmortems"] += 1
                st["last_postmortem"] = {
                    "ts": rec.get("ts"),
                    "reason": rec.get("reason"),
                    "bundle": rec.get("bundle"),
                }
            elif span == "round":
                st["last_round_failed"] = bool(rec.get("failed"))

    # ----------------------------------------------------------------- poll
    def poll(self, *, now: float | None = None) -> dict:
        """One scrape pass over every target: updates burn state, fires/
        clears alerts, appends the fleet snapshot record, and returns
        it. ``now`` is injectable so burn-window tests never sleep."""
        t_unix = time.time()
        if now is None:
            now = t_unix
        events: list[dict]
        rows: list[dict] = []
        worst_lag: float | None = None
        n_up = 0
        # Scrape every target CONCURRENTLY: each down/slow daemon costs
        # up to scrape_timeout_s, and paying that serially would stall
        # the whole screen by N*timeout exactly during the incident the
        # health view exists for (and skew the burn-window timestamps
        # of the targets scraped last). The hub is a pure reader —
        # nothing shared is touched until the locked section below.
        scraped: dict[str, tuple] = {}

        def _scrape_into(t: Target) -> None:
            scraped[t.key] = self._scrape(t)

        if len(self.targets) == 1:
            _scrape_into(self.targets[0])
        else:
            threads = [
                threading.Thread(
                    target=_scrape_into, args=(t,), daemon=True
                )
                for t in self.targets
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=self.scrape_timeout_s + 2.0)
        for target in self.targets:
            families, lag_ms, err = scraped.get(
                target.key,
                (None, self.scrape_timeout_s * 1e3, "scrape timed out"),
            )
            with self._lock:
                st = self._state[target.key]
                st["scrape_lag_ms"] = round(lag_ms, 3)
                st["error"] = err
                st["up"] = families is not None
                if families is not None:
                    n_up += 1
                    summary = summarize_families(families)
                    st["summary"] = summary
                    prev = st["prev"]
                    cadence: dict[str, float] = {}
                    if prev is not None and now > prev[0]:
                        dt = now - prev[0]
                        for name, v in summary["counters"].items():
                            base = prev[1].get(name)
                            if base is not None and v >= base:
                                cadence[name] = (v - base) / dt
                    st["cadence"] = cadence
                    st["prev"] = (now, dict(summary["counters"]))
                else:
                    self._m_scrape_errors.inc()
                self._tail_events(target, st)
                row = self._row(target, st)
            rows.append(row)
            if families is not None:
                self.alerts.ingest(
                    families, now=now, instance=target.key
                )
            worst_lag = lag_ms if worst_lag is None else max(worst_lag, lag_ms)
        events = self.alerts.evaluate(now=now)
        with self._lock:
            self.polls += 1
            self.last_scrape_lag_ms = (
                round(worst_lag, 3) if worst_lag is not None else None
            )
        self._m_polls.inc()
        self._g_targets_up.set(float(n_up))
        if worst_lag is not None:
            self._g_scrape_lag.set(round(worst_lag, 3))
        snapshot = {
            "schema": FLEET_SCHEMA,
            "ts": t_unix,
            "targets": rows,
            "slo": self.alerts.states(),
            "events": events,
            "scrape_lag_ms": self.last_scrape_lag_ms,
        }
        if self.snapshot_jsonl:
            try:
                self._write_snapshot(json.dumps(snapshot))
            except OSError:
                pass  # a full disk costs the record, never the poll loop
        if self.tracer is not None:
            self.tracer.record(
                "slo-eval",
                t_start=t_unix,
                dur_s=(worst_lag or 0.0) / 1e3,
                targets=len(self.targets),
                up=n_up,
                firing=sum(1 for s in snapshot["slo"] if s["firing"]),
                scrape_lag_ms=self.last_scrape_lag_ms,
            )
        return snapshot

    def _write_snapshot(self, line: str) -> None:
        """Append one snapshot record, with bounded retention when
        ``snapshot_max_mb`` is set: once the live file crosses the cap
        it is atomically rolled to ``<path>.1`` (os.replace — a reader
        sees the old file or the new, never a torn middle) and the
        write starts a fresh generation, so an unattended ``--watch``
        holds at most ~2x the cap on disk. The capped path deliberately
        avoids append_jsonl_line's shared long-lived fd: a cached fd
        would pin the rotated inode and keep growing it invisibly."""
        if self.snapshot_max_mb is None:
            append_jsonl_line(self.snapshot_jsonl, line)
            return
        path = self.snapshot_jsonl
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        try:
            if os.path.getsize(path) >= self.snapshot_max_mb * 1024 * 1024:
                os.replace(path, path + ".1")
        except OSError:
            pass  # no file yet — the append below creates it
        with open(path, "a") as f:
            f.write(line + "\n")

    @staticmethod
    def _row(target: Target, st: dict) -> dict:
        """The per-target snapshot row — the ONE shape both poll()'s
        fleet-JSONL record and render_status(None) emit (two hand-built
        copies had already drifted once). Caller holds ``_lock``."""
        return {
            "tier": target.tier,
            "instance": target.instance,
            "up": st["up"],
            "scrape_lag_ms": st["scrape_lag_ms"],
            "summary": st["summary"],
            "cadence": {k: round(v, 4) for k, v in st["cadence"].items()},
            "last_drift": st["last_drift"],
            "postmortems": st["postmortems"],
            "last_round_failed": st["last_round_failed"],
            "error": st["error"],
        }

    # --------------------------------------------------------------- render
    def render_status(self, snapshot: dict | None = None) -> str:
        """The one-screen fleet view (``fedtpu obs health``). Pass the
        snapshot :meth:`poll` just returned, or None to render the last
        known state without scraping."""
        if snapshot is None:
            with self._lock:
                rows = [
                    self._row(t, self._state[t.key]) for t in self.targets
                ]
            states = self.alerts.states()
        else:
            rows = snapshot["targets"]
            states = snapshot["slo"]
        out: list[str] = []
        n_up = sum(1 for r in rows if r["up"])
        out.append(
            f"fedtpu fleet health  {time.strftime('%H:%M:%S')}  "
            f"({n_up}/{len(rows)} targets up, "
            f"{sum(1 for s in states if s['firing'])} alert(s) firing)"
        )
        out.append("")
        out.append(
            f"  {'tier':<12} {'instance':<22} {'up':<5} "
            f"{'lag':>7}  state"
        )
        for r in rows:
            lag = (
                f"{r['scrape_lag_ms']:.0f}ms"
                if r.get("scrape_lag_ms") is not None
                else "-"
            )
            out.append(
                f"  {r['tier']:<12} {r['instance']:<22} "
                f"{'ok' if r['up'] else 'DOWN':<5} {lag:>7}  "
                f"{self._state_line(r)}"
            )
        firing = [s for s in states if s["firing"]]
        out.append("")
        out.append("  SLO burn:")
        if not states:
            out.append("    (no SLO has seen data yet)")
        for s in states:
            burn = ", ".join(
                f"{w} {v:.1f}" for w, v in sorted(s["burn"].items())
            )
            flag = "FIRING" if s["firing"] else "ok"
            out.append(
                f"    {s['slo']:<24} {s['instance']:<30} {flag:<7} {burn}"
            )
        if firing:
            out.append("")
            out.append(
                f"  {len(firing)} alert(s) FIRING: "
                + ", ".join(f"{s['slo']}@{s['instance']}" for s in firing)
            )
        notable: list[str] = []
        for r in rows:
            if r.get("last_drift"):
                d = r["last_drift"]
                notable.append(
                    f"drift {d.get('method')}={d.get('drift')} on "
                    f"{r['tier']}/{r['instance']} top_bins="
                    f"{d.get('top_bins')}"
                )
            if r.get("postmortems"):
                notable.append(
                    f"{r['postmortems']} postmortem bundle(s) from "
                    f"{r['tier']}/{r['instance']}"
                )
        if notable:
            out.append("")
            out.append("  recent: " + "; ".join(notable))
        return "\n".join(out) + "\n"

    @staticmethod
    def _state_line(row: dict) -> str:
        """Per-tier key state out of the counter/gauge summary."""
        if not row["up"]:
            return row.get("error") or "unreachable"
        summary = row.get("summary") or {}
        c = summary.get("counters", {})
        g = summary.get("gauges", {})
        cadence = row.get("cadence", {})
        bits: list[str] = []
        if row.get("last_round_failed"):
            bits.append("LAST ROUND FAILED")

        def _count(name: str, label: str) -> None:
            if name in c:
                bits.append(f"{label} {c[name]:.0f}")

        rounds_rate = cadence.get(
            "fedtpu_server_rounds_total"
        ) or cadence.get("fedtpu_controller_rounds_total")
        if rounds_rate is not None:
            bits.append(f"{rounds_rate * 60.0:.1f} rounds/min")
        _count("fedtpu_server_rounds_total", "rounds")
        _count("fedtpu_server_round_failures_total", "failed")
        _count("fedtpu_server_uploads_total", "uploads")
        _count("fedtpu_server_stream_fallbacks_total", "fallbacks")
        _count("fedtpu_controller_promotions_total", "promoted")
        _count("fedtpu_controller_gate_rejections_total", "gate-rejected")
        _count("fedtpu_controller_drift_triggers_total", "drift-triggers")
        _count("fedtpu_serve_scored_total", "scored")
        _count("fedtpu_serve_rejects_total", "rejects")
        _count("fedtpu_router_forwarded_total", "fwd")
        _count("fedtpu_router_ejects_total", "ejects")
        if "fedtpu_serve_queue_depth" in g:
            depth = sum(g["fedtpu_serve_queue_depth"].values())
            bits.append(f"queue {depth:.0f}")
        if "fedtpu_router_inflight" in g:
            per = g["fedtpu_router_inflight"]
            bits.append(
                "inflight "
                + "/".join(
                    f"{per[k]:.0f}" for k in sorted(per)
                )
            )
        return ", ".join(bits) if bits else "(no known families)"

    # ---------------------------------------------------------------- watch
    def watch(
        self,
        *,
        interval_s: float = 2.0,
        max_seconds: float | None = None,
        out=None,
        stop=None,
    ) -> int:
        """The ``--watch`` loop: poll + render every ``interval_s``,
        clearing the screen between frames (the obs tail follow shape:
        deadline-bounded, stop-callable, KeyboardInterrupt = clean
        exit). Returns the number of polls."""
        import sys

        out = out or sys.stdout
        deadline = (
            time.monotonic() + float(max_seconds)
            if max_seconds is not None
            else None
        )
        n = 0
        try:
            while True:
                snapshot = self.poll()
                frame = self.render_status(snapshot)
                out.write("\x1b[2J\x1b[H" if out.isatty() else "")
                out.write(frame)
                out.flush()
                n += 1
                if stop is not None and stop():
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    break
                sleep_for = float(interval_s)
                if deadline is not None:
                    sleep_for = min(
                        sleep_for, max(deadline - time.monotonic(), 0.0)
                    )
                time.sleep(sleep_for)
        except KeyboardInterrupt:
            pass
        return n


def health_verdict(snapshot: dict) -> dict:
    """The machine-readable twin of :meth:`ScrapeHub.render_status` —
    ``fedtpu obs health --json``'s contract for cron/CI consumers.

    Same judgement the rendered screen (and the CLI's exit code) makes,
    as one schema-versioned dict: ``healthy`` is False exactly when a
    target is down or an SLO is firing. Raw per-target summaries stay
    in the snapshot JSONL; this is the verdict layer."""
    rows = snapshot.get("targets") or []
    states = snapshot.get("slo") or []
    down = [
        {
            "tier": r["tier"],
            "instance": r["instance"],
            "error": r.get("error"),
        }
        for r in rows
        if not r["up"]
    ]
    firing = [
        {
            "slo": s["slo"],
            "instance": s["instance"],
            "severity": s.get("severity"),
            "burn": s.get("burn"),
        }
        for s in states
        if s["firing"]
    ]
    notable: list[dict] = []
    for r in rows:
        if r.get("last_drift"):
            notable.append(
                {
                    "kind": "drift",
                    "tier": r["tier"],
                    "instance": r["instance"],
                    **{
                        k: r["last_drift"].get(k)
                        for k in ("ts", "drift", "method")
                    },
                }
            )
        if r.get("postmortems"):
            notable.append(
                {
                    "kind": "postmortems",
                    "tier": r["tier"],
                    "instance": r["instance"],
                    "count": r["postmortems"],
                }
            )
        if r.get("last_round_failed"):
            notable.append(
                {
                    "kind": "round-failed",
                    "tier": r["tier"],
                    "instance": r["instance"],
                }
            )
    return {
        "schema": HEALTH_SCHEMA,
        "ts": snapshot.get("ts"),
        "healthy": not down and not firing,
        "targets": len(rows),
        "targets_up": sum(1 for r in rows if r["up"]),
        "targets_down": down,
        "slo_total": len(states),
        "slo_firing": firing,
        "scrape_lag_ms": snapshot.get("scrape_lag_ms"),
        "notable": notable,
    }
