"""Merge per-process span JSONLs into per-round timelines + Chrome traces.

The analysis half of the obs subsystem (the write half is obs/trace.py):
load every process's events-JSONL, group spans on the shared
(trace, round) identity the server stamped across the wire, and answer
the question the uncorrelated metrics streams could not — *where did
round N's wall-clock go?*

Per-round attribution model (client-centric, from the spans each side
actually measured)::

    compute  the client's ``client-local`` span
    upload   its ``wire-upload`` send
    wait     straggler wait: the client's reply-recv window minus the
             server's measured agg + reply time (clamped at 0 — the
             residual is time spent blocked on OTHER clients)
    agg      the server's ``agg`` span (shared by every client row)
    reply    the server's ``wire-reply`` fan-out span

``compute + upload + wait + agg + reply`` reconstructs each client's
measured round wall (first-span start to last-span end) up to clamp
error and inter-span gaps — the tests pin the 10% bound.

The Chrome export emits trace-event-format "X" (complete) events —
``json.load``-able, loadable in ``chrome://tracing`` / Perfetto — one
pid per process (``proc``), one tid per span name so nested server spans
(round ⊃ agg ⊃ wire-reply) render as lanes instead of overlapping.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Iterable, Mapping

from .trace import SCHEMA


def load_spans(
    paths: Iterable[str] | None = None, *, trace_dir: str | None = None
) -> list[dict]:
    """Read span records from explicit JSONL paths and/or every
    ``*.jsonl`` under ``trace_dir``. Foreign or truncated lines (a
    crashed writer's partial tail, a concatenated metrics stream) are
    skipped, not fatal — merge tools must survive dirty inputs."""
    files: list[str] = list(paths or [])
    if trace_dir:
        files.extend(sorted(glob.glob(os.path.join(trace_dir, "*.jsonl"))))
    spans: list[dict] = []
    for path in files:
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or rec.get("schema") != SCHEMA:
                continue
            if "span" not in rec or "ts" not in rec or "dur_s" not in rec:
                continue
            spans.append(rec)
    spans.sort(key=lambda r: r["ts"])
    return spans


def read_new_jsonl_lines(
    path: str, offset: int
) -> tuple[int, list[bytes]]:
    """Incremental complete-line tail of one JSONL file: read from
    ``offset``, return ``(new_offset, complete line bytes)``. The one
    copy of the byte-offset resume pattern the drift monitor
    (control/drift.py) and the scrape hub's events tail (obs/fleet.py)
    both poll with: a missing file is empty (not an error), truncation/
    rotation restarts at 0, and a partially-flushed trailing line waits
    for the next poll (writers append whole lines atomically)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return offset, []
    if size < offset:
        offset = 0  # file truncated/rotated: start over
    if size == offset:
        return offset, []
    with open(path, "rb") as f:
        f.seek(offset)
        chunk = f.read(size - offset)
    end = chunk.rfind(b"\n")
    if end < 0:
        return offset, []
    return offset + end + 1, chunk[: end + 1].splitlines()


def tail_spans(
    paths: Iterable[str] | None = None,
    *,
    trace_dir: str | None = None,
    poll_s: float = 0.5,
    from_start: bool = False,
    stop=None,
):
    """Follow-mode span reader (the ``fedtpu obs tail`` engine): a
    generator yielding span dicts as they are APPENDED to the
    events-JSONL set — the live counterpart of :func:`load_spans`.

    Files named up front start at their end (``from_start=True`` replays
    them first); files that APPEAR later under ``trace_dir`` (a process
    opening its ``--trace-jsonl`` mid-run) are picked up from offset 0 —
    a late-starting client's spans are new by definition. Partial tails
    are never parsed: a line is consumed only once its newline landed,
    so a mid-append poll cannot yield half a record (the writers append
    whole lines atomically, obs/trace.py). ``stop`` is a zero-arg
    callable polled between passes — the tailer's only exit besides
    GeneratorExit."""
    offsets: dict[str, int] = {}

    def _files() -> list[str]:
        files = list(paths or [])
        if trace_dir:
            files.extend(
                sorted(glob.glob(os.path.join(trace_dir, "*.jsonl")))
            )
        return files

    for path in _files():
        try:
            offsets[path] = 0 if from_start else os.path.getsize(path)
        except OSError:
            offsets[path] = 0
    while True:
        for path in _files():
            off = offsets.setdefault(path, 0)
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read()
            except OSError:
                continue
            if not chunk:
                continue
            end = chunk.rfind(b"\n")
            if end < 0:
                continue  # no complete line yet
            offsets[path] = off + end + 1
            for line in chunk[: end + 1].splitlines():
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict) or rec.get("schema") != SCHEMA:
                    continue
                if "span" not in rec or "ts" not in rec or "dur_s" not in rec:
                    continue
                yield rec
        if stop is not None and stop():
            return
        time.sleep(poll_s)


def group_rounds(spans: Iterable[dict]) -> dict[tuple, list[dict]]:
    """(trace, round) -> spans. Spans that carry neither identity
    (e.g. serve-batch outside any round) group under (None, None)."""
    groups: dict[tuple, list[dict]] = {}
    for s in spans:
        key = (s.get("trace"), s.get("round"))
        groups.setdefault(key, []).append(s)
    return groups


def _one(spans: list[dict], name: str, proc: str | None = None) -> dict | None:
    cands = [
        s
        for s in spans
        if s["span"] == name and (proc is None or s.get("proc") == proc)
    ]
    return max(cands, key=lambda s: s["dur_s"]) if cands else None


def round_breakdown(spans: list[dict]) -> dict:
    """One (trace, round) group -> the per-client phase attribution the
    module docstring defines, plus slowest-span attribution."""
    agg = _one(spans, "agg")
    # Server-side reply fan-out ONLY: without an agg span (a partial
    # deployment where the server isn't tracing) there is no server
    # identity to filter on, and a wildcard would grab a CLIENT's
    # wire-reply recv window — misattributing straggler wait as reply.
    srv_proc = agg.get("proc") if agg else None
    srv_reply = (
        _one(spans, "wire-reply", proc=srv_proc) if srv_proc else None
    )
    agg_s = agg["dur_s"] if agg else 0.0
    reply_s = srv_reply["dur_s"] if srv_reply else 0.0
    # Streaming chunk aggregation (comm/stream_agg.py): fold work that
    # ran DURING the wire phase — hidden inside clients' wait, so it
    # joins no per-client sum; reported as the round's overlapped-vs-
    # exposed wire attribution instead. The exposed aggregation time is
    # the ``agg`` span as before.
    overlap = _one(spans, "wire-overlap", proc=srv_proc) if srv_proc else None
    overlap_s = overlap["dur_s"] if overlap else 0.0
    round_span = _one(spans, "round")
    client_procs = sorted(
        {
            s["proc"]
            for s in spans
            if s["span"] in ("client-local", "wire-upload")
        }
    )
    clients: dict[str, dict] = {}
    for proc in client_procs:
        mine = [s for s in spans if s.get("proc") == proc]
        compute = sum(
            s["dur_s"] for s in mine if s["span"] == "client-local"
        )
        upload = sum(s["dur_s"] for s in mine if s["span"] == "wire-upload")
        recv = sum(
            s["dur_s"]
            for s in mine
            if s["span"] == "wire-reply"
        )
        wait = max(recv - agg_s - reply_s, 0.0)
        t0 = min(s["ts"] for s in mine)
        t1 = max(s["ts"] + s["dur_s"] for s in mine)
        clients[proc] = {
            "compute_s": compute,
            "upload_s": upload,
            "wait_s": wait,
            "agg_s": agg_s,
            "reply_s": reply_s,
            "attributed_s": compute + upload + wait + agg_s + reply_s,
            "measured_s": t1 - t0,
        }
    slowest = max(spans, key=lambda s: s["dur_s"]) if spans else None
    return {
        "trace": spans[0].get("trace") if spans else None,
        "round": spans[0].get("round") if spans else None,
        "round_wall_s": round_span["dur_s"] if round_span else None,
        # The server's aggregated-contributor ids (agg span attr, PR 6):
        # a client row present here but absent from contributors was
        # dropped/excluded; absent entirely = never arrived. None on
        # traces from servers that predate the attribute.
        "contributors": agg.get("contributors") if agg else None,
        "failed": bool(round_span.get("failed")) if round_span else False,
        "agg_s": agg_s,
        "reply_s": reply_s,
        "overlap_s": overlap_s,
        "overlap_frac": overlap.get("overlap_frac") if overlap else None,
        "peak_agg_bytes": (
            overlap.get("peak_agg_bytes") if overlap else None
        ),
        "clients": clients,
        "slowest_span": (
            {
                "span": slowest["span"],
                "proc": slowest.get("proc"),
                "dur_s": slowest["dur_s"],
            }
            if slowest
            else None
        ),
        "n_spans": len(spans),
    }


def timeline_table(
    spans: list[dict], *, round_filter: int | None = None
) -> str:
    """Human-readable per-round table over the merged spans (the
    ``fedtpu obs timeline`` output)."""
    groups = group_rounds(spans)
    out: list[str] = []
    keys = sorted(
        (k for k in groups if k != (None, None)),
        key=lambda k: (k[1] if k[1] is not None else -1, str(k[0])),
    )
    for key in keys:
        trace, rnd = key
        if round_filter is not None and rnd != round_filter:
            continue
        b = round_breakdown(groups[key])
        head = f"trace {trace or '-'} round {rnd if rnd is not None else '-'}"
        if b["round_wall_s"] is not None:
            head += f"  server wall {b['round_wall_s']:.3f}s"
        if b["failed"]:
            head += "  FAILED"
        if b["contributors"] is not None:
            head += f"  contributors {b['contributors']}"
        out.append(head)
        if b["clients"]:
            out.append(
                f"  {'client':<14} {'compute':>9} {'upload':>9} "
                f"{'wait':>9} {'agg':>9} {'reply':>9} {'total':>9} "
                f"{'measured':>9}"
            )
            for proc, row in sorted(b["clients"].items()):
                out.append(
                    f"  {proc:<14} "
                    f"{row['compute_s']:>8.3f}s {row['upload_s']:>8.3f}s "
                    f"{row['wait_s']:>8.3f}s {row['agg_s']:>8.3f}s "
                    f"{row['reply_s']:>8.3f}s {row['attributed_s']:>8.3f}s "
                    f"{row['measured_s']:>8.3f}s"
                )
        # Device-vs-host row (obs/profile.py StepProfiler): client-local
        # spans carrying sampled step attrs split their compute seconds
        # into host batch-prep / dispatch / device-execute.
        for s in groups[key]:
            if s["span"] != "client-local" or s.get(
                "step_device_ms_p50"
            ) is None:
                continue
            out.append(
                f"  step-profile   {str(s.get('proc', '?')):<14} "
                f"host {s.get('step_host_ms_p50', 0.0):.2f}ms  "
                f"dispatch {s.get('step_dispatch_ms_p50', 0.0):.2f}ms  "
                f"device {s['step_device_ms_p50']:.2f}ms p50 "
                f"({s.get('step_sampled', 0)} sampled)"
            )
        # Wire-codec row (obs/profile.py "wire" site): wire-upload /
        # wire-reply spans carrying sampled per-leaf pack/unpack timings
        # — the stream hot loops the step profiler's train/score sites
        # never covered.
        for s in groups[key]:
            if s["span"] not in ("wire-upload", "wire-reply") or s.get(
                "step_wire_ms_p50"
            ) is None:
                continue
            kind = "pack" if s["span"] == "wire-upload" else "unpack"
            out.append(
                f"  wire-codec     {str(s.get('proc', '?')):<14} "
                f"{kind} {s['step_wire_ms_p50']:.2f}ms p50 / "
                f"{s.get('step_wire_ms_p95', 0.0):.2f}ms p95 per leaf "
                f"({s.get('step_sampled', 0)} sampled)"
            )
        if b["overlap_s"] > 0.0:
            # Overlapped vs exposed wire/aggregation time: fold seconds
            # hidden inside the wire phase, next to the exposed agg.
            frac = b["overlap_frac"]
            peak = b["peak_agg_bytes"]
            out.append(
                f"  wire-overlap   {b['overlap_s']:>8.3f}s folded during "
                "the wire phase"
                + (f" ({frac:.0%} of fold input)" if frac is not None else "")
                + (f", peak agg {peak / 1e6:.1f} MB" if peak else "")
            )
        # Wire-efficiency row (PR 17): quantized-upload dtypes + fold
        # engine/throughput ride the wire-overlap span; sparse upward
        # hops stamp their bytes on relay-forward spans. One compressed
        # line showing what the round's wire actually carried.
        for s in groups[key]:
            if s["span"] != "wire-overlap" or (
                not s.get("wire_dtypes")
                and not s.get("fold_engine")
            ):
                continue
            dts = s.get("wire_dtypes") or ["fp32"]
            gbps = s.get("fold_throughput_gbps")
            out.append(
                f"  wire-dtype     uploads {'+'.join(str(d) for d in dts)}"
                + (f", fold {s['fold_engine']}" if s.get("fold_engine") else "")
                + (f" @ {gbps:.2f} GB/s" if gbps else "")
            )
        up_spans = [
            s
            for s in groups[key]
            if s["span"] == "relay-forward"
            and s.get("upward_bytes") is not None
        ]
        if up_spans:
            up_total = sum(int(s["upward_bytes"]) for s in up_spans)
            n_sparse = sum(1 for s in up_spans if s.get("upward_sparse"))
            out.append(
                f"  relay-upward   {up_total / 1e6:>8.2f} MB over "
                f"{len(up_spans)} hop(s)"
                + (
                    f" ({n_sparse} sparse topk)"
                    if n_sparse
                    else " (dense)"
                )
            )
        extra = [
            s
            for s in groups[key]
            if s["span"]
            in (
                "eval-gate",
                "promote",
                "serve-batch",
                "batch-prefetch",
                "relay-forward",
                "router-forward",
                "replica-drain",
                "slo-eval",
                "postmortem-dump",
                "drift-trigger",
                "xla-compile",
            )
        ]
        for s in extra:
            out.append(
                f"  {s['span']:<14} {s['dur_s']:>8.3f}s  ({s.get('proc')})"
            )
        if b["slowest_span"]:
            sl = b["slowest_span"]
            out.append(
                f"  slowest span: {sl['span']} on {sl['proc']} "
                f"({sl['dur_s']:.3f}s)"
            )
        out.append("")
    # Health-plane spans carry NO (trace, round) by construction — the
    # hub's slo-eval poll and a flight-recorder dump happen outside any
    # round's identity — so they live in the (None, None) group the
    # per-round rendering above excludes. Surface the notable ones in a
    # trailing section (newest last, capped) instead of dropping them.
    unscoped = [
        s
        for s in groups.get((None, None), ())
        if s["span"]
        in (
            "postmortem-dump",
            "drift-trigger",
            "slo-eval",
            "xla-compile",
            "shadow-mirror",
            "shadow-compare",
            "shadow-gate",
            "canary-probe",
            "sentinel-eval",
            "regression-fire",
        )
    ]
    if unscoped and round_filter is None:
        out.append("unscoped health-plane spans:")
        for s in unscoped[-10:]:
            attrs = " ".join(
                f"{k}={s[k]}"
                for k in (
                    "reason", "bundle", "drift", "firing", "up",
                    "site", "recompile", "pairs", "flip_rate", "passed",
                    "artifact", "mirrored", "mismatches", "flips",
                    "drift_fired", "regressions", "field", "now_mean",
                )
                if s.get(k) is not None
            )
            out.append(
                f"  {s['span']:<16} {s['dur_s']:>8.3f}s  "
                f"({s.get('proc')})" + (f"  {attrs}" if attrs else "")
            )
        out.append("")
    if not out:
        return "(no round-scoped spans found)\n"
    return "\n".join(out)


# ------------------------------------------------------- chrome export
def chrome_trace(spans: list[dict]) -> dict:
    """Chrome trace-event JSON (the object form with ``traceEvents``):
    one "X" complete event per span, microsecond timestamps rebased to
    the earliest span, pid per process, tid per span name (nested server
    spans become lanes, never overlaps)."""
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(s["ts"] for s in spans)
    procs = sorted({str(s.get("proc", "?")) for s in spans})
    pid_of = {p: i + 1 for i, p in enumerate(procs)}
    names = sorted({s["span"] for s in spans})
    tid_of = {n: i + 1 for i, n in enumerate(names)}
    events: list[dict[str, Any]] = []
    for p in procs:
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid_of[p],
                "tid": 0,
                "args": {"name": p},
            }
        )
    for n in names:
        for p in procs:
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid_of[p],
                    "tid": tid_of[n],
                    "args": {"name": n},
                }
            )
    for s in spans:
        args = {
            k: v
            for k, v in s.items()
            if k not in ("schema", "proc", "span", "ts", "dur_s")
        }
        events.append(
            {
                "name": s["span"],
                "cat": "fedtpu",
                "ph": "X",
                "ts": round((s["ts"] - t0) * 1e6, 3),
                "dur": round(s["dur_s"] * 1e6, 3),
                "pid": pid_of[str(s.get("proc", "?"))],
                "tid": tid_of[s["span"]],
                "args": args,
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(
    spans: list[dict], out_path: str
) -> str:
    """Write :func:`chrome_trace` to ``out_path``; returns the path."""
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(chrome_trace(spans), f)
    return out_path


def round_summaries(spans: list[dict]) -> list[dict]:
    """Machine-readable per-round breakdowns (what ``obs timeline
    --json`` prints), sorted by round."""
    groups = group_rounds(spans)
    out = [
        round_breakdown(g)
        for key, g in sorted(
            groups.items(),
            key=lambda kv: (
                kv[0][1] if kv[0][1] is not None else -1,
                str(kv[0][0]),
            ),
        )
        if key != (None, None)
    ]
    return out
