"""Sentinel plane: canary probing, journal-tailing supervised drift, and
long-horizon regression detection — ``fedtpu obs sentinel``.

The health plane up to here answers "what is burning NOW": every scrape-
hub verdict is a two-poll delta with no memory, the supervised error
monitor only ran when a gate happened to look, and nothing continuously
proved the router -> replica -> score chain end to end against known
truth. Fleets degrade *gradually* between rounds — exactly the failure
mode an instantaneous view structurally cannot see. The sentinel is one
standalone watch daemon with three rungs:

* **Canary probes** (:class:`CanaryProber`). A checked-in set of
  known-label flows (benign + attack, per preset — the
  ``tests/data/canary_flows.jsonl`` fixture shape) is scored on a
  cadence through the REAL serving chain via the scoring SDK
  (serving/client.probe_scores). Each pass asserts (1) the reply's
  model round matches the registry's promoted serving pointer — a stale
  replica answering for a superseded artifact is an incident — and
  (2) the score is bit-stable per (serving artifact, canary id): a
  score flip WITHOUT a promotion is an incident (a legitimate promotion
  changes the artifact id, which re-keys the expectation and never
  fires). End-to-end latency feeds ``fedtpu_canary_latency_seconds``
  (the canary SLO's histogram); results ride ``canary-probe`` spans and
  page-severity incidents trip the flight recorder.
* **Journal tailing** (:class:`JournalTail`). The serving tier's
  scored-JSONL export and the ground-truth journal (labels/store.py)
  are tailed incrementally (byte-offset resume, complete lines only —
  the DriftMonitor discipline); joined (prediction, label) pairs feed a
  :class:`~..control.drift.ErrorRateMonitor` CONTINUOUSLY, so a
  supervised-drift verdict can fire BETWEEN gates. A fired verdict is
  journaled to a verdicts-JSONL the controller's
  :class:`~..control.drift.SentinelLink` tails — the cross-process poke
  that starts a corrective round.
* **Long-horizon retention** (:class:`RetentionRing`). A downsampled
  on-disk ring of compact per-tick rows (canary p99, round cadence,
  supervised error, eject rate) with pure-arithmetic trend checks
  against a PINNED baseline window: the first ``baseline_n`` retained
  rows are frozen, and a current-window mean moving past
  ``baseline * ratio + floor`` (direction-aware — cadence regresses
  DOWN) fires a ``regression-fire`` span + alert with the
  baseline-vs-now evidence attached.

The sentinel is a READER of the fleet (the scrape-hub contract): it
holds no lock any daemon shares, and a sentinel crash costs detection,
never rounds or requests.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable, Mapping

from . import metrics as obs_metrics
from .flight import get_global_recorder
from .slo import ALERT_SCHEMA
from .timeline import read_new_jsonl_lines
from .trace import append_jsonl_line

#: Schema tag on every canary-fixture line (tests/data/canary_flows.jsonl).
CANARY_SCHEMA = "fedtpu-canary-v1"

#: Schema tag on every sentinel tick report (``obs sentinel --json``).
SENTINEL_SCHEMA = "fedtpu-sentinel-v1"

#: Schema tag on every retention-ring row.
RING_SCHEMA = "fedtpu-ring-v1"

#: Schema tag on every journaled supervised-drift verdict (the file the
#: controller's SentinelLink tails).
VERDICT_SCHEMA = "fedtpu-sentinel-verdict-v1"

#: The ring fields the stock trend check watches, with (ratio, floor,
#: direction): a regression fires when the current-window mean moves
#: past ``baseline * ratio + floor`` for "up" fields, or below
#: ``baseline / ratio - floor`` for "down" fields (round cadence
#: regresses by DROPPING).
DEFAULT_TREND_FIELDS: dict[str, tuple[float, float, str]] = {
    "latency_p99_ms": (1.5, 5.0, "up"),
    "round_cadence": (1.5, 0.0, "down"),
    "supervised_error": (1.5, 0.02, "up"),
    "eject_rate": (1.5, 0.001, "up"),
}


def parse_trend_field_spec(spec: str) -> tuple[str, tuple[float, float, str]]:
    """One ``--trend-field NAME[:direction]`` value -> a
    ``(name, (ratio, floor, direction))`` trend-fields entry.

    Per-deployment ring fields: the named counter's per-tick rate is
    pulled from the fleet snapshot's cadence dicts (max across targets,
    exactly how the stock ``round_cadence``/``eject_rate`` rows are
    built) and judged by the same pure-arithmetic baseline/window check
    as the stock fields. Direction defaults to "up" (a counter whose
    RATE growing past baseline*ratio is the regression); ":down" watches
    for the rate collapsing (a heartbeat counter going quiet). The
    stock ratio/floor defaults (1.5, 0.0) apply — deployments needing
    custom thresholds pair this with ``--regression-ratio``."""
    name, sep, direction = spec.partition(":")
    name = name.strip()
    direction = direction.strip() if sep else "up"
    if not name:
        raise ValueError(
            f"--trend-field {spec!r}: want NAME or NAME:direction "
            "(e.g. fedtpu_server_stream_fallbacks_total:up)"
        )
    if direction not in ("up", "down"):
        raise ValueError(
            f"--trend-field {spec!r}: direction must be up|down "
            f"(got {direction!r})"
        )
    return name, (1.5, 0.0, direction)


# ------------------------------------------------------------------ canaries
@dataclass(frozen=True)
class CanaryFlow:
    """One checked-in known-truth flow: a rendered template text plus
    the label the fleet must keep agreeing with itself about."""

    id: str
    preset: str
    label: int
    text: str
    #: K-class presets carry the class NAME too (class 0 = benign by
    #: the data/datasets.py convention); binary presets leave it None.
    class_label: str | None = None


def load_canary_flows(
    path: str, *, preset: str | None = None
) -> list[CanaryFlow]:
    """Read + validate a ``fedtpu-canary-v1`` fixture JSONL.

    Every line must carry the schema tag, a unique non-empty ``id``, a
    ``preset``, an integer ``label`` >= 0, and a non-empty ``text``.
    Foreign or torn lines FAIL LOUDLY — a silently dropped canary is a
    silently narrowed proof. ``preset`` filters to one dataset's
    canaries (the fixture is per-preset by design)."""
    flows: list[CanaryFlow] = []
    seen: set[str] = set()
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: not JSON ({e})"
                ) from None
            if not isinstance(rec, dict) or rec.get("schema") != CANARY_SCHEMA:
                raise ValueError(
                    f"{path}:{lineno}: not a {CANARY_SCHEMA} record"
                )
            missing = [
                k for k in ("id", "preset", "label", "text") if not rec.get(k)
                and rec.get(k) != 0
            ]
            if missing:
                raise ValueError(f"{path}:{lineno}: missing {missing}")
            cid = str(rec["id"])
            if cid in seen:
                raise ValueError(f"{path}:{lineno}: duplicate canary id {cid!r}")
            seen.add(cid)
            label = rec["label"]
            if not isinstance(label, int) or label < 0:
                raise ValueError(
                    f"{path}:{lineno}: label {label!r} must be an int >= 0"
                )
            flows.append(
                CanaryFlow(
                    id=cid,
                    preset=str(rec["preset"]),
                    label=label,
                    text=str(rec["text"]),
                    class_label=rec.get("class_label"),
                )
            )
    if preset is not None:
        have = sorted({f.preset for f in flows})
        flows = [f for f in flows if f.preset == preset]
        if not flows:
            raise ValueError(
                f"{path}: no canaries for preset {preset!r} (have {have})"
            )
    if not flows:
        raise ValueError(f"{path}: no canary flows")
    return flows


class CanaryProber:
    """Rung 1: score the canary set through the live serving chain and
    hold the fleet to the registry's promoted pointer.

    ``probe_fn`` defaults to :func:`~..serving.client.probe_scores`
    (one real TCP connection per pass); tests inject a fake. A probe
    pass NEVER raises — a down serving tier is a counted failure, not a
    sentinel crash."""

    def __init__(
        self,
        flows: Iterable[CanaryFlow],
        host: str,
        port: int,
        *,
        registry=None,
        timeout_s: float = 5.0,
        deadline_ms: float | None = None,
        auth_key: bytes | None = None,
        tracer=None,
        recorder=None,
        probe_fn: Callable | None = None,
    ):
        self.flows = list(flows)
        if not self.flows:
            raise ValueError("canary prober needs at least one flow")
        self.host = host
        self.port = int(port)
        self.registry = registry
        self.timeout_s = float(timeout_s)
        self.deadline_ms = deadline_ms
        self.auth_key = auth_key
        self.tracer = tracer
        self._recorder = recorder
        if probe_fn is None:
            from ..serving.client import probe_scores

            probe_fn = probe_scores
        self._probe_fn = probe_fn
        # (serving artifact id, canary id) -> last observed probability.
        # A legitimate promotion changes the artifact id, so its score
        # change lands under a FRESH key and can never fire.
        self._scores: dict[tuple[str, str], float] = {}
        m = obs_metrics.default_registry()
        self._m_probes = m.counter(
            "fedtpu_canary_probes_total",
            help="canary flows scored through the live serving chain",
        )
        self._m_failures = m.counter(
            "fedtpu_canary_failures_total",
            help="canary probe passes that could not reach the serving tier",
        )
        self._m_incidents = m.counter(
            "fedtpu_canary_incidents_total",
            help="canary incidents: stale-pointer round mismatches plus "
            "score flips without a promotion",
        )
        self._m_latency = m.histogram(
            "fedtpu_canary_latency_seconds",
            help="end-to-end canary score latency through the SDK "
            "(the canary SLO's histogram)",
            buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
        )

    def _pointer(self) -> tuple[str | None, int | None]:
        """(serving artifact id, its round) off the registry — None/None
        when no registry is wired or nothing is promoted yet."""
        if self.registry is None:
            return None, None
        try:
            info = self.registry.serving_info()
        except Exception:
            return None, None
        if not info:
            return None, None
        return info.get("artifact"), info.get("round")

    def probe(self, *, now: float | None = None) -> dict:
        """One pass: score every canary, judge identity + bit-stability
        + latency, ride a ``canary-probe`` span, trip the recorder on
        incidents. Returns the pass verdict dict."""
        if now is None:
            now = time.time()
        artifact, expected_round = self._pointer()
        t0 = time.monotonic()
        incidents: list[dict] = []
        latencies_ms: list[float] = []
        failures = 0
        replies: list[tuple[dict, float]] = []
        try:
            replies = self._probe_fn(
                self.host,
                self.port,
                [f.text for f in self.flows],
                timeout=self.timeout_s,
                deadline_ms=self.deadline_ms,
                auth_key=self.auth_key,
            )
        except Exception as e:  # down tier = counted, never fatal
            failures = len(self.flows)
            self._m_failures.inc(failures)
            incidents.append(
                {
                    "kind": "probe-failure",
                    "detail": f"{type(e).__name__}: {str(e)[:200]}",
                }
            )
        flips = mismatches = wrong = 0
        for flow, (reply, lat_s) in zip(self.flows, replies):
            self._m_probes.inc()
            self._m_latency.observe(lat_s)
            latencies_ms.append(lat_s * 1e3)
            if reply.get("rejected"):
                failures += 1
                self._m_failures.inc()
                incidents.append(
                    {
                        "kind": "probe-reject",
                        "canary": flow.id,
                        "code": reply.get("code"),
                        "reason": reply.get("reason"),
                    }
                )
                continue
            got_round = reply.get("round")
            stale = (
                expected_round is not None
                and got_round is not None
                and int(got_round) != int(expected_round)
            )
            if stale:
                mismatches += 1
                incidents.append(
                    {
                        "kind": "pointer-mismatch",
                        "canary": flow.id,
                        "reply_round": int(got_round),
                        "expected_round": int(expected_round),
                        "artifact": artifact,
                    }
                )
            prob = float(reply["prob"])
            # Bit-stability is keyed by what actually ANSWERED: on a
            # pointer mismatch the registry's artifact id is exactly the
            # claim that proved false, so keying the score under it
            # would fire a spurious flip when the replica is repaired.
            key = (
                (artifact if not stale else None) or f"round-{got_round}",
                flow.id,
            )
            prev = self._scores.get(key)
            if prev is not None and prob != prev:
                flips += 1
                incidents.append(
                    {
                        "kind": "score-flip",
                        "canary": flow.id,
                        "artifact": key[0],
                        "prev_prob": prev,
                        "prob": prob,
                    }
                )
            self._scores[key] = prob
            if int(reply.get("prediction", 0)) != (1 if flow.label else 0):
                # A persistently misclassified canary is a QUALITY
                # signal the report surfaces, not a stability incident
                # — a weak model is the gate's problem, not an outage.
                wrong += 1
        if incidents:
            self._m_incidents.inc(len(incidents))
        latencies_ms.sort()
        p99_ms = (
            latencies_ms[max(0, int(len(latencies_ms) * 0.99) - 1)]
            if latencies_ms
            else None
        )
        result = {
            "probes": len(replies),
            "failures": failures,
            "mismatches": mismatches,
            "flips": flips,
            "wrong_label": wrong,
            "incidents": incidents,
            "artifact": artifact,
            "expected_round": expected_round,
            "latency_p99_ms": round(p99_ms, 3) if p99_ms is not None else None,
        }
        if self.tracer is not None:
            self.tracer.record(
                "canary-probe",
                t_start=now,
                dur_s=time.monotonic() - t0,
                canaries=len(self.flows),
                probes=len(replies),
                failures=failures,
                mismatches=mismatches,
                flips=flips,
                artifact=artifact,
                latency_p99_ms=result["latency_p99_ms"],
            )
        if mismatches or flips:
            rec = self._recorder or get_global_recorder()
            if rec is not None:
                try:
                    rec.maybe_dump(
                        "canary-incident",
                        extra={"incidents": incidents[:10]},
                    )
                except OSError:
                    pass
        return result


# ------------------------------------------------------------ journal tailing
class JournalTail:
    """Rung 2: the between-gates supervised drift poll loop.

    Tails the serving tier's scored-JSONL (rid -> prob) and the
    ground-truth journal (rid -> label, plus the completeness
    watermark), joins pairs as both sides arrive, and feeds an
    :class:`~..control.drift.ErrorRateMonitor` continuously — closing
    the "error monitor only observes at gate time" gap. A fired verdict
    is journaled to ``verdicts_jsonl`` for the controller's
    SentinelLink to tail."""

    #: Unjoined scored flows retained while their label is in flight;
    #: oldest evicted beyond this (delayed truth is partial by nature).
    MAX_PENDING = 100_000

    def __init__(
        self,
        scored_jsonl: str,
        journal: str,
        *,
        monitor,
        threshold: float = 0.5,
        verdicts_jsonl: str | None = None,
        tracer=None,
    ):
        self.scored_jsonl = scored_jsonl
        self.journal = journal
        self.monitor = monitor
        self.threshold = float(threshold)
        self.verdicts_jsonl = verdicts_jsonl
        self.tracer = tracer
        self._scored_offset = 0
        self._journal_offset = 0
        self._pending: dict[str, float] = {}  # rid -> prob, label not yet seen
        self._labels: dict[str, int] = {}  # rid -> label, score not yet seen
        # Recent (wrong, total) per poll — the tail's OWN error window
        # for the retention ring, surviving the monitor's reset-on-fire.
        self._recent: list[tuple[int, int]] = []
        self.watermark: float | None = None
        self.joined_total = 0
        self.fires = 0
        m = obs_metrics.default_registry()
        self._m_joined = m.counter(
            "fedtpu_sentinel_joined_total",
            help="scored flows joined against delayed ground truth by "
            "the sentinel's journal tail",
        )
        self._m_drift_fires = m.counter(
            "fedtpu_sentinel_drift_fires_total",
            help="supervised-drift verdicts fired between gates",
        )

    def _evict(self) -> None:
        while len(self._pending) > self.MAX_PENDING:
            self._pending.pop(next(iter(self._pending)))

    def poll(self, *, now: float | None = None) -> dict:
        """One tail pass: ingest new scored records + labels, join, feed
        the monitor, check for a verdict. Returns the rung status (with
        ``verdict`` set on a fire, None otherwise)."""
        if now is None:
            now = time.time()
        pairs: list[tuple[int, int]] = []  # (prediction, label)
        self._scored_offset, scored_lines = read_new_jsonl_lines(
            self.scored_jsonl, self._scored_offset
        )
        for line in scored_lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or "rid" not in rec or "prob" not in rec:
                continue
            rid = str(rec["rid"])
            prob = float(rec["prob"])
            label = self._labels.pop(rid, None)
            if label is not None:
                pairs.append((1 if prob >= self.threshold else 0, label))
            else:
                self._pending[rid] = prob
        self._evict()
        self._journal_offset, label_lines = read_new_jsonl_lines(
            self.journal, self._journal_offset
        )
        for line in label_lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict):
                continue
            if "watermark" in rec:
                wm = float(rec["watermark"])
                if self.watermark is None or wm > self.watermark:
                    self.watermark = wm
                continue
            if "rid" not in rec or "label" not in rec:
                continue
            rid = str(rec["rid"])
            label = 1 if int(rec["label"]) else 0
            prob = self._pending.pop(rid, None)
            if prob is not None:
                pairs.append((1 if prob >= self.threshold else 0, label))
            else:
                self._labels[rid] = label
        verdict = None
        if pairs:
            wrong = sum(1 for pred, label in pairs if pred != label)
            self.monitor.observe(wrong, len(pairs))
            self.joined_total += len(pairs)
            self._m_joined.inc(len(pairs))
            self._recent.append((wrong, len(pairs)))
            del self._recent[:-32]
        # check() even on an empty poll: the window may already hold
        # enough joined evidence from earlier passes.
        fired = self.monitor.check()
        if fired is not None:
            self.fires += 1
            self._m_drift_fires.inc()
            verdict = {"schema": VERDICT_SCHEMA, "ts": float(now), **fired}
            if self.watermark is not None:
                verdict["watermark"] = self.watermark
            if self.verdicts_jsonl:
                try:
                    append_jsonl_line(self.verdicts_jsonl, json.dumps(verdict))
                except OSError:
                    pass  # a full disk costs the poke, never the loop
        return {
            "joined": self.joined_total,
            "pending": len(self._pending),
            "unmatched_labels": len(self._labels),
            "watermark": self.watermark,
            "window_error": self._window_error(),
            "fires": self.fires,
            "verdict": verdict,
        }

    def _window_error(self) -> float | None:
        """Error rate over the last <=32 polls' joined pairs (None
        before any join) — the retention ring's supervised_error input.
        Kept here rather than read off the monitor: a fired verdict
        resets the monitor's window, and the ring wants continuity."""
        wrong = sum(w for w, _ in self._recent)
        total = sum(t for _, t in self._recent)
        return (wrong / total) if total else None


# ------------------------------------------------------------- retention ring
class RetentionRing:
    """Rung 3: bounded long-horizon memory + pure-arithmetic trend
    verdicts.

    ``note`` keeps every ``stride``-th row (downsampling makes a day of
    2 s polls a few hundred rows) in memory AND on disk; the file is
    compacted with an atomic ``os.replace`` roll when it doubles past
    ``max_records`` (a plain per-note append — the ring is single-
    writer, so the obs/trace.py shared-fd discipline is not needed and
    would pin the rotated inode). The BASELINE window is the first
    ``baseline_n`` retained rows, frozen once full: "how the fleet
    looked when watching began" is exactly the pin a slow regression is
    measured against."""

    def __init__(
        self,
        path: str | None = None,
        *,
        max_records: int = 512,
        stride: int = 1,
        baseline_n: int = 8,
        window_n: int = 8,
        trend_fields: Mapping[str, tuple[float, float, str]] | None = None,
    ):
        if max_records < max(baseline_n, window_n):
            raise ValueError(
                f"max_records={max_records} must hold at least the "
                f"baseline ({baseline_n}) and current ({window_n}) windows"
            )
        if stride < 1:
            raise ValueError(f"stride={stride} must be >= 1")
        self.path = path
        self.max_records = int(max_records)
        self.stride = int(stride)
        self.baseline_n = int(baseline_n)
        self.window_n = int(window_n)
        self.trend_fields = dict(
            DEFAULT_TREND_FIELDS if trend_fields is None else trend_fields
        )
        self._lock = threading.Lock()
        self._records: list[dict] = []
        self._baseline: list[dict] = []
        self._seen = 0
        self._firing: set[str] = set()
        if path and os.path.exists(path):
            self._load(path)
        m = obs_metrics.default_registry()
        self._g_records = m.gauge(
            "fedtpu_sentinel_ring_records",
            help="retained long-horizon ring rows",
        )

    def _load(self, path: str) -> None:
        """Resume a prior watch: replay the on-disk ring (tolerating
        torn tails) so the pinned baseline survives a sentinel restart."""
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            return
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("schema") == RING_SCHEMA:
                self._records.append(rec)
                if len(self._baseline) < self.baseline_n:
                    self._baseline.append(rec)
        self._records = self._records[-self.max_records:]

    def note(self, row: Mapping, *, now: float) -> None:
        """Retain one tick's compact row (every ``stride``-th; the first
        is always kept so a short watch still has a baseline)."""
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self.stride:
                return
            rec = {"schema": RING_SCHEMA, "ts": float(now), **row}
            self._records.append(rec)
            if len(self._baseline) < self.baseline_n:
                self._baseline.append(rec)
            if len(self._records) > self.max_records:
                self._records = self._records[-self.max_records:]
            self._g_records.set(float(len(self._records)))
            if not self.path:
                return
            try:
                os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                self._maybe_compact()
            except OSError:
                pass  # a full disk costs retention, never the loop

    def _maybe_compact(self) -> None:
        """Atomic roll: once the file doubles past the ring bound,
        rewrite the retained tail to a tmp twin and ``os.replace`` it
        over the live file — a reader sees the old file or the new one,
        never a truncated middle. Caller holds ``_lock``."""
        try:
            with open(self.path) as f:
                n_lines = sum(1 for _ in f)
        except OSError:
            return
        if n_lines <= 2 * self.max_records:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for rec in self._records:
                f.write(json.dumps(rec) + "\n")
        os.replace(tmp, self.path)

    @property
    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    @property
    def baseline_pinned(self) -> bool:
        with self._lock:
            return len(self._baseline) >= self.baseline_n

    @staticmethod
    def _mean(rows: list[dict], field: str) -> float | None:
        vals = [
            float(r[field])
            for r in rows
            if isinstance(r.get(field), (int, float))
        ]
        return (sum(vals) / len(vals)) if vals else None

    def trend(self) -> list[dict]:
        """Judge the current window against the pinned baseline. Pure
        arithmetic over retained rows — no clock, no state mutation
        beyond fire/clear edge tracking (a regression fires ONCE per
        excursion, re-arming when the trend recovers)."""
        fired: list[dict] = []
        with self._lock:
            if len(self._baseline) < self.baseline_n:
                return []
            if len(self._records) < self.baseline_n + self.window_n:
                # The current window must not overlap the rows that
                # seeded the baseline, or a fleet that was ALWAYS slow
                # would "regress" against itself.
                return []
            recent = self._records[-self.window_n:]
            for field, (ratio, floor, direction) in self.trend_fields.items():
                base = self._mean(self._baseline, field)
                cur = self._mean(recent, field)
                if base is None or cur is None:
                    continue
                if direction == "down":
                    breached = cur < base / ratio - floor
                else:
                    breached = cur > base * ratio + floor
                if breached and field not in self._firing:
                    self._firing.add(field)
                    fired.append(
                        {
                            "field": field,
                            "baseline": round(base, 6),
                            "now": round(cur, 6),
                            "ratio": ratio,
                            "floor": floor,
                            "direction": direction,
                            "baseline_window": len(self._baseline),
                            "current_window": len(recent),
                        }
                    )
                elif not breached:
                    self._firing.discard(field)
        return fired


# ------------------------------------------------------------------ sentinel
class Sentinel:
    """The composed watch daemon: one ``tick`` runs every configured
    rung and returns a schema-versioned report; ``watch`` is the
    ``fedtpu obs sentinel`` loop. Any rung may be absent — a sentinel
    with only canaries (or only the journal tail) is a valid deployment."""

    def __init__(
        self,
        *,
        prober: CanaryProber | None = None,
        tail: JournalTail | None = None,
        ring: RetentionRing | None = None,
        hub=None,
        alerts_jsonl: str | None = None,
        tracer=None,
        recorder=None,
    ):
        if prober is None and tail is None and ring is None:
            raise ValueError("sentinel needs at least one rung")
        self.prober = prober
        self.tail = tail
        self.ring = ring
        self.hub = hub
        self.alerts_jsonl = alerts_jsonl
        self.tracer = tracer
        self._recorder = recorder
        self.ticks = 0
        self.canary_flips = 0  # pointer mismatches + unexplained flips
        self.drift_fires = 0
        self.regression_fires = 0
        m = obs_metrics.default_registry()
        self._m_ticks = m.counter(
            "fedtpu_sentinel_ticks_total",
            help="sentinel evaluation passes",
        )
        self._m_regressions = m.counter(
            "fedtpu_sentinel_regression_fires_total",
            help="long-horizon trend regressions fired against the "
            "pinned baseline window",
        )

    @staticmethod
    def _fleet_rates(snapshot: dict | None) -> tuple[float | None, float | None]:
        """(round cadence, eject rate) out of a fleet snapshot's
        per-target cadence deltas — the ring's fleet-side inputs."""
        if not snapshot:
            return None, None
        cadence = eject = None
        for row in snapshot.get("targets", ()):
            c = row.get("cadence") or {}
            r = c.get("fedtpu_server_rounds_total")
            if r is None:
                r = c.get("fedtpu_controller_rounds_total")
            if r is not None:
                cadence = max(cadence or 0.0, float(r))
            e = c.get("fedtpu_router_ejects_total")
            if e is not None:
                eject = max(eject or 0.0, float(e))
        return cadence, eject

    def _alert(self, ev: dict) -> None:
        """Sentinel-originated alert: same ``fedtpu-alert-v1`` shape the
        burn machinery emits, so alert consumers need one parser."""
        if self.alerts_jsonl:
            try:
                append_jsonl_line(self.alerts_jsonl, json.dumps(ev))
            except OSError:
                pass
        rec = self._recorder or get_global_recorder()
        if rec is not None:
            try:
                rec.note_alert(ev)
                rec.maybe_dump(f"sentinel-{ev['slo']}", extra=ev)
            except OSError:
                pass

    def tick(self, *, now: float | None = None) -> dict:
        """One sentinel pass over every configured rung."""
        if now is None:
            now = time.time()
        t0 = time.monotonic()
        self.ticks += 1
        self._m_ticks.inc()
        snapshot = self.hub.poll(now=now) if self.hub is not None else None
        canary = self.prober.probe(now=now) if self.prober is not None else None
        drift = self.tail.poll(now=now) if self.tail is not None else None
        if canary is not None:
            self.canary_flips += canary["mismatches"] + canary["flips"]
        if drift is not None and drift["verdict"] is not None:
            self.drift_fires += 1
            self._alert(
                {
                    "schema": ALERT_SCHEMA,
                    "ts": float(now),
                    "event": "fire",
                    "slo": "sentinel-supervised-drift",
                    "instance": "sentinel",
                    "severity": "page",
                    "objective": None,
                    "burn": {},
                    "verdict": {
                        k: v
                        for k, v in drift["verdict"].items()
                        if k != "schema"
                    },
                }
            )
        regressions: list[dict] = []
        if self.ring is not None:
            cadence, eject = self._fleet_rates(snapshot)
            row = {
                "latency_p99_ms": (
                    canary.get("latency_p99_ms") if canary else None
                ),
                "round_cadence": cadence,
                "supervised_error": (
                    drift.get("window_error") if drift else None
                ),
                "eject_rate": eject,
            }
            # Custom --trend-field rows: any watched field that is not a
            # stock row input is a per-deployment counter rate, pulled
            # from the fleet snapshot's cadence dicts the same way the
            # stock fleet-side inputs are (max across targets — the
            # hottest instance is the one regressing).
            for field in self.ring.trend_fields:
                if field in row:
                    continue
                val = None
                for t in (snapshot or {}).get("targets", ()):
                    v = (t.get("cadence") or {}).get(field)
                    if v is not None:
                        val = max(val or 0.0, float(v))
                row[field] = val
            self.ring.note(row, now=now)
            regressions = self.ring.trend()
            for reg in regressions:
                self.regression_fires += 1
                self._m_regressions.inc()
                if self.tracer is not None:
                    self.tracer.record(
                        "regression-fire",
                        t_start=now,
                        dur_s=0.0,
                        field=reg["field"],
                        baseline=reg["baseline"],
                        now_mean=reg["now"],
                        ratio=reg["ratio"],
                        direction=reg["direction"],
                    )
                self._alert(
                    {
                        "schema": ALERT_SCHEMA,
                        "ts": float(now),
                        "event": "fire",
                        "slo": "sentinel-regression",
                        "instance": "sentinel",
                        "severity": "page",
                        "objective": None,
                        "burn": {},
                        "evidence": reg,
                    }
                )
        report = {
            "schema": SENTINEL_SCHEMA,
            "ts": float(now),
            "tick": self.ticks,
            "canary": canary,
            "drift": drift,
            "regressions": regressions,
            "counters": {
                "canary_flips": self.canary_flips,
                "drift_fires": self.drift_fires,
                "regression_fires": self.regression_fires,
            },
            "fleet": (
                {
                    "targets_up": sum(
                        1 for r in snapshot["targets"] if r["up"]
                    ),
                    "targets": len(snapshot["targets"]),
                    "slo_firing": sum(
                        1 for s in snapshot["slo"] if s["firing"]
                    ),
                }
                if snapshot
                else None
            ),
        }
        if self.tracer is not None:
            self.tracer.record(
                "sentinel-eval",
                t_start=now,
                dur_s=time.monotonic() - t0,
                tick=self.ticks,
                canary_incidents=(
                    len(canary["incidents"]) if canary else None
                ),
                drift_fired=bool(drift and drift["verdict"]),
                regressions=len(regressions),
            )
        return report

    # ---------------------------------------------------------------- render
    def render_status(self, report: dict) -> str:
        """The one-screen sentinel view (``fedtpu obs sentinel``)."""
        out = [
            f"fedtpu sentinel  tick {report['tick']}  "
            f"{time.strftime('%H:%M:%S', time.localtime(report['ts']))}"
        ]
        c = report.get("canary")
        if c is not None:
            state = "ok"
            if c["failures"]:
                state = "UNREACHABLE"
            elif c["mismatches"] or c["flips"]:
                state = "INCIDENT"
            out.append(
                f"  canary     {state:<12} {c['probes']} probe(s), "
                f"{c['mismatches']} mismatch(es), {c['flips']} flip(s), "
                f"p99 {c['latency_p99_ms']} ms, artifact "
                f"{(c['artifact'] or '?')[:12]} round {c['expected_round']}"
            )
        d = report.get("drift")
        if d is not None:
            err = d.get("window_error")
            out.append(
                f"  supervised {'DRIFT' if d['verdict'] else 'ok':<12} "
                f"{d['joined']} joined, window error "
                f"{'-' if err is None else f'{err:.4f}'}, "
                f"watermark {d['watermark']}, {d['fires']} fire(s)"
            )
        regs = report.get("regressions") or []
        if self.ring is not None:
            base = "pinned" if self.ring.baseline_pinned else "filling"
            out.append(
                f"  long-term  {'REGRESSION' if regs else 'ok':<12} "
                f"{len(self.ring.records)} ring row(s), baseline {base}"
            )
            for reg in regs:
                out.append(
                    f"    {reg['field']}: baseline {reg['baseline']} -> "
                    f"now {reg['now']} ({reg['direction']}, x{reg['ratio']})"
                )
        fleet = report.get("fleet")
        if fleet is not None:
            out.append(
                f"  fleet      {fleet['targets_up']}/{fleet['targets']} up, "
                f"{fleet['slo_firing']} SLO(s) firing"
            )
        ctr = report["counters"]
        out.append(
            f"  totals     canary {ctr['canary_flips']}, drift "
            f"{ctr['drift_fires']}, regression {ctr['regression_fires']}"
        )
        return "\n".join(out) + "\n"

    # ----------------------------------------------------------------- watch
    def watch(
        self,
        *,
        interval_s: float = 5.0,
        max_seconds: float | None = None,
        out=None,
        stop=None,
    ) -> int:
        """The daemon loop (the ScrapeHub.watch shape: deadline-bounded,
        stop-callable, KeyboardInterrupt = clean exit). Returns ticks."""
        import sys

        out = out or sys.stdout
        deadline = (
            time.monotonic() + float(max_seconds)
            if max_seconds is not None
            else None
        )
        n = 0
        try:
            while True:
                report = self.tick()
                frame = self.render_status(report)
                out.write("\x1b[2J\x1b[H" if out.isatty() else "")
                out.write(frame)
                out.flush()
                n += 1
                if stop is not None and stop():
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    break
                sleep_for = float(interval_s)
                if deadline is not None:
                    sleep_for = min(
                        sleep_for, max(deadline - time.monotonic(), 0.0)
                    )
                time.sleep(sleep_for)
        except KeyboardInterrupt:
            pass
        return n
