"""The serving router: one front port, N scorer replicas behind it.

One ``infer-serve`` process was the serving tier's last single-process
bottleneck (ROADMAP "Serving tier for millions of users"): every
connection, every tokenize, every dispatch rode one scorer thread, and a
promotion hot-reloaded the only replica in place. Offloading the
fan-in/fan-out path to a dedicated forwarding tier is the server-side
fix the Smart-NIC line of work argues for (arXiv:2307.06561) — here as a
software router: a thin TCP process that speaks the existing scoring
protocol on its front port and multiplexes requests across a fleet of
replica backends. Communication-side scaling is the recognized
production bottleneck (arXiv:2405.20431); the router is deliberately
model-free — it never tokenizes, never scores, never holds params — so
its per-request cost is two JSON id rewrites and two socket writes.

Design, in order of importance:

* **Per-request routing, least-in-flight.** Each request picks the
  healthy, non-draining replica with the fewest requests in flight (tie:
  lowest replica id). Client connections therefore multiplex onto shared
  backend connections, which forces the id remap: the router mints a
  backend-local id per forwarded request
  (:func:`~..serving.protocol.rewrite_id`), remembers
  ``(client writer, client id)``, and rewrites the matching reply back.
  Replies to one client connection may arrive out of order — the SDK's
  pipelined clients match by id, the synchronous client never has two
  outstanding.
* **Health probes via the stats frame.** A prober thread sends the
  in-band ``stats()`` probe (serving/protocol.py SCORE_STAT) on each
  replica's live connection every ``probe_interval_s``. In-band on
  purpose: the probe exercises the same socket, auth, and reader thread
  a real request rides, so "probe healthy" cannot diverge from
  "requests flow". A probe timeout, connect failure, or wire error
  **ejects** the replica: its pending requests are answered with
  explicit 503 rejects (shed, not hung — the admission-control
  contract), and the prober keeps dialing until the replica answers a
  probe again (**readmit**). The last probe's stats snapshot is kept
  per replica, so ``router.stats()`` reports each backend's model round
  — the rolling-reload observer reads fleet state from here.
* **Auth end-to-end.** With a key the router challenges every front
  connection exactly as a scoring server does, and answers every
  backend's challenge exactly as a scoring client does — the whole
  chain is authenticated with the one shared secret, and a keyless
  client meets the same refusal it would meet at a bare replica.
* **Drain/readmit for rolling reload.** ``drain(replica)`` removes a
  replica from the pick set without touching its in-flight requests;
  ``wait_drained`` blocks until they finish. The fleet manager
  (router/fleet.py) drains one replica at a time around each hot-swap,
  which is what makes a promotion a zero-drop event.

Threads: one accept loop, one reader per client connection, one reader
per replica connection, one prober, plus the per-connection writer
threads the serving tier already uses (``_ConnWriter`` — the scorer/
router never blocks on a slow client's socket). All shared state is
lock-guarded per the PR-8 concurrency rule; the per-replica lock also
serializes backend frame writes (interleaved ``sendall`` chunks from two
threads would corrupt the stream).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Sequence

from ..comm import framing
from ..comm.wire import NONCE_LEN, NONCE_MAGIC, WireError
from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..serving import protocol
from ..serving.client import _set_nodelay, answer_auth_challenge
from ..serving.server import MAX_REQUEST_FRAME, _ConnWriter
from ..utils.logging import get_logger

log = get_logger()


class _Pending:
    """One forwarded request awaiting its backend reply. ``writer`` is
    None for the router's own control traffic (health probes, reload
    frames). ``mirror_id`` links a shadow-mirrored request (shadow/
    mirror.py) to its pair key, so the serving-side probability can be
    handed to the comparator when the reply flows back."""

    __slots__ = ("writer", "client_id", "t_sent", "mirror_id")

    def __init__(
        self,
        writer,
        client_id: int,
        t_sent: float,
        mirror_id: int | None = None,
    ):
        self.writer = writer
        self.client_id = client_id
        self.t_sent = t_sent
        self.mirror_id = mirror_id


class Replica:
    """Router-side state for one backend scorer.

    ``lock`` guards every mutable field AND serializes frame writes on
    ``sock`` — a single lock per replica keeps the acquisition graph
    trivially acyclic (the runtime lock-order detector is armed across
    the fast lane)."""

    def __init__(self, host: str, port: int, replica_id: int):
        self.host = host
        self.port = int(port)
        self.replica_id = int(replica_id)
        self.lock = threading.Lock()
        self.sock: socket.socket | None = None
        self.healthy = False
        self.draining = False
        self.inflight = 0
        self.pending: dict[int, _Pending] = {}
        self.next_id = 0
        self.ejects = 0
        self.last_stats: dict | None = None
        self.probe_id: int | None = None
        self.probe_sent_t = 0.0
        # In-flight SCORE_RELOAD choreography (reload_replica): the
        # pending control frame's id, its parsed reply, and the event the
        # coordinating caller waits on. All guarded by ``lock``.
        self.reload_id: int | None = None
        self.reload_reply: dict | None = None
        self.reload_evt: threading.Event | None = None

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"


class ScoringRouter:
    """Thin TCP router over ``backends`` (a list of (host, port))."""

    def __init__(
        self,
        backends: Sequence[tuple[str, int]],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        auth_key: bytes | None = None,
        probe_interval_s: float = 1.0,
        probe_timeout_s: float = 5.0,
        connect_timeout_s: float = 5.0,
        max_inflight_per_replica: int = 1024,
        tracer=None,
        trace_sample: float = 1.0,
        eject_storm_n: int = 3,
        eject_storm_window_s: float = 60.0,
    ):
        if not backends:
            raise ValueError("router needs at least one backend")
        if not 0.0 < float(trace_sample) <= 1.0:
            raise ValueError(
                f"trace_sample={trace_sample} must be in (0, 1]"
            )
        self.replicas = [
            Replica(h, p, i) for i, (h, p) in enumerate(backends)
        ]
        self.auth_key = auth_key
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.max_inflight_per_replica = int(max_inflight_per_replica)
        self.tracer = tracer
        # router-forward span sampling, the serve-batch pattern: one span
        # per ``stride`` forwarded replies via the counter — deterministic,
        # and the events-JSONL stays bounded on a hot router.
        self._trace_stride = max(1, round(1.0 / float(trace_sample)))
        self._closed = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._conn_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        # Shadow mirror hook (shadow/mirror.py): when armed, a counter-
        # strided sample of live requests is duplicated onto the shadow
        # backend (fire-and-forget — admit() never blocks) and the
        # matching serving replies are handed to the comparator. None =
        # the literal pre-shadow forward path.
        self._mirror_lock = threading.Lock()
        self._mirror = None
        self._forwarded = 0
        self._rejects = {"no_replica": 0, "replica_lost": 0, "auth": 0}
        # Eject-storm detection (obs/flight.py): N ejects across the
        # fleet inside the window dumps ONE postmortem bundle — a dying
        # backend host shows up as a burst of ejects long before any
        # operator reads the counters.
        self._eject_storm_n = max(1, int(eject_storm_n))
        self._eject_storm_window_s = float(eject_storm_window_s)
        self._eject_times: list[float] = []
        m = obs_metrics.default_registry()
        self._m_forwarded = m.counter(
            "fedtpu_router_forwarded_total",
            help="scoring requests forwarded to a replica",
        )
        self._m_rejects = {
            kind: m.counter(
                "fedtpu_router_rejects_total",
                help="router-issued explicit rejects by kind",
                labels={"kind": kind},
            )
            for kind in self._rejects
        }
        self._g_inflight = {
            rep.replica_id: m.gauge(
                "fedtpu_router_inflight",
                help="requests in flight per replica",
                labels={"replica": str(rep.replica_id)},
            )
            for rep in self.replicas
        }
        self._m_ejects = {
            rep.replica_id: m.counter(
                "fedtpu_router_ejects_total",
                help="replica ejections (probe/connection failure)",
                labels={"replica": str(rep.replica_id)},
            )
            for rep in self.replicas
        }
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]

    # --------------------------------------------------------------- control
    def start(self) -> "ScoringRouter":
        # Dial every backend before accepting traffic: the first request
        # must find a pick set, not race the prober's first pass.
        for rep in self.replicas:
            self._try_connect(rep)
        self._sock.listen(128)
        for target, name in (
            (self._accept_loop, "accept"),
            (self._probe_loop, "prober"),
        ):
            t = threading.Thread(
                target=target, name=f"fedtpu-router-{name}", daemon=True
            )
            t.start()
            self._threads.append(t)
        log.info(
            f"[ROUTER] routing on port {self.port} over "
            f"{len(self.replicas)} replica(s) "
            f"({sum(r.healthy for r in self.replicas)} up), auth "
            f"{'on' if self.auth_key else 'off'}"
        )
        return self

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for rep in self.replicas:
            with rep.lock:
                sock, rep.sock = rep.sock, None
                rep.healthy = False
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        for t in self._threads:
            t.join(timeout=5.0)
        s = self.stats()
        log.info(
            f"[ROUTER] forwarded {s['forwarded']} request(s), rejects "
            f"{s['rejects']}, ejects "
            f"{sum(b['ejects'] for b in s['backends'])}"
        )

    def __enter__(self) -> "ScoringRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._stats_lock:
            forwarded = self._forwarded
            rejects = dict(self._rejects)
        backends = []
        for rep in self.replicas:
            with rep.lock:
                last = rep.last_stats or {}
                backends.append(
                    {
                        "replica": rep.replica_id,
                        "addr": rep.addr,
                        "healthy": rep.healthy,
                        "draining": rep.draining,
                        "inflight": rep.inflight,
                        "ejects": rep.ejects,
                        "round": last.get("round"),
                        "scored": last.get("scored"),
                    }
                )
        return {
            "kind": "router",
            "forwarded": forwarded,
            "rejects": rejects,
            "rejects_total": sum(rejects.values()),
            "backends": backends,
            "healthy": sum(1 for b in backends if b["healthy"]),
        }

    # -------------------------------------------------------- shadow mirror
    def set_mirror(self, mirror) -> None:
        """Arm (or, with None, disarm) the shadow-traffic mirror. The
        mirror object's contract (shadow/mirror.py ShadowMirror):
        ``admit(frame) -> mirror_id | None`` (O(1), never blocks),
        ``note_serving_reply(mirror_id, frame)``, ``abandon(mirror_id)``."""
        with self._mirror_lock:
            self._mirror = mirror

    def _get_mirror(self):
        with self._mirror_lock:
            return self._mirror

    # -------------------------------------------------------- drain control
    def drain(self, replica_id: int) -> None:
        """Remove a replica from the pick set (in-flight requests keep
        running — ``wait_drained`` waits them out)."""
        rep = self.replicas[replica_id]
        with rep.lock:
            rep.draining = True

    def undrain(self, replica_id: int) -> None:
        rep = self.replicas[replica_id]
        with rep.lock:
            rep.draining = False

    def wait_drained(self, replica_id: int, timeout: float = 30.0) -> bool:
        """True once the replica's in-flight count hits zero (poll; the
        counts move on reply/eject, both of which are prompt)."""
        rep = self.replicas[replica_id]
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            with rep.lock:
                if rep.inflight == 0:
                    return True
            time.sleep(0.005)
        with rep.lock:
            return rep.inflight == 0

    # ------------------------------------------- out-of-process reload
    def reload_replica(
        self,
        replica_id: int,
        *,
        timeout_s: float = 60.0,
        drain: bool = True,
        drain_timeout_s: float = 30.0,
    ) -> dict | None:
        """Drain-then-reload-now for ONE backend the router cannot
        hot-swap directly (a subprocess/remote ``infer-serve`` replica):
        remove it from the pick set, wait out its in-flight requests,
        send the SCORE_RELOAD control frame on the same authenticated
        backend connection, and readmit once the replica answers that
        its adoption attempt finished. Returns the parsed reload reply
        (``{"reloaded": bool, "round": int}``) or None when the replica
        was unreachable / never answered — the caller decides whether a
        missing reply fails the sweep."""
        rep = self.replicas[replica_id]
        drained = True
        if drain:
            self.drain(replica_id)
            drained = self.wait_drained(
                replica_id, timeout=drain_timeout_s
            )
            if not drained:
                log.warning(
                    f"[ROUTER] replica {replica_id} did not drain within "
                    f"{drain_timeout_s}s; sending reload anyway (its "
                    "in-flight batches finish on the old weights)"
                )
        try:
            eject_sock = None
            with rep.lock:
                if rep.sock is None or rep.reload_id is not None:
                    return None
                rep.next_id += 1
                bid = rep.next_id
                rep.pending[bid] = _Pending(None, 0, time.monotonic())
                rep.reload_id = bid
                rep.reload_reply = None
                rep.reload_evt = evt = threading.Event()
                try:
                    framing.send_frame(
                        rep.sock,
                        protocol.build_reload_request(bid),
                        await_ack=False,
                    )
                except (OSError, ConnectionError):
                    rep.pending.pop(bid, None)
                    rep.reload_id = None
                    rep.reload_evt = None
                    eject_sock = rep.sock
            if eject_sock is not None:
                self._eject(rep, eject_sock, "reload send failed")
                return None
            if not evt.wait(timeout_s):
                with rep.lock:
                    rep.pending.pop(bid, None)
                    if rep.reload_id == bid:
                        rep.reload_id = None
                        rep.reload_evt = None
                log.warning(
                    f"[ROUTER] replica {replica_id} did not answer the "
                    f"reload frame within {timeout_s}s"
                )
                return None
            with rep.lock:
                return rep.reload_reply
        finally:
            if drain:
                self.undrain(replica_id)

    def rolling_remote_reload(
        self,
        *,
        drain_timeout_s: float = 30.0,
        reload_timeout_s: float = 120.0,
    ) -> dict:
        """The out-of-process rolling sweep: drain -> SCORE_RELOAD ->
        readmit, one replica at a time, so N-1 replicas keep serving
        while each one reloads — the same zero-drop choreography
        ServingFleet.rolling_reload runs for in-process replicas, for
        backends that live in their own processes/hosts. Single-replica
        deployments skip the drain (draining the whole pick set would
        CAUSE the drops). Returns per-replica outcomes."""
        sweep: list[dict] = []
        solo = len(self.replicas) == 1
        for rep in self.replicas:
            t_unix = time.time()
            t0 = time.monotonic()
            reply = self.reload_replica(
                rep.replica_id,
                timeout_s=reload_timeout_s,
                drain=not solo,
                drain_timeout_s=drain_timeout_s,
            )
            dur = time.monotonic() - t0
            out = {
                "replica": rep.replica_id,
                "answered": reply is not None,
                "reloaded": bool(reply and reply.get("reloaded")),
                "round": reply.get("round") if reply else None,
                "swap_s": dur,
            }
            sweep.append(out)
            if self.tracer is not None:
                self.tracer.record(
                    "replica-drain",
                    t_start=t_unix,
                    dur_s=dur,
                    round=out["round"],
                    replica=rep.replica_id,
                    drained=out["answered"],
                    remote=True,
                )
            log.info(
                f"[ROUTER] replica {rep.replica_id} reload "
                f"{'answered' if out['answered'] else 'UNANSWERED'} "
                f"(reloaded={out['reloaded']}, round {out['round']}) in "
                f"{dur:.3f}s"
            )
        return {"replicas": sweep}

    # ------------------------------------------------------------ accept path
    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            _set_nodelay(conn)
            with self._conn_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._client_loop, args=(conn,), daemon=True
            ).start()

    def _auth_front(self, conn: socket.socket) -> bool:
        """The scoring server's challenge-response, verbatim semantics:
        nonce out, keyed proof back, constant-time check."""
        import os as _os

        nonce = _os.urandom(NONCE_LEN)
        try:
            conn.settimeout(10.0)
            framing.send_frame(conn, NONCE_MAGIC + nonce, await_ack=False)
            proof = framing.recv_frame(
                conn, send_ack=False, max_frame=MAX_REQUEST_FRAME
            )
            conn.settimeout(None)
        except (OSError, ConnectionError, WireError) as e:
            self._count_reject("auth")
            log.warning(f"[ROUTER] auth handshake failed: {e}")
            return False
        if not protocol.check_auth_response(proof, self.auth_key, nonce):
            self._count_reject("auth")
            log.warning(
                "[ROUTER] dropping connection: bad or missing auth proof"
            )
            return False
        return True

    def _client_loop(self, conn: socket.socket) -> None:
        if self.auth_key is not None and not self._auth_front(conn):
            with self._conn_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
            return
        writer = _ConnWriter(conn)
        try:
            while not self._closed.is_set():
                try:
                    frame = framing.recv_frame(
                        conn, send_ack=False, max_frame=MAX_REQUEST_FRAME
                    )
                except (ConnectionError, OSError):
                    return
                except WireError as e:
                    log.warning(f"[ROUTER] dropping connection: {e}")
                    return
                fb = bytes(frame)
                if protocol.is_stats_request(fb):
                    # The router answers stats probes itself — its own
                    # aggregate view; per-replica stats ride inside it.
                    try:
                        body = protocol.parse_stats_request(fb)
                    except WireError as e:
                        log.warning(f"[ROUTER] dropping connection: {e}")
                        return
                    writer.send(
                        protocol.build_stats_reply(body["id"], self.stats())
                    )
                    continue
                # Hot path: magic sniff + id only. Full body validation
                # is the replica's job — it answers a malformed body
                # with a 400 reject carrying this id, which flows back
                # through the ordinary reply path; the router parsing
                # every request twice would halve the tier's headroom.
                if not protocol.is_request(fb):
                    log.warning(
                        "[ROUTER] dropping connection: not a scoring "
                        f"request frame ({fb[:4]!r})"
                    )
                    return
                try:
                    req_id = protocol.frame_id(fb)
                except WireError as e:
                    log.warning(f"[ROUTER] dropping connection: {e}")
                    return
                # Shadow mirroring (shadow/mirror.py): a deterministic
                # counter-strided sample of live requests is duplicated
                # onto the shadow backend. admit() is an O(1) enqueue
                # that NEVER blocks or fails the serving path — a full
                # mirror queue drops the COPY, the live request proceeds
                # untouched.
                mirror = self._get_mirror()
                mid = mirror.admit(fb) if mirror is not None else None
                # One failover retry: the pick can race an eject (the
                # send discovers the dead socket first) — a second pick
                # excludes the replica the first attempt marked down.
                sent = False
                for _attempt in range(2):
                    rep = self._pick()
                    if rep is None:
                        break
                    if self._forward(
                        rep, fb, req_id, writer, mirror_id=mid
                    ):
                        sent = True
                        break
                if not sent:
                    if mid is not None and mirror is not None:
                        mirror.abandon(mid)
                    kind = (
                        "no_replica" if self._pick() is None
                        else "replica_lost"
                    )
                    self._count_reject(kind)
                    writer.send(
                        protocol.build_reject(
                            req_id,
                            code=protocol.REJECT_OVERLOADED,
                            reason="no healthy replica available",
                        )
                    )
        finally:
            with self._conn_lock:
                self._conns.discard(conn)
            writer.close()
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------------- forwarding
    def _pick(self) -> Replica | None:
        """Least-in-flight healthy, non-draining replica (tie: lowest
        id — deterministic, so tests can pin the spread)."""
        best: Replica | None = None
        best_load = None
        for rep in self.replicas:
            with rep.lock:
                if (
                    not rep.healthy
                    or rep.draining
                    or rep.sock is None
                    or rep.inflight >= self.max_inflight_per_replica
                ):
                    continue
                load = rep.inflight
            if best_load is None or load < best_load:
                best, best_load = rep, load
        return best

    def _forward(
        self,
        rep: Replica,
        frame: bytes,
        client_id: int,
        writer,
        mirror_id: int | None = None,
    ) -> bool:
        """Rewrite + send one request to ``rep``; False = the replica
        went away under us (caller retries elsewhere)."""
        eject_sock = None
        with rep.lock:
            if not rep.healthy or rep.sock is None:
                return False
            rep.next_id += 1
            bid = rep.next_id
            out = protocol.rewrite_id(frame, bid)
            rep.pending[bid] = _Pending(
                writer, client_id, time.monotonic(), mirror_id
            )
            rep.inflight += 1
            inflight = rep.inflight
            try:
                framing.send_frame(rep.sock, out, await_ack=False)
            except (OSError, ConnectionError):
                rep.pending.pop(bid, None)
                rep.inflight -= 1
                inflight = rep.inflight
                eject_sock = rep.sock
        self._g_inflight[rep.replica_id].set(inflight)
        if eject_sock is not None:
            self._eject(rep, eject_sock, "send failed")
            return False
        return True

    def _replica_loop(self, rep: Replica, sock: socket.socket) -> None:
        """Reader for one backend connection: match replies to pending
        requests by id, rewrite back, hand to the client's writer."""
        while not self._closed.is_set():
            try:
                frame = bytes(
                    framing.recv_frame(
                        sock, send_ack=False, max_frame=MAX_REQUEST_FRAME
                    )
                )
                bid = protocol.frame_id(frame)
            except (OSError, ConnectionError, WireError) as e:
                self._eject(rep, sock, f"connection lost ({e})")
                return
            reload_evt = None
            with rep.lock:
                pend = rep.pending.pop(bid, None)
                if pend is not None and pend.writer is not None:
                    rep.inflight -= 1
                inflight = rep.inflight
                if pend is not None and pend.writer is None:
                    if rep.reload_id is not None and bid == rep.reload_id:
                        # SCORE_RELOAD answered: the adoption attempt on
                        # the replica finished — wake the coordinator.
                        rep.reload_id = None
                        try:
                            rep.reload_reply = protocol.parse_reload_reply(
                                frame
                            )
                        except WireError:
                            rep.reload_reply = None
                        reload_evt = rep.reload_evt
                    else:
                        # Probe result: adopt the stats snapshot; a
                        # healthy answer is also the readmit signal
                        # after an eject.
                        rep.probe_id = None
                        if protocol.is_stats_reply(frame):
                            try:
                                rep.last_stats = protocol.parse_stats_reply(
                                    frame
                                )["stats"]
                            except WireError:
                                rep.last_stats = None
                        rep.healthy = True
            if reload_evt is not None:
                reload_evt.set()
            if pend is None or pend.writer is None:
                continue
            if pend.mirror_id is not None:
                # The mirrored request's serving-side reply: hand the
                # probability to the comparator (outside rep.lock — the
                # mirror takes its own locks). A reject abandons the pair.
                mirror = self._get_mirror()
                if mirror is not None:
                    mirror.note_serving_reply(pend.mirror_id, frame)
            self._g_inflight[rep.replica_id].set(inflight)
            pend.writer.send(protocol.rewrite_id(frame, pend.client_id))
            self._m_forwarded.inc()
            with self._stats_lock:
                self._forwarded += 1
                n_fwd = self._forwarded
            if self.tracer is not None and (
                (n_fwd - 1) % self._trace_stride == 0
            ):
                dur = time.monotonic() - pend.t_sent
                self.tracer.record(
                    "router-forward",
                    t_start=time.time() - dur,
                    dur_s=dur,
                    replica=rep.replica_id,
                    inflight=inflight,
                    sampled_requests=(
                        self._trace_stride
                        if self._trace_stride > 1
                        else None
                    ),
                )

    # ------------------------------------------------------------ health path
    def _try_connect(self, rep: Replica) -> bool:
        """Dial + (auth +) first probe. The replica joins the pick set
        immediately on a successful handshake; the probe reply then
        refreshes its stats snapshot."""
        try:
            sock = socket.create_connection(
                (rep.host, rep.port), timeout=self.connect_timeout_s
            )
            sock.settimeout(None)
            _set_nodelay(sock)
            if self.auth_key is not None:
                sock.settimeout(self.connect_timeout_s)
                answer_auth_challenge(sock, self.auth_key)
                sock.settimeout(None)
        except (OSError, ConnectionError, WireError) as e:
            log.debug(f"[ROUTER] replica {rep.replica_id} dial failed: {e}")
            return False
        was_down = False
        with rep.lock:
            rep.sock = sock
            was_down = not rep.healthy
            rep.healthy = True
            rep.pending.clear()
            rep.inflight = 0
            rep.probe_id = None
        threading.Thread(
            target=self._replica_loop, args=(rep, sock), daemon=True
        ).start()
        self._send_probe(rep)
        if was_down:
            log.info(
                f"[ROUTER] replica {rep.replica_id} ({rep.addr}) readmitted"
            )
        return True

    def _send_probe(self, rep: Replica) -> None:
        eject_sock = None
        with rep.lock:
            if rep.sock is None:
                return
            if rep.probe_id is not None:
                # Previous probe still unanswered; the prober's timeout
                # check decides its fate, not a second probe.
                return
            rep.next_id += 1
            bid = rep.next_id
            rep.pending[bid] = _Pending(None, 0, time.monotonic())
            rep.probe_id = bid
            rep.probe_sent_t = time.monotonic()
            try:
                framing.send_frame(
                    rep.sock,
                    protocol.build_stats_request(bid),
                    await_ack=False,
                )
            except (OSError, ConnectionError):
                eject_sock = rep.sock
        if eject_sock is not None:
            self._eject(rep, eject_sock, "probe send failed")

    def _probe_loop(self) -> None:
        while not self._closed.wait(self.probe_interval_s):
            for rep in self.replicas:
                with rep.lock:
                    sock = rep.sock
                    stale = (
                        rep.probe_id is not None
                        and time.monotonic() - rep.probe_sent_t
                        > self.probe_timeout_s
                    )
                if sock is None:
                    self._try_connect(rep)
                elif stale:
                    self._eject(rep, sock, "probe timeout")
                else:
                    self._send_probe(rep)

    def _eject(self, rep: Replica, sock: socket.socket, reason: str) -> None:
        """Take a replica out of service: fail its pending requests with
        explicit rejects, close the connection, count the eject. The
        prober's next pass starts the readmit dial. ``sock`` pins WHICH
        connection died — a racing eject from the reader and the prober
        must not double-count or tear down a fresh reconnect."""
        with rep.lock:
            if rep.sock is not sock:
                return  # stale: already ejected / reconnected
            rep.sock = None
            rep.healthy = False
            rep.probe_id = None
            dropped = [p for p in rep.pending.values() if p.writer is not None]
            rep.pending.clear()
            rep.inflight = 0
            rep.ejects += 1
            # A reload coordinator waiting on this connection must wake
            # now (its reply can never arrive) instead of its timeout.
            rep.reload_id = None
            reload_evt, rep.reload_evt = rep.reload_evt, None
        if reload_evt is not None:
            reload_evt.set()
        try:
            sock.close()
        except OSError:
            pass
        self._m_ejects[rep.replica_id].inc()
        self._g_inflight[rep.replica_id].set(0)
        mirror = self._get_mirror()
        for pend in dropped:
            if pend.mirror_id is not None and mirror is not None:
                mirror.abandon(pend.mirror_id)
            self._count_reject("replica_lost")
            pend.writer.send(
                protocol.build_reject(
                    pend.client_id,
                    code=protocol.REJECT_OVERLOADED,
                    reason=f"replica {rep.replica_id} ejected: {reason}",
                )
            )
        log.warning(
            f"[ROUTER] ejected replica {rep.replica_id} ({rep.addr}): "
            f"{reason}; {len(dropped)} in-flight request(s) shed"
        )
        now = time.monotonic()
        with self._stats_lock:
            self._eject_times.append(now)
            cutoff = now - self._eject_storm_window_s
            self._eject_times = [t for t in self._eject_times if t >= cutoff]
            in_window = len(self._eject_times)
            storm = in_window >= self._eject_storm_n
        if storm:
            recorder = obs_flight.get_global_recorder()
            if recorder is not None:
                try:
                    recorder.maybe_dump(
                        "eject-storm",
                        extra={
                            "ejects_in_window": in_window,
                            "window_s": self._eject_storm_window_s,
                            "replica": rep.replica_id,
                            "reason": reason,
                        },
                    )
                except OSError as e:
                    log.warning(
                        f"[ROUTER] postmortem dump failed (non-fatal): {e}"
                    )

    def _count_reject(self, kind: str) -> None:
        with self._stats_lock:
            self._rejects[kind] += 1
        self._m_rejects[kind].inc()
