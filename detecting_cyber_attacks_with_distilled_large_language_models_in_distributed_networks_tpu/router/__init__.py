"""Serving replica fleet: a thin router over N ``infer-serve`` scorers.

The serving tier's scale-out layer (ROADMAP "Serving tier for millions
of users"): ``fedtpu route`` runs the model-free TCP router
(:mod:`.core` — least-in-flight pick, in-band stats health probes,
eject/readmit, end-to-end HMAC), and ``fedtpu fleet`` composes N local
replicas behind it with registry-following **rolling hot-reload**
(:mod:`.fleet` — drain one replica at a time around each promotion, so
the serving pointer moves without dropping a single request).
"""

from .core import Replica, ScoringRouter
from .fleet import FleetReplica, ServingFleet

__all__ = [
    "FleetReplica",
    "Replica",
    "ScoringRouter",
    "ServingFleet",
]
