"""The replica fleet: N local scorers + router + rolling hot-reload.

``fedtpu fleet`` composes what already exists — :class:`~..serving.
server.ScoringServer` replicas (each with its own bucketed engine) and
the :class:`~.core.ScoringRouter` in front — and adds the one genuinely
new behavior: **rolling reload**. The single-replica tiers swap params
in place (atomic under the engine lock, fine for a same-architecture
swap); a fleet can do strictly better: take ONE replica out of the pick
set, wait out its in-flight requests, swap it, readmit it, move to the
next. During the whole sweep N-1 replicas keep serving, so a promotion
— however slow the params load — is a zero-drop event, which is the
property the bench pins (``router_rolling_reload_dropped == 0``).

The manager follows the registry's serving pointer exactly like
serving/reload.RegistryWatcher, with the fleet-shaped differences: ONE
poll for the whole fleet (N replicas polling independently would reload
in an uncoordinated burst, the opposite of rolling), the architecture
guard runs once against the shared engine config, and every completed
per-replica swap is recorded back into the registry's events trail
(:meth:`~..registry.store.ModelRegistry.record_reload`) — the audit
answer to "which replica is serving which artifact right now".

Each drain→swap→readmit cycle emits a ``replica-drain`` span (obs
vocabulary), so the obs timeline shows promotion cost per replica next
to round compute and the eval gate.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

from ..serving import MicroBatcher, ScoreEngine, ScoringServer
from ..utils.logging import get_logger
from .core import ScoringRouter

log = get_logger()


class FleetReplica:
    """One in-process serving replica: engine + scoring server on its
    own loopback port. ``adopt()`` is the hot-swap target the rolling
    reload drives (same-architecture params only — the fleet manager
    guards architecture before the sweep starts)."""

    def __init__(
        self,
        replica_id: int,
        model_cfg,
        params,
        tok,
        *,
        spec=None,
        round_id: int = 0,
        host: str = "127.0.0.1",
        buckets: tuple[int, ...] = (1, 8, 32),
        max_queue: int = 256,
        gather_window_s: float = 0.002,
        threshold: float = 0.5,
        auth_key: bytes | None = None,
        warmup: bool = True,
        idle_tick_s: float = 0.02,
        tracer=None,
        trace_sample: float = 1.0,
        mesh=None,
    ):
        self.replica_id = int(replica_id)
        # ``mesh``: an FSDP host mesh makes this a SHARDED replica —
        # params at rest split per-leaf across the mesh's chips, gathered
        # at use inside each warm bucket program. adopt() (the rolling-
        # reload swap target) re-places onto the same shape-deterministic
        # layout, so a mid-traffic drain→swap never retraces a bucket.
        self.engine = ScoreEngine(
            model_cfg,
            params,
            pad_id=tok.pad_id,
            buckets=buckets,
            round_id=round_id,
            mesh=mesh,
        )
        self.server = ScoringServer(
            self.engine,
            tok,
            host=host,
            port=0,
            spec=spec,
            threshold=threshold,
            batcher=MicroBatcher(
                max_batch=buckets[-1],
                max_queue=max(max_queue, buckets[-1]),
                gather_window_s=gather_window_s,
            ),
            auth_key=auth_key,
            warmup=warmup,
            idle_tick_s=idle_tick_s,
            tracer=tracer,
            trace_sample=trace_sample,
            replica_id=replica_id,
        )
        self.host = host

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def round_id(self) -> int:
        return self.engine.round_id

    def adopt(self, params, *, round_id: int) -> None:
        """Atomic same-architecture hot-swap (engine lock)."""
        self.engine.swap(params, round_id=round_id)

    def start(self) -> "FleetReplica":
        self.server.start()
        return self

    def close(self) -> None:
        self.server.close()


class ServingFleet:
    """Replicas + router + (optionally) the pointer-following rolling-
    reload manager.

    ``registry``: a :class:`~..registry.store.ModelRegistry` to follow —
    the manager thread polls its serving pointer every
    ``reload_poll_s`` and answers a pointer move with one rolling
    sweep. None = no manager; :meth:`rolling_reload` can still be driven
    directly (tests, manual ops).

    **Shadow plane** (shadow/): with ``shadow_factory`` set and
    ``shadow_sample >= 1``, the same manager poll also follows the
    registry's SHADOW pointer — an artifact promoted to the ``shadow``
    state gets its own replica (spun from ``shadow_factory``, NEVER in
    the router's pick set), the router's traffic mirror is armed at the
    configured stride, and the comparator publishes paired records +
    an atomic status file under ``<registry>/shadow/`` for the
    controller's disagreement gate. When the artifact leaves the shadow
    state (promoted or rejected) the mirror disarms and the shadow
    replica is torn down.
    """

    def __init__(
        self,
        replicas: list[FleetReplica],
        *,
        registry=None,
        auth_key: bytes | None = None,
        router_host: str = "127.0.0.1",
        router_port: int = 0,
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 5.0,
        drain_timeout_s: float = 30.0,
        reload_poll_s: float = 2.0,
        max_inflight_per_replica: int = 1024,
        tracer=None,
        trace_sample: float = 1.0,
        shadow_factory=None,
        shadow_sample: int = 0,
        shadow_threshold: float = 0.5,
        shadow_bins: int = 10,
        shadow_queue: int = 256,
    ):
        if not replicas:
            raise ValueError("fleet needs at least one replica")
        if shadow_sample < 0:
            raise ValueError(
                f"shadow_sample={shadow_sample} must be >= 0 (0 = off)"
            )
        self.replicas = replicas
        self.registry = registry
        self.drain_timeout_s = float(drain_timeout_s)
        self.reload_poll_s = float(reload_poll_s)
        self.tracer = tracer
        self.auth_key = auth_key
        # Shadow plane state (all guarded by _lock; the manager thread
        # owns the lifecycle, stats() reads).
        self.shadow_factory = shadow_factory
        self.shadow_sample = int(shadow_sample)
        self.shadow_threshold = float(shadow_threshold)
        self.shadow_bins = int(shadow_bins)
        self.shadow_queue = int(shadow_queue)
        self._shadow_aid: str | None = None
        self._shadow_replica = None
        self._shadow_mirror = None
        self._shadow_compare = None
        self._shadow_warned: str | None = None
        # Spin-up failure backoff: a corrupt artifact or failing factory
        # must not cost a full params load + engine build every poll.
        self._shadow_retry_at = 0.0
        self.router = ScoringRouter(
            [(r.host, r.port) for r in replicas],
            host=router_host,
            port=router_port,
            auth_key=auth_key,
            probe_interval_s=probe_interval_s,
            probe_timeout_s=probe_timeout_s,
            max_inflight_per_replica=max_inflight_per_replica,
            tracer=tracer,
            trace_sample=trace_sample,
        )
        self.port = self.router.port
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._manager: threading.Thread | None = None
        self._seen: str | None = None
        self._warned: str | None = None
        self.reloads = 0  # completed rolling sweeps
        self.serving_artifact: str | None = None

    # --------------------------------------------------------------- control
    def start(self) -> "ServingFleet":
        self.router.start()
        if self.registry is not None:
            info = self.registry.serving_info()
            # Prime on the artifact the replicas were BUILT from (the
            # caller restored the current pointer); a promotion that
            # lands between restore and here is caught by the first poll.
            with self._lock:
                self._seen = info["artifact"] if info else None
                self.serving_artifact = self._seen
            self._manager = threading.Thread(
                target=self._manager_loop,
                name="fedtpu-fleet-manager",
                daemon=True,
            )
            self._manager.start()
        log.info(
            f"[FLEET] {len(self.replicas)} replica(s) behind router port "
            f"{self.port}"
            + (
                f", following registry pointer ({self._seen})"
                if self.registry is not None
                else ""
            )
        )
        return self

    def close(self) -> None:
        self._closed.set()
        if self._manager is not None:
            self._manager.join(timeout=10.0)
        self._teardown_shadow()
        self.router.close()
        for rep in self.replicas:
            rep.close()

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            reloads = self.reloads
            artifact = self.serving_artifact
            shadow_aid = self._shadow_aid
            mirror = self._shadow_mirror
        return {
            **self.router.stats(),
            "reloads": reloads,
            "serving_artifact": artifact,
            "replica_rounds": [r.round_id for r in self.replicas],
            "shadow_artifact": shadow_aid,
            "shadow_mirror": mirror.stats() if mirror is not None else None,
        }

    # ------------------------------------------------------- rolling reload
    def rolling_reload(
        self, params, *, round_id: int, artifact: str | None = None
    ) -> dict:
        """Drain → swap → readmit, one replica at a time. Never drains
        the only pick-set member to zero on a single-replica fleet (the
        swap is atomic anyway — draining the whole pick set would CAUSE
        the drops rolling reload exists to prevent). Returns per-replica
        timings for the caller's logs/bench."""
        sweep: list[dict] = []
        solo = len(self.replicas) == 1
        for rep in self.replicas:
            t_unix = time.time()
            t0 = time.monotonic()
            drained = True
            if not solo:
                self.router.drain(rep.replica_id)
                drained = self.router.wait_drained(
                    rep.replica_id, timeout=self.drain_timeout_s
                )
                if not drained:
                    log.warning(
                        f"[FLEET] replica {rep.replica_id} did not drain "
                        f"within {self.drain_timeout_s}s; swapping anyway "
                        "(in-flight batches finish on the old weights)"
                    )
            rep.adopt(params, round_id=round_id)
            if not solo:
                self.router.undrain(rep.replica_id)
            dur = time.monotonic() - t0
            sweep.append(
                {
                    "replica": rep.replica_id,
                    "drained": drained,
                    "swap_s": dur,
                }
            )
            if self.tracer is not None:
                self.tracer.record(
                    "replica-drain",
                    t_start=t_unix,
                    dur_s=dur,
                    round=round_id,
                    replica=rep.replica_id,
                    artifact=artifact,
                    drained=drained,
                )
            if self.registry is not None and artifact is not None:
                self.registry.record_reload(
                    artifact, consumer=f"replica-{rep.replica_id}"
                )
            log.info(
                f"[FLEET] replica {rep.replica_id} -> round {round_id}"
                + (f" ({artifact})" if artifact else "")
                + f" in {dur:.3f}s (drained={drained})"
            )
        with self._lock:
            self.reloads += 1
            self.serving_artifact = artifact
        return {"replicas": sweep, "round": round_id, "artifact": artifact}

    # ----------------------------------------------------- the shadow plane
    def shadow_enabled(self) -> bool:
        return self.shadow_factory is not None and self.shadow_sample >= 1

    def _teardown_shadow(self) -> None:
        """Disarm the mirror FIRST (the router's forward path must stop
        touching it before it dies), publish the final status, then
        close the shadow replica."""
        with self._lock:
            aid = self._shadow_aid
            mirror, self._shadow_mirror = self._shadow_mirror, None
            compare, self._shadow_compare = self._shadow_compare, None
            replica, self._shadow_replica = self._shadow_replica, None
            self._shadow_aid = None
        if aid is None:
            return
        self.router.set_mirror(None)
        if mirror is not None:
            mirror.close()
        if compare is not None:
            compare.write_status()
        if replica is not None:
            try:
                replica.close()
            except Exception as e:
                log.warning(
                    f"[FLEET] shadow replica close failed (non-fatal): {e}"
                )
        log.info(f"[FLEET] shadow plane for {aid} torn down")

    def _poll_shadow(self) -> None:
        """One manager pass over the registry's SHADOW pointer: arm the
        plane when an artifact enters the shadow state, tear it down
        when it leaves. Any failure degrades to no-shadow — the live
        fleet must never die for its shadow."""
        if not self.shadow_enabled():
            return
        from ..shadow import ShadowCompare, ShadowMirror, pairs_path, status_path

        try:
            info = self.registry.shadow_info()
        except Exception as e:
            log.warning(f"[FLEET] shadow pointer read failed: {e}")
            return
        aid = info.get("artifact") if info else None
        with self._lock:
            cur = self._shadow_aid
        if aid == cur:
            return
        if cur is not None:
            self._teardown_shadow()
        if aid is None:
            return
        if (
            self._shadow_warned == aid
            and time.monotonic() < self._shadow_retry_at
        ):
            return  # recent spin-up failure for this artifact: back off
        engine = self.replicas[0].engine
        try:
            manifest = self.registry.manifest(aid)
            mc = manifest.get("model_config")
            if mc is not None and mc != dataclasses.asdict(engine.model_cfg):
                if self._shadow_warned != aid:
                    with self._lock:
                        self._shadow_warned = aid
                    log.warning(
                        f"[FLEET] shadow artifact {aid} declares a "
                        "different architecture than the fleet's engines; "
                        "not mirroring (the gate will fail closed)"
                    )
                return
            params = self.registry.load_params(aid)
            replica = self.shadow_factory(
                params, round_id=int(manifest.get("round", 0))
            )
        except Exception as e:
            with self._lock:
                self._shadow_warned = aid
            self._shadow_retry_at = time.monotonic() + max(
                5.0, 10.0 * self.reload_poll_s
            )
            log.warning(
                f"[FLEET] shadow replica spin-up for {aid} failed "
                f"({type(e).__name__}: {e}); not mirroring (retrying "
                "with backoff while the shadow pointer names it)"
            )
            return
        root = self.registry.root
        # Fresh evidence per evaluation: a PREVIOUS shadow run of this
        # same artifact (a gate rejection later re-promoted, a crashed
        # gate) left its status/pairs files behind, and the gate would
        # rule on that stale evidence within one poll — the registry
        # events keep the historical verdicts, the files do not need to.
        # The pairs JSONL is TRUNCATED, not removed: the obs append path
        # caches one O_APPEND fd per path, and unlinking would strand a
        # previous in-process comparator's cached fd on a dead inode.
        try:
            os.remove(status_path(root, aid))
        except OSError:
            pass
        try:
            os.truncate(pairs_path(root, aid), 0)
        except OSError:
            pass
        compare = ShadowCompare(
            threshold=self.shadow_threshold,
            bins=self.shadow_bins,
            pairs_jsonl=pairs_path(root, aid),
            status_path=status_path(root, aid),
            # Publish every 8th pair, not every pair: the status rewrite
            # (snapshot + tmp + os.replace) per pair would make the
            # compare thread the bottleneck at exactly the mirror rates
            # the plane exists to measure; the gate's min_pairs is
            # always a multiple of this granularity in practice.
            status_every=8,
            tracer=self.tracer,
        )
        mirror = ShadowMirror(
            replica.host,
            replica.port,
            sample=self.shadow_sample,
            compare=compare,
            auth_key=self.auth_key,
            max_queue=self.shadow_queue,
            tracer=self.tracer,
        ).start()
        with self._lock:
            self._shadow_aid = aid
            self._shadow_replica = replica
            self._shadow_mirror = mirror
            self._shadow_compare = compare
            self._shadow_warned = None
        self.router.set_mirror(mirror)
        log.info(
            f"[FLEET] shadow plane armed for {aid}: replica on "
            f"{replica.host}:{replica.port}, mirroring "
            f"1/{self.shadow_sample} of live requests"
        )

    # ---------------------------------------------------------- the manager
    def _manager_loop(self) -> None:
        while not self._closed.wait(self.reload_poll_s):
            try:
                self._poll_shadow()
            except Exception as e:
                log.warning(
                    f"[FLEET] shadow poll failed (non-fatal): {e}"
                )
            try:
                info = self.registry.serving_info()
            except Exception as e:
                log.warning(f"[FLEET] registry pointer read failed: {e}")
                continue
            with self._lock:
                seen, warned = self._seen, self._warned
            if info is None or info.get("artifact") == seen:
                continue
            aid = info["artifact"]
            engine = self.replicas[0].engine
            try:
                manifest = self.registry.manifest(aid)
                mc = manifest.get("model_config")
                if mc is not None and mc != dataclasses.asdict(
                    engine.model_cfg
                ):
                    # Not marked seen: a rollback to a compatible
                    # artifact must still be adopted (RegistryWatcher's
                    # contract, fleet-wide).
                    if warned != aid:
                        with self._lock:
                            self._warned = aid
                        log.warning(
                            f"[FLEET] serving artifact {aid} declares a "
                            "different architecture than the fleet's "
                            "engines; skipping rolling reload (restart "
                            "the fleet to change shapes)"
                        )
                    continue
                params = self.registry.load_params(aid)
            except Exception as e:
                log.warning(
                    f"[FLEET] reload of serving artifact {aid} failed "
                    f"({type(e).__name__}: {e}); keeping the serving "
                    "weights"
                )
                continue
            self.rolling_reload(
                params,
                round_id=int(manifest.get("round", 0)),
                artifact=aid,
            )
            with self._lock:
                self._seen = aid
                self._warned = None
