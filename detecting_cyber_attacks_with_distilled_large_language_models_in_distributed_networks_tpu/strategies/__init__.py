"""Pluggable server-side aggregation strategies for the TCP round engine.

The streamed fold (comm/stream_agg.py) stays exactly what it is — raw
leaves folded in ascending-id order into the bit-exact weighted mean.
A Strategy is a PURE transform applied once per round at finalize time:

    new_global = strategy.apply(prev_global, folded_mean,
                                round_no=..., client_stats=...)

``fedavg`` is the identity on the mean, so ``serve --strategy fedavg``
is bit-identical to the historical fold and every crc replay gate
(fleet_crc_exact, aggregate_tree) extends unchanged.
"""

from .core import (
    STRATEGIES,
    FedAvg,
    FedOpt,
    FedProx,
    HeadBoost,
    Momentum,
    Strategy,
    make_strategy,
    parse_strategy,
)

__all__ = [
    "STRATEGIES",
    "FedAvg",
    "FedOpt",
    "FedProx",
    "HeadBoost",
    "Momentum",
    "Strategy",
    "make_strategy",
    "parse_strategy",
]
