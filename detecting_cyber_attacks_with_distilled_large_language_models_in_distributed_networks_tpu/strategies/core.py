"""Server aggregation strategies over flat numpy param dicts.

The comparative study (arXiv:2509.17836) shows plain FedAvg degrading
hard on non-IID cybersecurity partitions; TurboSVM-FL (arXiv:2401.12012)
shows aggregation-side boosting recovering lazy-client fleets. This
module is the registry both the TCP round engine (comm/server.py) and
the scenario/bench replay gates draw from.

Contract
--------
A strategy NEVER touches the fold: ``comm/stream_agg.py`` keeps folding
raw leaves in ascending-id order into the bit-exact weighted mean. At
finalize time the server calls::

    new_global = strategy.apply(prev_global, mean,
                                round_no=r, client_stats=stats)

with ``prev_global`` the previous post-strategy global (None on the
first round), ``mean`` the folded mean, and ``client_stats`` the
per-client fold stats from ``StreamAgg.client_stats()``. ``apply`` is a
pure function of ``(prev_global, mean)`` — ``client_stats`` informs
telemetry only — so a replay fed the same clean means reproduces the
live global bit-for-bit and the crc gates extend to every strategy.

FedOpt strategies treat the round's mean as a pseudo-gradient
``g = prev - mean`` and run a persistent optax server optimizer over it,
reusing ``parallel/fedavg.py::make_server_optimizer`` (Reddi et al.).
At server_lr=1 / momentum=0 this reduces exactly to the mean.
"""

from __future__ import annotations

from typing import Any

import numpy as np

__all__ = [
    "STRATEGIES",
    "Strategy",
    "FedAvg",
    "FedProx",
    "Momentum",
    "FedOpt",
    "HeadBoost",
    "parse_strategy",
    "make_strategy",
]

Flat = dict[str, np.ndarray]


class Strategy:
    """Base: a stateful per-server object applied once per round."""

    name: str = ""

    def params(self) -> dict[str, Any]:
        """Hyperparameters for wire-meta / trace / metrics stamping."""
        return {}

    def client_mu(self) -> float:
        """Proximal term advertised to clients (FedProx); 0 = none."""
        return 0.0

    def reset(self) -> None:
        """Drop optimizer state (model shape changed / replay restart)."""

    def describe(self) -> dict[str, Any]:
        return {"name": self.name, "params": self.params()}

    def export_state(self) -> "list[np.ndarray] | None":
        """Optimizer-state leaves for server-restart checkpointing
        (comm/server.py strategy_state_path); None = stateless."""
        return None

    def restore_state(
        self, leaves: "list[np.ndarray]", template_params: Flat
    ) -> bool:
        """Rebuild optimizer state from exported leaves against the
        restored global. False = leaves don't fit (start fresh)."""
        return False

    def apply(
        self,
        prev: Flat | None,
        mean: Flat,
        *,
        round_no: int = 0,
        client_stats: dict[int, dict[str, float]] | None = None,
    ) -> Flat:
        raise NotImplementedError


def _compatible(prev: Flat | None, mean: Flat) -> bool:
    """prev is usable as the round anchor: same keys, same shapes."""
    if prev is None:
        return False
    if sorted(prev) != sorted(mean):
        return False
    return all(
        np.shape(prev[k]) == np.shape(mean[k]) for k in sorted(mean)
    )


class FedAvg(Strategy):
    """Identity on the folded mean — the historical fold, bit-for-bit."""

    name = "fedavg"

    def apply(self, prev, mean, *, round_no=0, client_stats=None):
        return mean


class FedProx(Strategy):
    """Server-side identity; the proximal term lives on the CLIENT.

    FedProx (Li et al.) anchors each client's local loss with
    ``mu/2 * ||w - w_round_start||^2``. The server's aggregation is the
    plain weighted mean, so ``apply`` is the identity; the strategy
    carries ``mu`` so the round-START wire meta advertises it and the
    scenario harness threads it into the client train-step builders
    (train/engine.py, TrainConfig.prox_mu).
    """

    name = "fedprox"

    def __init__(self, mu: float = 0.01):
        if mu <= 0.0:
            raise ValueError(f"fedprox mu={mu} must be > 0")
        self.mu = float(mu)

    def params(self):
        return {"mu": self.mu}

    def client_mu(self):
        return self.mu

    def apply(self, prev, mean, *, round_no=0, client_stats=None):
        return mean


class _ServerOptStrategy(Strategy):
    """Shared FedOpt machinery: pseudo-gradient + persistent optax tx.

    ``g = prev - mean``; ``new = prev + tx(g)``. The optimizer state
    persists across rounds (unlike the per-round client optimizer
    reset), mirroring parallel/fedavg.py's mesh-tier server_opt.
    """

    def __init__(self, server_opt: str, lr: float, momentum: float = 0.9):
        if lr <= 0.0:
            raise ValueError(f"{self.name} lr={lr} must be > 0")
        if not 0.0 <= momentum < 1.0:
            raise ValueError(
                f"{self.name} momentum={momentum} must be in [0, 1)"
            )
        self._server_opt = server_opt
        self.lr = float(lr)
        self.momentum = float(momentum)
        self._tx = None
        self._opt_state = None

    def _transform(self):
        if self._tx is None:
            # Lazy: keeps `fedtpu serve --strategy fedavg` from paying
            # the jax/optax import at CLI start.
            from ..config import FedConfig
            from ..parallel.fedavg import make_server_optimizer

            self._tx = make_server_optimizer(
                FedConfig(
                    server_opt=self._server_opt,
                    server_lr=self.lr,
                    server_momentum=self.momentum,
                )
            )
        return self._tx

    def reset(self):
        self._opt_state = None

    def export_state(self):
        """The optax state's leaves in tree order (counts, momenta,
        second moments — all dense arrays), host-materialized so the
        server's npz writer can persist them without touching jax."""
        if self._opt_state is None:
            return None
        import jax

        return [
            np.asarray(leaf)
            for leaf in jax.tree_util.tree_leaves(self._opt_state)
        ]

    def restore_state(self, leaves, template_params):
        """Inverse of :func:`export_state`: build a fresh ``tx.init``
        state over the restored global (the structure/treedef donor),
        then substitute the persisted leaves. Leaf count or any
        shape mismatch means the model or optimizer changed — refuse,
        the caller starts with fresh optimizer memory."""
        import jax

        tx = self._transform()
        template = tx.init(
            {
                k: np.asarray(template_params[k], np.float32)
                for k in sorted(template_params)
            }
        )
        t_leaves, treedef = jax.tree_util.tree_flatten(template)
        if len(leaves) != len(t_leaves):
            return False
        cast = []
        for got, want in zip(leaves, t_leaves):
            w = np.asarray(want)
            if np.shape(got) != w.shape:
                return False
            cast.append(np.asarray(got, w.dtype))
        self._opt_state = jax.tree_util.tree_unflatten(treedef, cast)
        return True

    def apply(self, prev, mean, *, round_no=0, client_stats=None):
        if not _compatible(prev, mean):
            # First round (no global yet) or model shape changed: the
            # mean IS the new global; optimizer state restarts.
            self.reset()
            return mean
        import optax  # deferred with the tx build

        tx = self._transform()
        prev32 = {
            k: np.asarray(prev[k], np.float32) for k in sorted(mean)
        }
        grad = {
            k: prev32[k] - np.asarray(mean[k], np.float32)
            for k in sorted(mean)
        }
        if self._opt_state is None:
            self._opt_state = tx.init(prev32)
        updates, self._opt_state = tx.update(grad, self._opt_state, prev32)
        new = optax.apply_updates(prev32, updates)
        return {k: np.asarray(new[k], np.float32) for k in sorted(new)}


class Momentum(_ServerOptStrategy):
    """FedAvgM: heavy-ball memory over round updates (Hsu et al.)."""

    name = "momentum"

    def __init__(self, lr: float = 1.0, momentum: float = 0.9):
        super().__init__("momentum", lr, momentum)

    def params(self):
        return {"lr": self.lr, "momentum": self.momentum}


class FedOpt(_ServerOptStrategy):
    """FedAdam / FedYogi: adaptive per-parameter server steps."""

    name = "fedopt"

    def __init__(self, opt: str = "adam", lr: float = 0.1):
        opt = str(opt)
        if opt not in ("adam", "yogi"):
            raise ValueError(f"fedopt opt={opt!r} must be adam|yogi")
        self.opt = opt
        super().__init__(opt, lr)

    def params(self):
        return {"opt": self.opt, "lr": self.lr}


class HeadBoost(Strategy):
    """TurboSVM-style head-level boost (arXiv:2401.12012, adapted).

    Lazy fleets move the classifier head too slowly: the encoder's mean
    drift is tiny but the head — the only task-specific capacity — gets
    diluted by barely-trained uploads. Boost the head's round update by
    ``gamma`` while the body takes the plain mean::

        head leaf:  new = prev + gamma * (mean - prev)
        body leaf:  new = mean

    Degrades to exact FedAvg when no leaf matches ``match`` or there is
    no previous global to measure the update against.
    """

    name = "headboost"

    def __init__(self, gamma: float = 1.5, match: str = "classifier"):
        if gamma <= 0.0:
            raise ValueError(f"headboost gamma={gamma} must be > 0")
        if not match:
            raise ValueError("headboost match pattern must be non-empty")
        self.gamma = float(gamma)
        self.match = str(match)

    def params(self):
        return {"gamma": self.gamma, "match": self.match}

    def apply(self, prev, mean, *, round_no=0, client_stats=None):
        if not _compatible(prev, mean):
            return mean
        out: Flat = {}
        for k in sorted(mean):
            m = np.asarray(mean[k], np.float32)
            if self.match in k:
                p = np.asarray(prev[k], np.float32)
                out[k] = np.asarray(
                    p + self.gamma * (m - p), np.float32
                )
            else:
                out[k] = m
        return out


STRATEGIES: dict[str, type[Strategy]] = {
    FedAvg.name: FedAvg,
    FedProx.name: FedProx,
    Momentum.name: Momentum,
    FedOpt.name: FedOpt,
    HeadBoost.name: HeadBoost,
}


def parse_strategy(spec: str) -> tuple[str, dict[str, Any]]:
    """``"name:key=val,key=val"`` -> (name, kwargs).

    Values parse as float when they look like one, else stay strings
    (``fedopt:opt=yogi,lr=0.05``). A bare name means defaults.
    """
    spec = str(spec).strip()
    name, _, rest = spec.partition(":")
    name = name.strip()
    if name not in STRATEGIES:
        raise ValueError(
            f"unknown strategy {name!r} "
            f"(choose from {'|'.join(sorted(STRATEGIES))})"
        )
    kwargs: dict[str, Any] = {}
    if rest.strip():
        for item in rest.split(","):
            key, sep, val = item.partition("=")
            key, val = key.strip(), val.strip()
            if not key or not sep or not val:
                raise ValueError(
                    f"bad strategy param {item!r} in {spec!r} "
                    "(want key=value[,key=value...])"
                )
            try:
                kwargs[key] = float(val)
            except ValueError:
                kwargs[key] = val
    return name, kwargs


def make_strategy(spec: "str | Strategy | None") -> Strategy:
    """Build a Strategy from a spec string; None -> fedavg."""
    if spec is None:
        return FedAvg()
    if isinstance(spec, Strategy):
        return spec
    name, kwargs = parse_strategy(spec)
    try:
        return STRATEGIES[name](**kwargs)
    except TypeError as exc:
        raise ValueError(
            f"strategy {name!r} rejected params "
            f"{sorted(kwargs)}: {exc}"
        ) from None
