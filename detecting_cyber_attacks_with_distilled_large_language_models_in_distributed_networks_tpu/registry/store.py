"""Content-addressed artifact store with an atomic serving pointer.

Layout under the registry root::

    artifacts/<id>/params.npz     flat fp32 params ('/'-joined keys)
    artifacts/<id>/manifest.json  round lineage, state, eval metrics,
                                  eval score histogram, model config
    serving.json                  the serving pointer (atomic os.replace)
    events.jsonl                  append-only audit trail

The artifact id is a truncated SHA-256 over the sorted tensor manifest
(key, dtype, shape, bytes), so identical params dedup to one artifact
and an id can never name two different models. Artifact directories are
staged under a tmp name and ``os.rename``d into place, manifests are
rewritten via tmp + ``os.replace``, and the pointer is one small JSON
file swapped with ``os.replace`` — every read a concurrent serving
process can make sees either the old state or the new one, never a torn
write (pinned by tests/test_registry.py's concurrent-reader test).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Mapping

import numpy as np

from ..comm import wire
from ..utils.logging import get_logger

log = get_logger()

#: Promotion ladder (promote() advances one rung; serving swaps the
#: pointer). ``rejected`` is the eval gate's terminal verdict; ``retired``
#: is what a serving artifact becomes when another one replaces it.
STATES = ("candidate", "shadow", "serving", "rejected", "retired")
_LADDER = ("candidate", "shadow", "serving")

_POINTER = "serving.json"
#: The shadow pointer (shadow/): the artifact currently under live
#: shadow evaluation. Written when an artifact is promoted TO ``shadow``
#: and cleared when it leaves the state (serving, rejected, or an
#: explicit re-promote) — the fleet manager follows it to spin the
#: shadow replica up and down, exactly as the serving tier follows
#: serving.json.
_SHADOW = "shadow.json"
_EVENTS = "events.jsonl"
_ID_HEX = 16  # 64 bits of sha256 — collision-safe for any real fleet


class RegistryError(ValueError):
    """Unknown artifact, illegal state transition, or a corrupt store."""


def _atomic_write_json(path: str, obj: Mapping[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _flatten(params: Any) -> dict[str, np.ndarray]:
    """Nested-or-flat params -> flat fp32 dict (the registry's one storage
    dtype; non-fp32 leaves — e.g. a bf16-trained tree — are upcast, which
    is exact for every dtype the engine trains in). An already-flat dict
    (every value a leaf — what serve_round returns, '/'-joined keys) is
    taken as-is; anything nested goes through wire.flatten_params."""
    if isinstance(params, Mapping) and params and all(
        not isinstance(v, Mapping) for v in params.values()
    ):
        flat: Mapping[str, Any] = {str(k): v for k, v in params.items()}
    else:
        flat = wire.flatten_params(params)
    return {k: np.asarray(v, np.float32) for k, v in flat.items()}


def artifact_id(params: Any) -> str:
    """Content address: SHA-256 over the sorted (key, dtype, shape, bytes)
    manifest, truncated to 64 bits of hex."""
    flat = _flatten(params)
    h = hashlib.sha256()
    for key in sorted(flat):
        arr = np.ascontiguousarray(flat[key])
        h.update(key.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:_ID_HEX]


class ModelRegistry:
    """Artifact store + promotion state machine + serving pointer.

    ``tracer`` (obs/trace.py): promotion events double as ``promote``
    spans — each state transition / pointer swap lands on the unified
    events-JSONL with its measured duration, so the obs timeline shows
    registry time next to round compute and the eval gate."""

    def __init__(self, root: str, *, tracer=None):
        self.root = os.path.abspath(root)
        self._artifacts = os.path.join(self.root, "artifacts")
        self.tracer = tracer
        os.makedirs(self._artifacts, exist_ok=True)

    # ---------------------------------------------------------------- events
    def _event(self, kind: str, **fields: Any) -> None:
        rec = {"ts": time.time(), "event": kind, **fields}
        with open(os.path.join(self.root, _EVENTS), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def record_reload(self, aid: str, *, consumer: str) -> None:
        """Audit one serving-tier adoption of an artifact on the events
        trail — the fleet's rolling reload (router/fleet.py) records each
        replica's completed hot-swap here, so ``events.jsonl`` answers
        "which replica served which artifact when" without scraping
        process logs. Append-only telemetry: never touches manifests or
        the pointer."""
        self._event("reload", artifact=str(aid), consumer=str(consumer))

    def _promote_span(
        self, t_unix: float, t0: float, aid: str, state: str, round_index
    ) -> None:
        if self.tracer is None:
            return
        self.tracer.record(
            "promote",
            t_start=t_unix,
            dur_s=time.monotonic() - t0,
            round=round_index if isinstance(round_index, int) else None,
            artifact=aid,
            state=state,
        )

    # --------------------------------------------------------------- writing
    def add(
        self,
        params: Any,
        *,
        round_index: int,
        metrics: Mapping[str, float] | None = None,
        eval_hist: Any | None = None,
        model_config: Any | None = None,
        parent: str | None = None,
        extra: Mapping[str, Any] | None = None,
    ) -> str:
        """Register one finished round's params as an immutable candidate.

        Returns the artifact id. Re-adding identical params is a no-op
        returning the existing id (content addressing); the artifact is
        staged under a tmp directory and renamed into place, so a reader
        can never see a partially-written artifact.

        ``eval_hist``: the held-out eval score-distribution histogram
        (train/fedeval.reference_histogram) the drift monitor compares
        live serving scores against once this artifact is promoted.
        ``model_config``: a ModelConfig (or its asdict) recorded so the
        serving tier refuses to hot-swap an architecture mismatch.
        """
        flat = _flatten(params)
        aid = artifact_id(flat)
        final = os.path.join(self._artifacts, aid)
        if os.path.isdir(final):
            log.info(f"[REGISTRY] artifact {aid} already registered (dedup)")
            return aid
        if model_config is not None and dataclasses.is_dataclass(model_config):
            model_config = dataclasses.asdict(model_config)
        manifest = {
            "id": aid,
            "state": "candidate",
            "round": int(round_index),
            "created_unix": time.time(),
            "parent": parent,
            "metrics": _scalar_metrics(metrics),
            "eval_hist": (
                None
                if eval_hist is None
                else [int(c) for c in np.asarray(eval_hist).ravel()]
            ),
            "model_config": model_config,
            "n_tensors": len(flat),
            "n_params": int(sum(v.size for v in flat.values())),
        }
        if extra:
            manifest["extra"] = dict(extra)
        tmp = os.path.join(self._artifacts, f".tmp-{aid}-{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)
        try:
            with open(os.path.join(tmp, "params.npz"), "wb") as f:
                np.savez(f, **flat)
            _atomic_write_json(os.path.join(tmp, "manifest.json"), manifest)
            os.rename(tmp, final)
        except OSError:
            # A racing add() of the same content may have won the rename;
            # that is success (identical bytes by construction).
            if not os.path.isdir(final):
                raise
        finally:
            if os.path.isdir(tmp):
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        self._event("added", artifact=aid, round=int(round_index))
        log.info(
            f"[REGISTRY] registered candidate {aid} (round {round_index}, "
            f"{manifest['n_params']:,} params)"
        )
        return aid

    # --------------------------------------------------------------- reading
    def _manifest_path(self, aid: str) -> str:
        return os.path.join(self._artifacts, aid, "manifest.json")

    def manifest(self, aid: str) -> dict:
        try:
            with open(self._manifest_path(aid)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise RegistryError(f"unknown or corrupt artifact {aid!r}: {e}") from None

    def load_params(self, aid: str) -> dict:
        """Artifact params as the nested dict the engines consume."""
        path = os.path.join(self._artifacts, aid, "params.npz")
        try:
            with np.load(path) as z:
                flat = {k: np.asarray(z[k]) for k in z.files}
        except OSError as e:
            raise RegistryError(f"artifact {aid!r} has no params: {e}") from None
        return wire.unflatten_params(flat)

    def list(self) -> list[dict]:
        """Every artifact's manifest, oldest first."""
        out = []
        try:
            entries = sorted(os.listdir(self._artifacts))
        except OSError:
            return out
        for name in entries:
            if name.startswith("."):
                continue
            try:
                out.append(self.manifest(name))
            except RegistryError:
                continue
        out.sort(key=lambda m: m.get("created_unix", 0.0))
        return out

    # --------------------------------------------------------------- pointer
    def serving_info(self) -> dict | None:
        """The serving pointer's content (None before any promotion).
        One atomic file read — safe against a concurrent promote()."""
        try:
            with open(os.path.join(self.root, _POINTER)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, json.JSONDecodeError) as e:
            raise RegistryError(f"corrupt serving pointer: {e}") from None

    def serving_manifest(self) -> dict | None:
        info = self.serving_info()
        return None if info is None else self.manifest(info["artifact"])

    def shadow_info(self) -> dict | None:
        """The shadow pointer's content (None when nothing is under live
        shadow evaluation). Same atomicity contract as the serving
        pointer — one small JSON file swapped with os.replace."""
        try:
            with open(os.path.join(self.root, _SHADOW)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, json.JSONDecodeError) as e:
            raise RegistryError(f"corrupt shadow pointer: {e}") from None

    def _clear_shadow(self, aid: str) -> None:
        """Drop the shadow pointer iff it names ``aid`` (the artifact
        left the shadow state). A pointer naming a DIFFERENT artifact is
        untouched — promotions of unrelated candidates must not tear
        down a live shadow evaluation."""
        try:
            info = self.shadow_info()
        except RegistryError:
            info = None
        if info is not None and info.get("artifact") == aid:
            try:
                os.remove(os.path.join(self.root, _SHADOW))
            except OSError:
                pass

    # ----------------------------------------------------- state transitions
    def _set_state(self, aid: str, state: str) -> dict:
        if state not in STATES:
            raise RegistryError(f"unknown state {state!r}")
        m = self.manifest(aid)
        m["state"] = state
        m[f"{state}_unix"] = time.time()
        _atomic_write_json(self._manifest_path(aid), m)
        return m

    def promote(self, aid: str, *, to: str | None = None) -> dict:
        """Advance ``aid`` one rung up the ladder (or straight ``to`` a
        named rung). Reaching ``serving`` swaps the pointer atomically and
        retires the previous serving artifact. Returns the new manifest."""
        t_unix = time.time()
        t0 = time.monotonic()
        m = self.manifest(aid)
        cur = m.get("state", "candidate")
        if cur in ("rejected", "retired") and to is None:
            raise RegistryError(
                f"artifact {aid} is {cur}; promote it explicitly with "
                "to='candidate' first if that is really intended"
            )
        if to is None:
            if cur not in _LADDER:
                to = "candidate"
            elif cur == "serving":
                raise RegistryError(f"artifact {aid} is already serving")
            else:
                to = _LADDER[_LADDER.index(cur) + 1]
        if to not in STATES:
            raise RegistryError(f"unknown state {to!r}")
        if to != "serving":
            if to == "shadow":
                serving = self.serving_info()
                if serving is not None and serving.get("artifact") == aid:
                    # The explicit --to shadow path must refuse the
                    # incumbent: mirroring the serving artifact against
                    # itself spins a duplicate replica forever and can
                    # never produce a meaningful verdict.
                    raise RegistryError(
                        f"artifact {aid} is serving; a shadow evaluation "
                        "compares a CANDIDATE against the incumbent"
                    )
            m = self._set_state(aid, to)
            if to == "shadow":
                # Clear any PREVIOUS evaluation's evidence BEFORE the
                # pointer announces the new one: the controller's gate
                # starts polling status.json the moment promote()
                # returns, while the fleet manager arms (and does its
                # own arm-time clearing) only a poll later — leftover
                # evidence from an earlier run of this same artifact
                # must lose that race here, not there. The pairs JSONL
                # is truncated, not unlinked (the obs append path
                # caches one O_APPEND fd per path).
                from ..shadow.gate import pairs_path, status_path

                try:
                    os.remove(status_path(self.root, aid))
                except OSError:
                    pass
                try:
                    os.truncate(pairs_path(self.root, aid), 0)
                except OSError:
                    pass
                # Announce the live shadow evaluation: the fleet manager
                # follows this pointer to spin up the shadow replica and
                # arm the traffic mirror (shadow/).
                _atomic_write_json(
                    os.path.join(self.root, _SHADOW),
                    {
                        "artifact": aid,
                        "round": m.get("round"),
                        "since_unix": time.time(),
                    },
                )
            else:
                self._clear_shadow(aid)
            self._event("promoted", artifact=aid, state=to)
            log.info(f"[REGISTRY] {aid}: {cur} -> {to}")
            self._promote_span(t_unix, t0, aid, to, m.get("round"))
            return m
        prev = self.serving_info()
        prev_id = prev["artifact"] if prev else None
        if prev_id == aid:
            raise RegistryError(f"artifact {aid} is already serving")
        m = self._set_state(aid, "serving")
        self._clear_shadow(aid)
        pointer = {
            "artifact": aid,
            "round": m.get("round"),
            "promoted_at_unix": time.time(),
            # Rollback chain, most recent first (the pointer itself is the
            # single source of truth for "what served before").
            "history": ([prev_id] + list(prev.get("history", []))) if prev else [],
        }
        _atomic_write_json(os.path.join(self.root, _POINTER), pointer)
        if prev_id is not None:
            try:
                self._set_state(prev_id, "retired")
            except RegistryError:
                pass  # previous artifact deleted out-of-band; pointer moved anyway
        self._event("serving", artifact=aid, previous=prev_id)
        log.info(
            f"[REGISTRY] serving pointer -> {aid} (round {m.get('round')})"
            + (f", retired {prev_id}" if prev_id else "")
        )
        self._promote_span(t_unix, t0, aid, "serving", m.get("round"))
        return m

    def reject(
        self, aid: str, *, reason: str = "", verdict: Mapping[str, Any] | None = None
    ) -> dict:
        """The eval (or shadow) gate's verdict: mark a candidate rejected
        (it stays on disk as lineage; it can never reach the pointer
        without an explicit operator re-promote). ``verdict`` — the
        shadow gate's measured disagreement (pairs, flip rate, PSI) —
        rides the registry event so the audit trail records WHY live
        traffic refused this candidate, not just that it was refused."""
        m = self._set_state(aid, "rejected")
        self._clear_shadow(aid)
        extra = {"verdict": dict(verdict)} if verdict is not None else {}
        self._event("rejected", artifact=aid, reason=reason, **extra)
        log.info(f"[REGISTRY] rejected {aid}" + (f": {reason}" if reason else ""))
        return m

    def rollback(self) -> dict:
        """Swap the pointer back to the previous serving artifact (one
        atomic step). The demoted artifact is marked retired."""
        t_unix = time.time()
        t0 = time.monotonic()
        cur = self.serving_info()
        if cur is None:
            raise RegistryError("nothing is serving; no rollback target")
        history = list(cur.get("history", []))
        if not history:
            raise RegistryError(
                f"serving artifact {cur['artifact']} has no predecessor"
            )
        target, rest = history[0], history[1:]
        m = self.manifest(target)  # must still exist before we demote anyone
        self._set_state(target, "serving")
        pointer = {
            "artifact": target,
            "round": m.get("round"),
            "promoted_at_unix": time.time(),
            "history": rest,
            "rolled_back_from": cur["artifact"],
        }
        _atomic_write_json(os.path.join(self.root, _POINTER), pointer)
        try:
            self._set_state(cur["artifact"], "retired")
        except RegistryError:
            pass
        self._event("rollback", artifact=target, previous=cur["artifact"])
        log.info(
            f"[REGISTRY] rollback: serving pointer {cur['artifact']} -> {target}"
        )
        self._promote_span(t_unix, t0, target, "rollback", m.get("round"))
        return m


    # ------------------------------------------------------------------- gc
    def gc(self, *, max_artifacts: int) -> list[str]:
        """Prune oldest RETIRED/REJECTED artifacts until at most
        ``max_artifacts`` remain on disk (an unattended controller's
        registry otherwise grows by one model-sized artifact per round,
        forever). Returns the pruned ids, oldest first.

        Never pruned, regardless of the budget:

        * the serving artifact and every id on the pointer's rollback
          ``history`` chain — ``registry rollback`` must always have its
          targets;
        * live ladder states (``candidate``/``shadow``): they are still
          in flight toward the pointer, not garbage.

        When the protected set alone exceeds ``max_artifacts`` nothing
        beyond the eligible artifacts is touched — gc refuses to break
        the rollback chain rather than honoring the number."""
        if max_artifacts < 1:
            raise RegistryError(
                f"max_artifacts={max_artifacts} must be >= 1"
            )
        protected: set[str] = set()
        info = self.serving_info()
        if info is not None:
            protected.add(info["artifact"])
            protected.update(
                h for h in info.get("history", []) if h is not None
            )
        manifests = self.list()  # oldest first
        excess = len(manifests) - int(max_artifacts)
        removed: list[str] = []
        if excess <= 0:
            return removed
        import shutil

        for m in manifests:
            if excess <= 0:
                break
            aid = m["id"]
            if aid in protected:
                continue
            if m.get("state") not in ("retired", "rejected"):
                continue
            path = os.path.join(self._artifacts, aid)
            shutil.rmtree(path, ignore_errors=True)
            if os.path.exists(path):
                # A failed deletion (permissions, held-open file) must
                # not be recorded as pruned — the events trail would
                # permanently misreport and every later gc would
                # "re-prune" it while the registry exceeds its budget.
                log.warning(
                    f"[REGISTRY] gc could not remove artifact {aid} "
                    f"({path}); it remains on disk and still counts "
                    "toward the budget"
                )
                continue
            removed.append(aid)
            excess -= 1
        if removed:
            self._event(
                "gc", removed=removed, max_artifacts=int(max_artifacts)
            )
            log.info(
                f"[REGISTRY] gc pruned {len(removed)} retired/rejected "
                f"artifact(s) (budget {max_artifacts}): {removed}"
            )
        return removed


def _scalar_metrics(metrics: Mapping[str, Any] | None) -> dict:
    """Keep only scalar metrics, and only FINITE numeric ones: arrays
    (probs/labels) stay out of the manifest — the histogram is their
    registry representation — and a NaN metric is DROPPED, not stored as
    a null sentinel (a missing key reads as 'never measured' everywhere;
    a null would make a later gate comparison fail-open confusingly)."""
    out: dict[str, Any] = {}
    for k, v in (metrics or {}).items():
        if isinstance(v, (bool, str)):
            out[k] = v
        elif isinstance(v, (int, float, np.generic)):
            f = float(v)
            if np.isfinite(f):
                out[k] = f
    return out
