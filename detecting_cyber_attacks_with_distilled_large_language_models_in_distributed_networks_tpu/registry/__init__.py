"""Model registry: immutable round artifacts + eval-gated promotion.

The reference's deployment contract is a bare ``.pth`` path: whatever
file sits there IS the model, with no record of which round produced it,
how it evaluated, or what served before it — and the serving tier (PR 1)
inherited that shape by hot-reloading whatever checkpoint appears on
disk. This package is the control-plane half of closing that gap:

* every finished federated round can be written as an **immutable,
  content-addressed artifact** (flat params + a manifest carrying round
  lineage, held-out eval metrics, and the eval score histogram drift
  detection references);
* artifacts move through explicit **promotion states** —
  ``candidate -> shadow -> serving`` — with regression states
  (``rejected``/``retired``) for gate failures and demotions;
* the **serving pointer** is one atomically-swapped JSON file
  (``serving.json``), which ``serving/reload.RegistryWatcher`` follows
  instead of a raw checkpoint directory — the scoring tier can only ever
  serve a PROMOTED artifact, never a half-written or unevaluated one;
* ``rollback()`` swaps the pointer back to the previous serving
  artifact in one atomic step.

The promotion decisions themselves (the eval gate, drift triggers) live
in :mod:`..control`; this package is the storage + state machine.
"""

from .store import ModelRegistry, RegistryError

__all__ = ["ModelRegistry", "RegistryError"]
