"""Typed configuration system.

The reference has no config system at all — module-level constants and literals
scattered through three scripts (reference client1.py:22-23, server.py:10-13;
bs=16 / max_len=128 / lr=2e-5 / epochs=3 at client1.py:27,365-372,379-380), and
scaling to N clients means copy-pasting ``clientN.py`` with a new hard-coded
seed.  Here every knob is a dataclass field and per-client identity is derived
(``client_id -> seed``), never copy-pasted.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence


@dataclass(frozen=True)
class ModelConfig:
    """Transformer encoder + classification head.

    Defaults reproduce DistilBERT-base-uncased (6 layers, 768 hidden, 12 heads,
    3072 FFN, learned positions, post-LayerNorm, exact GELU) which the reference
    loads via HF ``DistilBertModel.from_pretrained`` (reference client1.py:56),
    plus the reference's classifier head: CLS pooling -> Dropout(0.3) ->
    Linear(768, 2) (reference client1.py:57-58,62-64).
    """

    vocab_size: int = 30522
    max_len: int = 128
    max_position_embeddings: int = 512  # HF DistilBERT position-table size
    dim: int = 768
    n_layers: int = 6
    n_heads: int = 12
    hidden_dim: int = 3072
    dropout: float = 0.1
    attention_dropout: float = 0.1
    head_dropout: float = 0.3
    n_classes: int = 2
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    pad_token_id: int = 0
    # "bf16" activations keep the MXU fed; params/optimizer stay fp32.
    compute_dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # FFN activation: "tanh" is the GPT-2-style tanh GELU — measured ~20%
    # faster per train step than the erf form on TPU v5e (the erf chain is
    # VPU-transcendental-bound), deviating from it by at most a few bf16
    # ulps (<0.8% relative), i.e. on the order of bf16 rounding itself.
    # "exact" is HF DistilBERT's erf GELU (reference client1.py:56 via HF);
    # use it for fp32 logit-parity comparisons (ModelConfig.tiny defaults
    # to it alongside fp32 compute).
    gelu: str = "tanh"
    # "dot" (XLA fused attention), "flash" (Pallas kernel), "ring"
    # (sequence-parallel ring attention over a mesh axis).
    attention_impl: str = "dot"
    # Compute Q/K/V with ONE [D, 3D] matmul over kernels concatenated at
    # apply time (the parameter tree keeps the separate q/k/v layout, so
    # checkpoints and HF conversion are unaffected). Same math, fewer
    # larger MXU dispatches; measured via BENCH_FUSED_QKV.
    fused_qkv: bool = False
    # Mesh axis the sequence dimension is sharded over when attention_impl
    # is "ring" (the forward must run inside shard_map with this axis bound).
    ring_axis: str = "seq"
    # Mesh axis the batch dimension shards over inside the same shard_map
    # (fedseq): hash-dropout masks offset their row coordinate by this
    # axis's shard index so data shards draw independent masks.
    data_axis: str = "data"
    remat: bool = False

    def __post_init__(self) -> None:
        if self.n_layers < 1:
            raise ValueError(f"n_layers={self.n_layers} must be >= 1")
        if self.max_len > self.max_position_embeddings:
            raise ValueError(
                f"max_len={self.max_len} exceeds the position-embedding table "
                f"(max_position_embeddings={self.max_position_embeddings}); "
                "XLA would silently clamp position indices"
            )
        if self.attention_impl not in ("dot", "flash", "ring"):
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        if self.gelu not in ("exact", "tanh"):
            raise ValueError(f"unknown gelu {self.gelu!r} (exact|tanh)")
        # attention_impl='ring' supports attention dropout since the ring
        # gained global-coordinate hash masks (parallel/ring_attention.py);
        # no impl/dropout combination is invalid anymore.

    @property
    def head_dim(self) -> int:
        if self.dim % self.n_heads:
            raise ValueError(f"dim={self.dim} not divisible by n_heads={self.n_heads}")
        return self.dim // self.n_heads

    def replace(self, **kw: Any) -> "ModelConfig":
        return dataclasses.replace(self, **kw)

    @classmethod
    def distilbert_base(cls, **kw: Any) -> "ModelConfig":
        return cls(**kw)

    @classmethod
    def bert_base(cls, **kw: Any) -> "ModelConfig":
        """BERT-base-sized scale-up encoder (BASELINE.json config 4)."""
        kw.setdefault("n_layers", 12)
        return cls(**kw)

    @classmethod
    def bert_large(cls, **kw: Any) -> "ModelConfig":
        """BERT-large-sized encoder (24L/1024/16H/4096, ~335 M params) —
        the capacity ceiling for single-chip federated fine-tuning here;
        larger models shard over the mesh's data axis."""
        kw.setdefault("n_layers", 24)
        kw.setdefault("dim", 1024)
        kw.setdefault("n_heads", 16)
        kw.setdefault("hidden_dim", 4096)
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw: Any) -> "ModelConfig":
        """Small config for tests / CI on CPU."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_len", 32)
        kw.setdefault("max_position_embeddings", 64)
        kw.setdefault("dim", 32)
        kw.setdefault("n_layers", 2)
        kw.setdefault("n_heads", 2)
        kw.setdefault("hidden_dim", 64)
        kw.setdefault("compute_dtype", "float32")
        kw.setdefault("gelu", "exact")  # fp32 tests compare against HF erf
        return cls(**kw)


@dataclass(frozen=True)
class DataConfig:
    """CICIDS2017-style flow CSV -> text -> token arrays.

    Mirrors reference semantics: ``±inf -> NaN -> column-mean`` imputation and a
    ``frac`` sample with a per-client seed (reference client1.py:84-93, seed 42;
    client2.py:79-88, seed 43), 60/20/20 split via two chained train_test_split
    calls (reference client1.py:365-366), label map ``'DDoS' -> 1 else 0``
    (reference client1.py:91).
    """

    csv_path: str = "CICIDS2017.csv"
    # Registered dataset schema: cicids2017 | cicddos2019 | unswnb15
    # (data/datasets.py). Governs the text template + binary-label semantics.
    dataset: str = "cicids2017"
    data_fraction: float = 0.1
    seed_base: int = 42  # client i uses seed_base + i  (42, 43, ... — matches reference)
    val_fraction: float = 0.2
    test_fraction: float = 0.2
    label_column: str = "Label"
    positive_label: str = "DDoS"
    max_len: int = 128
    batch_size: int = 16
    eval_batch_size: int = 16
    # "sample"  — reference behavior: independent frac-sample per client seed
    #             (overlap between clients possible, as in the reference).
    # "disjoint" — equal disjoint shards.
    # "dirichlet" — non-IID label-skew partition (BASELINE.json config 3).
    # "quantity" — quantity skew: disjoint IID-content shards with
    #             Dirichlet(alpha) sizes (data/partition.py).
    partition: str = "sample"
    # Concentration for BOTH skewed schemes: dirichlet (label skew) and
    # quantity (size skew); smaller = more skewed.
    dirichlet_alpha: float = 0.5
    vocab_path: str | None = None
    # Training batches: True (default) drops the final short batch of each
    # epoch so every step compiles once at one shape; False trains it at
    # its own (smaller) shape — the reference DataLoader's drop_last=False
    # (client1.py:370) at the cost of one extra XLA compilation. Eval is
    # unaffected (it always counts every example via row masks).
    drop_remainder: bool = True

    def __post_init__(self) -> None:
        if self.dirichlet_alpha <= 0.0:
            # numpy 2.x draws an all-zero Dirichlet for alpha=0 silently,
            # which would hand every sample to the last client.
            raise ValueError(
                f"dirichlet_alpha={self.dirichlet_alpha} must be > 0"
            )
        if self.partition not in ("sample", "disjoint", "dirichlet", "quantity"):
            # Fail at config time, not mid-partition: a typo'd scheme on
            # the TCP tier would otherwise surface only after the model
            # loaded (data/partition.py PARTITION_SCHEMES).
            raise ValueError(
                f"unknown partition scheme {self.partition!r} "
                "(sample|disjoint|dirichlet|quantity)"
            )

    def client_seed(self, client_id: int) -> int:
        return self.seed_base + client_id


@dataclass(frozen=True)
class TrainConfig:
    """Local-training hyperparameters (reference client1.py:370,379-380)."""

    learning_rate: float = 2e-5
    # Linear LR warmup over this many steps (0 = constant, the reference's
    # schedule). Larger per-client batches than the reference's 16 (the TPU
    # MFU sweet spot is 128, SURVEY.md §7c) train more stably with warmup.
    warmup_steps: int = 0
    epochs_per_round: int = 3
    weight_decay: float = 0.0
    grad_accum_steps: int = 1
    max_grad_norm: float | None = None
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    seed: int = 0
    # Per-step telemetry cadence: every N train steps the fit loops log
    # step, loss, and samples/s (the reference's tqdm per-batch loss line,
    # client1.py:101,112). Each log point syncs the device once; 0 disables
    # (per-epoch averages only).
    log_every: int = 100
    # Dropout-key PRNG implementation. "rbg" (counter-based, the standard
    # TPU choice for dropout masks) is ~10 points of MFU cheaper than
    # "threefry2x32" on the flagship model; both are valid JAX key impls.
    prng_impl: str = "rbg"
    # Which parameters the optimizer updates. "all" (default) is normal
    # training; "head" freezes the encoder and trains only the classifier
    # head (updates zeroed via optax.multi_transform) — the FedPer-style
    # personalization scope, also usable standalone for linear probing of
    # a pretrained encoder.
    trainable: str = "all"
    # FedProx proximal term for the TCP-tier client loop (strategies/):
    # local loss += mu/2 * ||w - w_round_start||^2 against the round's
    # adopted aggregate. 0 = plain local SGD. The SPMD mesh tier carries
    # the same knob as FedConfig.prox_mu (train/fedsteps.py); this one
    # reaches the per-client train-step builders in train/engine.py.
    prox_mu: float = 0.0

    def __post_init__(self) -> None:
        if self.prng_impl not in ("rbg", "threefry2x32", "unsafe_rbg"):
            raise ValueError(f"unknown prng_impl {self.prng_impl!r}")
        if self.trainable not in ("all", "head"):
            raise ValueError(
                f"trainable={self.trainable!r} must be 'all' or 'head'"
            )
        if self.prox_mu < 0.0:
            raise ValueError(f"prox_mu={self.prox_mu} must be >= 0")


@dataclass(frozen=True)
class FedConfig:
    """Federated-round structure.

    The reference runs exactly one FedAvg round per invocation with exactly
    ``NUM_CLIENTS=2`` clients and an unweighted mean (reference server.py:13,
    67-79); multi-round is re-running with warm start (client1.py:375-377).
    Here rounds and client count are first-class, aggregation may be weighted
    by client sample counts, and dropped clients are masked out of the mean
    instead of hanging the round (reference behavior: accept-loop hangs until
    timeout, server.py:69-71,124-132).
    """

    num_clients: int = 2
    rounds: int = 1
    # FedAvg weighting. None (default) = auto: weight by true per-client
    # sample count whenever the counts are known (the ragged stacked path
    # carries them) and DP is off — matching the reference's *semantics*
    # (each client's rows influence the fleet equally) for unequal fleets
    # while reproducing its unweighted mean exactly for equal ones.
    # True = require sample-count weights; False = force the uniform mean
    # (the reference's literal server.py:73-76 arithmetic).
    weighted: bool | None = None
    # FedProx (Li et al.): local loss += mu/2 * ||w - w_round_start||^2,
    # anchoring client drift under non-IID partitions (the dirichlet knob,
    # BASELINE.json config 3). 0 = plain FedAvg, the reference's algorithm.
    prox_mu: float = 0.0
    # Minimum fraction of clients that must survive a round for aggregation
    # to proceed (masked mean over survivors); reference requires all.
    min_client_fraction: float = 1.0
    # Fresh optimizer state each round — mirrors the reference, where every
    # round is a new process with a newly constructed Adam (client1.py:380).
    reset_optimizer_each_round: bool = True
    # Partial participation: fraction of clients whose round contributes to
    # the aggregate (sampled per round, seeded). Under SPMD every replica
    # still computes in lockstep; non-participants' local epochs are simply
    # excluded from the masked mean and overwritten by its result. 1.0 =
    # everyone, the reference's behavior.
    participation: float = 1.0
    # How the per-round cohort is drawn when participation < 1:
    #   "fixed"   — exactly cohort_size() clients without replacement (the
    #               classic FL sampler; the DP accountant's Poisson bound
    #               is then the standard approximation);
    #   "poisson" — each client joins independently with probability
    #               `participation` (variable cohort; the subsampled-
    #               Gaussian accountant's assumption holds EXACTLY);
    #   "auto"    — poisson when DP is on (exact epsilon), fixed otherwise.
    participation_mode: str = "auto"
    # DP-FedAvg (parallel/dp.py): clip each client's round update to this
    # global L2 norm before aggregation. 0 = off (plain FedAvg, the
    # reference's algorithm — which ships raw unclipped state dicts,
    # client1.py:276-295).
    dp_clip: float = 0.0
    # Gaussian-mechanism noise multiplier: noise std on the mean update is
    # noise_multiplier * dp_clip / n_participants. Requires dp_clip > 0.
    dp_noise_multiplier: float = 0.0
    # DP noise seed. None (default, the only private choice): fresh OS
    # entropy per run, agreed across hosts. Setting a value makes the noise
    # reproducible — anyone who knows it can subtract the noise, so it
    # VOIDS the (epsilon, delta) guarantee; tests only.
    dp_seed: int | None = None
    # Server-side optimizer over the round's mean update (FedOpt, Reddi et
    # al.): "none" = plain FedAvg (new global = mean, the reference's
    # algorithm); "momentum" = FedAvgM (heavy-ball over round updates);
    # "adam" = FedAdam and "yogi" = FedYogi (adaptive per-parameter server
    # steps; yogi's additive second moment resists the non-IID variance
    # spikes that swamp adam's EMA). Server state persists across rounds
    # (unlike the per-round client optimizer reset).
    server_opt: str = "none"
    server_lr: float = 1.0
    server_momentum: float = 0.9
    # Personalization (FedAvg + local fine-tuning): after the final round,
    # each client fine-tunes the aggregate on its own shard for this many
    # epochs and is evaluated as a THIRD phase ("personalized") next to
    # the reference's local/aggregated pair. 0 = off. Scope "full"
    # fine-tunes everything (FedAvg+FT); "head" freezes the shared encoder
    # and adapts only the classifier head (FedPer, Arivazhagan et al.).
    personalize_epochs: int = 0
    personalize_scope: str = "full"
    # Survivable fold trees (comm/relay.py): a relay's per-subtree
    # straggler deadline as a fraction of the round budget. Strictly
    # inside (0, 1) — the whole point is that a slow subtree resolves
    # (sheds stragglers locally, or fails its local quorum so its
    # clients re-home) while the root is still inside ITS deadline; a
    # factor >= 1 re-creates the stalled-root failure mode the relay
    # tier exists to remove.
    subtree_deadline_factor: float = 0.5
    # Wire dtype for STREAMED client uploads (comm/wire.py): "fp32" is
    # the exact historical encoding; "bf16" / "int8" quantize each
    # streamed chunk (int8 with a per-4096-element fp32 scale, ~3.98x
    # smaller uploads). Negotiated: the server adverts its decodable
    # encodings in reply meta and the client upgrades one reply behind,
    # so an old peer on either end keeps the fp32 wire. Lossy dtypes are
    # refused alongside secure-agg or compressed uploads; under DP the
    # server re-clips after dequantization (containment).
    wire_dtype: str = "fp32"

    def server_opt_enabled(self) -> bool:
        return self.server_opt != "none"

    def resolve_weighted(self) -> bool:
        """The effective weighting choice: auto (None) weights by sample
        count unless DP needs its uniform mean."""
        if self.weighted is None:
            return self.dp_clip == 0.0
        return self.weighted

    def cohort_size(self) -> int:
        """Clients sampled per round. ceil keeps k >= C * participation
        (round() could land below min_client_fraction via banker's
        rounding) — the SINGLE source of truth shared by the sampler
        (participation_mask) and the DP accountant's effective rate."""
        import math

        if self.participation >= 1.0:
            return self.num_clients
        return min(
            self.num_clients,
            max(1, math.ceil(self.num_clients * self.participation)),
        )

    def effective_participation(self) -> float:
        """The ACTUAL per-round sampling rate ``cohort_size / C`` — what
        the DP accountant must see under the FIXED sampler: ceil rounding
        makes it >= the nominal ``participation`` (e.g. 0.26 of 4 clients
        samples 2/4 = 0.5), and feeding the accountant the nominal
        fraction would overstate the privacy guarantee."""
        return self.cohort_size() / self.num_clients

    def dp_enabled(self) -> bool:
        return self.dp_clip > 0.0 and self.dp_noise_multiplier > 0.0

    def resolve_participation_mode(self) -> str:
        """The effective cohort sampler: "auto" picks poisson when DP is
        on (the accountant's Poisson-sampling assumption then holds
        exactly) and the classic fixed-size sampler otherwise."""
        if self.participation >= 1.0:
            return "fixed"  # everyone participates; no sampling at all
        if self.participation_mode == "auto":
            return "poisson" if self.dp_enabled() else "fixed"
        return self.participation_mode

    def dp_sampling_rate(self) -> tuple[float, bool]:
        """(q for the DP accountant, whether the SGM bound's sampling
        assumption is exact for the sampler in use). Poisson mode: q is
        the nominal participation, exactly the sampler's Bernoulli rate.
        Fixed mode: q = cohort_size/C, the standard approximation."""
        if self.participation >= 1.0:
            return 1.0, True
        if self.resolve_participation_mode() == "poisson":
            return self.participation, True
        return self.effective_participation(), False

    def __post_init__(self) -> None:
        if not 0.0 < self.participation <= 1.0:
            raise ValueError(
                f"participation={self.participation} must be in (0, 1]"
            )
        if self.participation_mode not in ("auto", "fixed", "poisson"):
            raise ValueError(
                f"participation_mode={self.participation_mode!r} must be "
                "'auto', 'fixed' or 'poisson'"
            )
        if self.personalize_epochs < 0:
            raise ValueError(
                f"personalize_epochs={self.personalize_epochs} must be >= 0"
            )
        if self.personalize_scope not in ("full", "head"):
            raise ValueError(
                f"personalize_scope={self.personalize_scope!r} must be "
                "'full' or 'head'"
            )
        if not 0.0 < self.subtree_deadline_factor < 1.0:
            raise ValueError(
                f"subtree_deadline_factor={self.subtree_deadline_factor} "
                "must be in (0, 1): the per-subtree straggler deadline "
                "has to be strictly tighter than the round budget"
            )
        if self.wire_dtype not in ("fp32", "bf16", "int8"):
            raise ValueError(
                f"wire_dtype={self.wire_dtype!r} must be "
                "'fp32', 'bf16' or 'int8'"
            )
        if self.participation < self.min_client_fraction:
            raise ValueError(
                f"participation={self.participation} below "
                f"min_client_fraction={self.min_client_fraction}: every "
                "round would fail its own survivor check — lower "
                "min_client_fraction to at most the participation rate"
            )
        if self.dp_clip < 0.0:
            raise ValueError(f"dp_clip={self.dp_clip} must be >= 0")
        if self.dp_noise_multiplier < 0.0:
            raise ValueError(
                f"dp_noise_multiplier={self.dp_noise_multiplier} must be >= 0"
            )
        if self.dp_noise_multiplier > 0.0 and self.dp_clip == 0.0:
            raise ValueError(
                "dp_noise_multiplier > 0 requires dp_clip > 0: the noise "
                "std is calibrated to the clip norm (sensitivity)"
            )
        if self.dp_clip > 0.0 and self.weighted:
            raise ValueError(
                "dp_clip > 0 is incompatible with weighted FedAvg: the DP "
                "sensitivity bound assumes a uniform mean over participants"
            )
        if self.server_opt not in ("none", "momentum", "adam", "yogi"):
            raise ValueError(
                f"unknown server_opt {self.server_opt!r} "
                "(none|momentum|adam|yogi)"
            )
        if self.server_lr <= 0.0:
            raise ValueError(f"server_lr={self.server_lr} must be > 0")
        if not 0.0 <= self.server_momentum < 1.0:
            raise ValueError(
                f"server_momentum={self.server_momentum} must be in [0, 1) "
                "(a decay >= 1 amplifies every round update geometrically)"
            )


@dataclass(frozen=True)
class DistillConfig:
    """Knowledge distillation (teacher -> student).

    The reference consumes a pre-distilled encoder (HF DistilBERT,
    client1.py:56) but has no distillation capability of its own. Here the
    DistilBERT recipe is first-class: soft-target KL at temperature T plus
    hard-label CE, with the student optionally initialized from every other
    teacher layer (the published DistilBERT init).
    """

    temperature: float = 2.0
    # Loss = alpha * T^2 * KL(teacher || student) + (1 - alpha) * CE(labels).
    alpha: float = 0.5
    # Initialize the student from evenly-strided teacher layers (DistilBERT
    # init: 12 -> 6 layers takes every other one). The stride is derived as
    # teacher_layers // student_layers by DistillTrainer.init_student_state,
    # not configured here; widths must match (depth-only distillation).
    init_from_teacher: bool = True

    def __post_init__(self) -> None:
        if not 0.0 <= self.alpha <= 1.0:
            raise ValueError(f"alpha={self.alpha} must be in [0, 1]")
        if self.temperature <= 0.0:
            raise ValueError(f"temperature={self.temperature} must be > 0")


@dataclass(frozen=True)
class ControlConfig:
    """Control-plane loop (control/ + registry/): the knobs of the
    unattended train -> gate -> promote -> serve -> monitor cycle.

    The reference has no loop at all — a round happens when a human
    re-runs three scripts, and nothing gates what the serving tier loads.
    """

    # Eval gate: a candidate must score >= incumbent[metric] - min_delta
    # on the held-out split or it is rejected (the serving pointer stays
    # on the incumbent — automatic rollback-by-refusal).
    gate_metric: str = "Accuracy"
    gate_min_delta: float = 0.0
    # Round cadence. min_interval_s throttles back-to-back rounds;
    # max_interval_s forces a round even when no drift fired (None = no
    # clock at all — purely drift-triggered once a monitor is attached).
    min_interval_s: float = 0.0
    max_interval_s: float | None = None
    # Drift monitor (control/drift.py): score-distribution shift of live
    # serving traffic vs the promoted artifact's eval reference
    # histogram. PSI > 0.25 is the classic "significant shift" bound.
    drift_method: str = "psi"  # psi | ks
    drift_threshold: float = 0.25
    drift_min_scores: int = 256
    # Histogram resolution for both the eval reference and the serving
    # tier's score export; both sides must agree.
    score_bins: int = 10
    # Per-round deadline handed to the TCP round engine (None = the
    # server's own timeout).
    round_deadline_s: float | None = None
    # Registry GC budget: after every promotion/rejection the controller
    # prunes oldest RETIRED/REJECTED artifacts beyond this count (the
    # serving artifact and its rollback chain are never pruned —
    # registry/store.py gc()). None (default) keeps everything.
    max_artifacts: int | None = None
    # Adaptive cadence (control/drift.py cadence_interval_s): a fired
    # drift verdict's MAGNITUDE scales the next inter-round interval
    # between min_interval_s (drift >= 2x threshold: urgent) and
    # max_interval_s (barely over threshold: relaxed). Needs both bounds
    # configured; the chosen interval rides the drift-trigger span.
    adaptive_cadence: bool = False
    # SLO-driven actuation: while a round-duration burn alert FIRES on
    # the tailed alerts-JSONL (controller --slo-alerts-jsonl), the
    # round's straggler deadline is multiplied by this factor — a fleet
    # already blowing its round SLO should cut stragglers loose sooner,
    # not wait the full budget on them. 1.0 disables the tightening.
    slo_deadline_factor: float = 0.5
    # Drift-scaled cohort (control/drift.py drift_cohort_fraction): a
    # fired drift verdict's MAGNITUDE picks the corrective round's
    # quorum between cohort_min_frac (barely over threshold: a lean,
    # fast cohort) and cohort_max_frac (>= 2x threshold: the full
    # quorum's evidence) of the server's configured min_clients — for
    # ONE round, then the base quorum restores.
    drift_cohort: bool = False
    cohort_min_frac: float = 0.5
    cohort_max_frac: float = 1.0

    def __post_init__(self) -> None:
        if not 0.0 < self.slo_deadline_factor <= 1.0:
            raise ValueError(
                f"slo_deadline_factor={self.slo_deadline_factor} must be "
                "in (0, 1] (1 = no tightening)"
            )
        if self.max_artifacts is not None and self.max_artifacts < 1:
            raise ValueError(
                f"max_artifacts={self.max_artifacts} must be >= 1 "
                "(or None to keep everything)"
            )
        if self.drift_method not in ("psi", "ks"):
            raise ValueError(
                f"drift_method={self.drift_method!r} must be 'psi' or 'ks'"
            )
        if self.drift_threshold <= 0.0:
            raise ValueError(
                f"drift_threshold={self.drift_threshold} must be > 0"
            )
        if self.drift_min_scores < 1:
            raise ValueError(
                f"drift_min_scores={self.drift_min_scores} must be >= 1"
            )
        if not 2 <= self.score_bins <= 64:
            # Upper bound matches the metrics-JSONL short-list cap
            # (reporting.append_metrics_jsonl keeps lists <= 64 entries):
            # a larger histogram would be silently dropped from every
            # serve_batch record and starve the drift monitor.
            raise ValueError(
                f"score_bins={self.score_bins} must be in [2, 64]"
            )
        if self.min_interval_s < 0.0:
            raise ValueError(
                f"min_interval_s={self.min_interval_s} must be >= 0"
            )
        if (
            self.max_interval_s is not None
            and self.max_interval_s < self.min_interval_s
        ):
            raise ValueError(
                f"max_interval_s={self.max_interval_s} below "
                f"min_interval_s={self.min_interval_s}"
            )
        if not 0.0 < self.cohort_min_frac <= 1.0:
            raise ValueError(
                f"cohort_min_frac={self.cohort_min_frac} must be in (0, 1]"
            )
        if not 0.0 < self.cohort_max_frac <= 1.0:
            raise ValueError(
                f"cohort_max_frac={self.cohort_max_frac} must be in (0, 1]"
            )
        if self.cohort_max_frac < self.cohort_min_frac:
            raise ValueError(
                f"cohort_max_frac={self.cohort_max_frac} below "
                f"cohort_min_frac={self.cohort_min_frac}"
            )


@dataclass(frozen=True)
class LabelsConfig:
    """Delayed ground-truth plane (labels/): the journal of late-arriving
    verdicts about what each scored flow actually WAS, the deterministic
    join against what the models ANSWERED, and the supervised promotion
    rung the join feeds. The reference has no feedback path at all once
    a model serves — nothing ever tells it it was wrong."""

    #: Ground-truth journal override (default:
    #: ``<registry>/labels/journal.jsonl`` — labels/store.journal_path).
    journal: str | None = None
    #: Decision threshold the join applies to both models' probabilities.
    threshold: float = 0.5
    #: Minimum joined (labeled) flows before the supervised gate may
    #: rule; fewer FAILS CLOSED.
    min_joined: int = 32
    #: Minimum joined/total coverage of the scored population; below it
    #: the gate FAILS CLOSED (a verdict over a sliver is noise).
    coverage_floor: float = 0.05
    #: Max tolerated candidate-over-serving supervised error excess.
    max_regression: float = 0.0
    #: Supervised drift margin (control/drift.py ErrorRateMonitor): the
    #: serving model's joined error rising this far past its promoted
    #: reference fires a corrective round.
    error_margin: float = 0.05
    #: Joined observations the error monitor needs before it may fire.
    error_min_joined: int = 64

    def __post_init__(self) -> None:
        if not 0.0 < self.threshold < 1.0:
            raise ValueError(
                f"threshold={self.threshold} must be in (0, 1)"
            )
        if self.min_joined < 1:
            raise ValueError(f"min_joined={self.min_joined} must be >= 1")
        if not 0.0 <= self.coverage_floor <= 1.0:
            raise ValueError(
                f"coverage_floor={self.coverage_floor} must be in [0, 1]"
            )
        if self.max_regression < 0.0:
            raise ValueError(
                f"max_regression={self.max_regression} must be >= 0"
            )
        if self.error_margin <= 0.0:
            raise ValueError(
                f"error_margin={self.error_margin} must be > 0"
            )
        if self.error_min_joined < 1:
            raise ValueError(
                f"error_min_joined={self.error_min_joined} must be >= 1"
            )


@dataclass(frozen=True)
class ShadowConfig:
    """Shadow evaluation plane (shadow/): mirror a sampled fraction of
    live scoring traffic onto the registry's ``shadow``-state artifact
    and gate promotion on the measured live disagreement instead of
    offline eval alone. The reference (and the pre-shadow controller)
    promotes on held-out metrics only — exactly the gate that misses
    live-distribution drift."""

    #: Mirror stride: duplicate one live request in ``sample`` onto the
    #: shadow backend (deterministic counter stride, no RNG — the
    #: serve-batch trace-sampling discipline). 0 = shadow plane off.
    sample: int = 0
    #: Minimum mirrored pairs before the gate may rule; fewer at timeout
    #: FAILS CLOSED (the candidate is rejected, the pointer never moves).
    min_pairs: int = 256
    #: Max tolerated fraction of pairs whose thresholded prediction
    #: flipped between serving and shadow.
    max_flip_rate: float = 0.02
    #: Max tolerated PSI between the paired serving/shadow score
    #: histograms (the drift monitor's distance, same 0.25 lore).
    psi_threshold: float = 0.25
    #: Gate patience: how long the controller waits for the evidence.
    timeout_s: float = 600.0
    #: Seconds between the gate's status polls.
    poll_s: float = 0.5
    #: Prediction threshold the flip comparison applies on both sides.
    threshold: float = 0.5
    #: Histogram bins for the paired score distributions (must match the
    #: drift tier's resolution so PSI thresholds transfer).
    bins: int = 10
    #: Mirror queue bound: a full queue drops the mirror COPY — never
    #: delays or fails the live request.
    queue: int = 256

    def __post_init__(self) -> None:
        if self.sample < 0:
            raise ValueError(f"sample={self.sample} must be >= 0 (0 = off)")
        if self.min_pairs < 1:
            raise ValueError(f"min_pairs={self.min_pairs} must be >= 1")
        if not 0.0 <= self.max_flip_rate <= 1.0:
            raise ValueError(
                f"max_flip_rate={self.max_flip_rate} must be in [0, 1]"
            )
        if self.psi_threshold <= 0.0:
            raise ValueError(
                f"psi_threshold={self.psi_threshold} must be > 0"
            )
        if self.timeout_s <= 0.0:
            raise ValueError(f"timeout_s={self.timeout_s} must be > 0")
        if self.poll_s <= 0.0:
            raise ValueError(f"poll_s={self.poll_s} must be > 0")
        if not 0.0 < self.threshold < 1.0:
            raise ValueError(
                f"threshold={self.threshold} must be in (0, 1)"
            )
        if not 2 <= self.bins <= 64:
            raise ValueError(f"bins={self.bins} must be in [2, 64]")
        if self.queue < 1:
            raise ValueError(f"queue={self.queue} must be >= 1")


@dataclass(frozen=True)
class RouterConfig:
    """Serving replica fleet (router/): the knobs of the thin router and
    the rolling hot-reload manager behind ``fedtpu route`` / ``fedtpu
    fleet``. The reference serves nothing at all; the single-process
    ``infer-serve`` tier serves from one scorer — these knobs govern the
    tier that scales past it."""

    #: Local replicas ``fedtpu fleet`` spawns behind the router.
    replicas: int = 3
    #: Seconds between in-band stats() health probes per replica.
    probe_interval_s: float = 1.0
    #: Unanswered-probe age that ejects a replica from the pick set.
    probe_timeout_s: float = 5.0
    #: Rolling reload: how long to wait for one replica's in-flight
    #: requests to finish before swapping anyway.
    drain_timeout_s: float = 30.0
    #: Seconds between serving-pointer polls by the fleet manager.
    reload_poll_s: float = 2.0
    #: Router-side admission bound: a replica at this many in-flight
    #: requests leaves the pick set until replies drain it.
    max_inflight_per_replica: int = 1024

    def __post_init__(self) -> None:
        if self.replicas < 1:
            raise ValueError(f"replicas={self.replicas} must be >= 1")
        if self.probe_interval_s <= 0.0:
            raise ValueError(
                f"probe_interval_s={self.probe_interval_s} must be > 0"
            )
        if self.probe_timeout_s <= 0.0:
            raise ValueError(
                f"probe_timeout_s={self.probe_timeout_s} must be > 0"
            )
        if self.drain_timeout_s < 0.0:
            raise ValueError(
                f"drain_timeout_s={self.drain_timeout_s} must be >= 0"
            )
        if self.reload_poll_s <= 0.0:
            raise ValueError(
                f"reload_poll_s={self.reload_poll_s} must be > 0"
            )
        if self.max_inflight_per_replica < 1:
            raise ValueError(
                f"max_inflight_per_replica={self.max_inflight_per_replica} "
                "must be >= 1"
            )


@dataclass(frozen=True)
class ObsConfig:
    """Observability (obs/): cross-tier round tracing + /metrics.

    The reference's observability is timestamped prints and one-row CSVs
    (SURVEY.md §5). These knobs configure the structured upgrade; the
    matching CLI flags (``--trace-jsonl``, ``--metrics-port``) override
    per process.
    """

    #: Span events-JSONL path for THIS process (obs.trace.Tracer). None
    #: (default) = tracing off. Give every process its own file; `fedtpu
    #: obs timeline --trace-dir` merges a directory of them.
    trace_jsonl: str | None = None
    #: Prometheus text endpoint port (stdlib HTTP, GET /metrics). 0
    #: (default) = off — the endpoint binds nothing unless asked.
    metrics_port: int = 0
    #: Run identity stamped on every span and metrics record. None =
    #: FEDTPU_RUN_ID env var, else a fresh per-process id.
    run_id: str | None = None
    #: Span sampling rate for HIGH-RATE span streams (today: the serving
    #: tier's per-coalesced-batch ``serve-batch`` spans): emit one span
    #: per ~1/rate batches via a deterministic batch-counter stride (no
    #: RNG — reruns sample identically), each carrying
    #: ``sampled_batches`` so consumers can re-scale. 1.0 = every batch.
    #: Round-scoped spans (round/agg/wire-*) are never sampled — they
    #: are one-per-round by construction.
    trace_sample: float = 1.0
    #: Failure flight recorder (obs/flight.py): postmortem bundles land
    #: in this directory on round failure / eject storm / SLO page.
    #: None (default) = recorder off — no ring, no hot-path cost. The
    #: matching CLI flag is ``--flight-dir``.
    flight_dir: str | None = None
    #: Span-ring depth the flight recorder retains per process.
    flight_ring: int = 256
    #: Device performance plane (obs/profile.py): sample every Nth
    #: train/score step with fenced host/dispatch/device timers
    #: (``fedtpu_*_step_seconds`` histograms + span attrs). 0 (default)
    #: = off — the hot loops run the literal unprofiled path (no
    #: fences, no timer reads). The matching CLI flag is
    #: ``--profile-stride``; a deterministic counter stride, no RNG.
    profile_stride: int = 0
    #: Snapshot-JSONL retention cap in MB for the scrape hub / sentinel
    #: (``--snapshot-max-mb``): past this size the live file atomically
    #: rolls to ``<path>.1`` (at most ~2x the cap on disk). None
    #: (default) = unbounded, the pre-existing behavior.
    snapshot_max_mb: float | None = None
    #: Sentinel cadence (obs/sentinel.py): seconds between ticks of the
    #: ``fedtpu obs sentinel`` watch loop.
    sentinel_interval_s: float = 5.0
    #: Long-horizon retention ring rows kept (memory + --ring-jsonl).
    sentinel_ring_records: int = 512
    #: Ring rows pinned as the regression baseline window (the FIRST N
    #: retained — "how the fleet looked when watching began").
    sentinel_baseline_n: int = 8
    #: Current-window rows a trend check averages against the baseline.
    sentinel_window_n: int = 8
    #: A watched field regresses when its current-window mean moves past
    #: baseline * ratio (+ the field's absolute floor); round cadence
    #: fires on the inverse drop.
    sentinel_regression_ratio: float = 1.5

    def __post_init__(self) -> None:
        if not 0 <= self.metrics_port <= 65535:
            raise ValueError(
                f"metrics_port={self.metrics_port} must be a port in "
                "[0, 65535] (0 = off)"
            )
        if not 0.0 < self.trace_sample <= 1.0:
            raise ValueError(
                f"trace_sample={self.trace_sample} must be in (0, 1]"
            )
        if self.flight_ring < 1:
            raise ValueError(
                f"flight_ring={self.flight_ring} must be >= 1"
            )
        if self.profile_stride < 0:
            raise ValueError(
                f"profile_stride={self.profile_stride} must be >= 0 "
                "(0 = off)"
            )
        if self.snapshot_max_mb is not None and self.snapshot_max_mb <= 0:
            raise ValueError(
                f"snapshot_max_mb={self.snapshot_max_mb} must be > 0 "
                "(None = unbounded)"
            )
        if self.sentinel_interval_s <= 0:
            raise ValueError(
                f"sentinel_interval_s={self.sentinel_interval_s} must "
                "be > 0"
            )
        if self.sentinel_ring_records < max(
            self.sentinel_baseline_n, self.sentinel_window_n
        ):
            raise ValueError(
                f"sentinel_ring_records={self.sentinel_ring_records} "
                "must hold at least the baseline "
                f"({self.sentinel_baseline_n}) and current "
                f"({self.sentinel_window_n}) windows"
            )
        if self.sentinel_regression_ratio <= 1.0:
            raise ValueError(
                f"sentinel_regression_ratio="
                f"{self.sentinel_regression_ratio} must be > 1 (it "
                "multiplies the baseline mean)"
            )


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout.

    axes: ``clients`` — federated replicas (FedAvg collective rides this axis);
    ``data`` — per-client batch parallelism (grad psum rides this axis).
    A 1-sized axis is dropped from the physical mesh automatically.
    """

    clients: int = 2
    data: int = 1
    # Sequence-parallel axis (ring attention): >1 adds a third ``seq`` mesh
    # axis and routes `federated` through FedSeqTrainer (--seq-parallel N).
    # On the TCP tier, `client --data-parallel/--seq-parallel` reuses the
    # data/seq axes as that host's LOCAL mesh (train/client_mesh.py); the
    # clients axis is the wire there, not a mesh dimension.
    seq: int = 1
    # FSDP shard-at-rest on the TCP client's local mesh (`client
    # --data-parallel N --fsdp`, train/client_mesh.py FsdpMeshTrainer):
    # params AND optimizer state shard per-leaf over the `data` axis
    # (all-gather at use inside the jitted step, backward re-gathers via
    # remat, grads reduce-scatter) so per-chip static bytes scale ~1/N —
    # the big-model-client mode. Trajectory matches the replicated mesh
    # to fp32 reduction-order ulps.
    fsdp: bool = False
    axis_names: tuple[str, str] = ("clients", "data")

    def __post_init__(self) -> None:
        if self.clients < 1 or self.data < 1:
            raise ValueError(
                f"mesh axes must be >= 1 (clients={self.clients}, "
                f"data={self.data})"
            )
        if self.seq < 1:
            raise ValueError(f"mesh.seq={self.seq} must be >= 1")
        if self.fsdp and self.data < 2:
            raise ValueError(
                "mesh.fsdp needs data >= 2 (--data-parallel N): sharding "
                "the static state over one device is a no-op"
            )
        if self.fsdp and self.seq > 1:
            raise ValueError(
                "mesh.fsdp does not compose with seq > 1: the C=1 fedseq "
                "trainer owns the 3-axis layout (sharded-scorer/fedseq "
                "FSDP is the ROADMAP follow-up)"
            )


@dataclass(frozen=True)
class ExperimentConfig:
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    fed: FedConfig = field(default_factory=FedConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    distill: DistillConfig = field(default_factory=DistillConfig)
    control: ControlConfig = field(default_factory=ControlConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    router: RouterConfig = field(default_factory=RouterConfig)
    shadow: ShadowConfig = field(default_factory=ShadowConfig)
    labels: LabelsConfig = field(default_factory=LabelsConfig)
    output_dir: str = "outputs"
    checkpoint_dir: str | None = None

    def __post_init__(self) -> None:
        # Logical clients may exceed the mesh's clients axis (several client
        # replicas per device shard) but must tile it evenly.
        if self.fed.num_clients % self.mesh.clients:
            raise ValueError(
                f"fed.num_clients={self.fed.num_clients} must be a multiple of "
                f"mesh.clients={self.mesh.clients}; use ExperimentConfig.for_clients(n)"
            )
        if self.data.max_len != self.model.max_len:
            raise ValueError(
                f"data.max_len={self.data.max_len} != model.max_len="
                f"{self.model.max_len}: tokenized sequences must match the "
                "position-embedding table"
            )

    @classmethod
    def for_clients(cls, num_clients: int, data_parallel: int = 1, **kw: Any) -> "ExperimentConfig":
        """Consistent config for an N-client fleet on a clients×data mesh."""
        kw.setdefault("fed", FedConfig(num_clients=num_clients))
        kw.setdefault(
            "mesh", MeshConfig(clients=num_clients, data=data_parallel)
        )
        if kw["fed"].num_clients != num_clients:
            kw["fed"] = dataclasses.replace(kw["fed"], num_clients=num_clients)
        return cls(**kw)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ExperimentConfig":
        sections = {
            "model": ModelConfig,
            "data": DataConfig,
            "train": TrainConfig,
            "fed": FedConfig,
            "mesh": MeshConfig,
            "distill": DistillConfig,
            "control": ControlConfig,
            "obs": ObsConfig,
            "router": RouterConfig,
            "shadow": ShadowConfig,
            "labels": LabelsConfig,
        }
        scalars = ("output_dir", "checkpoint_dir")
        unknown_top = set(d) - set(sections) - set(scalars)
        if unknown_top:
            raise ValueError(f"unknown config sections: {sorted(unknown_top)}")

        def _mk(tp, key):
            sub = dict(d.get(key, {}))
            names = {f.name for f in dataclasses.fields(tp)}
            unknown = set(sub) - names
            if unknown:
                raise ValueError(f"unknown {key} config keys: {sorted(unknown)}")
            # JSON round-trips tuples as lists; restore tuple-typed fields so
            # frozen dataclasses stay hashable and equality survives to_dict().
            for k, v in sub.items():
                if isinstance(v, list):
                    sub[k] = tuple(v)
            return tp(**sub)

        kw: dict[str, Any] = {key: _mk(tp, key) for key, tp in sections.items()}
        for scalar in scalars:
            if scalar in d:
                kw[scalar] = d[scalar]
        return cls(**kw)

    @classmethod
    def from_checkpoint_dict(cls, d: Mapping[str, Any]) -> "ExperimentConfig":
        """``from_dict`` for a checkpoint's *recorded* config, applying the
        library defaults that were in force when old checkpoints were saved
        rather than today's: configs that predate the ``gelu`` field were
        trained under the then-default erf GELU, so an absent key means
        "exact", not the current ``tanh`` default."""
        model = dict(d.get("model", {}))
        if "gelu" not in model:
            model["gelu"] = "exact"
        out = dict(d)
        out["model"] = model
        return cls.from_dict(out)
