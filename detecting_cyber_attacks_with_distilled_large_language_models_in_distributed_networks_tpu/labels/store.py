"""Append-only ground-truth journal: ``fedtpu-label-v1`` JSONL.

Ground truth for DDoS flows arrives LATE — from incident review, abuse
reports, honeypot confirmation — hours after the scoring tier answered,
out of order, sometimes twice, sometimes contradicting an earlier
verdict. The journal is built for exactly that arrival discipline:

* every ingested label is one ATOMIC appended line (the obs/trace.py
  append discipline — concurrent writers can never interleave partial
  lines), keyed by the request id (``rid``) the serving tier stamps on
  every scored flow;
* in-memory state is a last-writer-wins map by the CALLER-SUPPLIED
  label timestamp: a duplicate (same label) counts on ``duplicates``, a
  conflicting re-label counts on ``conflicts`` and the newer timestamp
  wins (a strictly-older conflicting arrival is counted but does not
  overwrite);
* a monotone **watermark** — "labels are complete through T" — is an
  explicit journal record, never inferred: labels arriving with
  ``ts <= watermark`` still apply but count on ``late`` (evidence the
  upstream labeler's completeness promise was optimistic, and the
  reason the join layer reports coverage instead of trusting it);
* ``load()`` replays the journal tolerating torn tails and foreign
  lines, so a store can be rebuilt from the file by any process (the
  gate, the CLI, the drift monitor) without coordination beyond the
  filesystem.

Timestamps are caller-supplied throughout: this module sits inside the
determinism-rule scope (analysis/determinism_rules.py) — replaying a
journal must rebuild bit-identical state, so nothing here reads a
clock.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Iterator

from ..obs import metrics as obs_metrics
from ..obs.trace import append_jsonl_line
from ..utils.logging import get_logger

log = get_logger()

#: Schema tag on every journal line, so stream consumers can reject
#: foreign JSONL lines when files get concatenated.
LABEL_SCHEMA = "fedtpu-label-v1"


def labels_dir(registry_root: str) -> str:
    """Where the ground-truth plane's files land (under the registry
    root — the control plane's one coordination directory)."""
    return os.path.join(os.path.abspath(registry_root), "labels")


def journal_path(registry_root: str) -> str:
    return os.path.join(labels_dir(registry_root), "journal.jsonl")


class LabelStore:
    """The journal plus its replayable in-memory projection.

    ``ingest``/``advance_watermark`` append one line and apply it;
    ``load`` replays an existing journal through the SAME apply path,
    so a store rebuilt from disk is bit-identical to the one that wrote
    it (the determinism contract the crc scope pins)."""

    def __init__(self, path: str, *, tracer=None):
        self.path = os.path.abspath(path)
        self.tracer = tracer
        self._lock = threading.Lock()
        # rid -> (ts, label): last-writer-wins by caller-supplied ts.
        self._labels: dict[str, tuple[float, int]] = {}
        self._watermark: float | None = None
        self._ingested = 0
        self._duplicates = 0
        self._conflicts = 0
        self._late = 0
        m = obs_metrics.default_registry()
        self._m_ingested = m.counter(
            "fedtpu_labels_ingested_total",
            help="ground-truth label records applied to the journal",
        )
        self._m_conflicts = m.counter(
            "fedtpu_labels_conflicts_total",
            help="label arrivals that contradicted an earlier label "
            "for the same request id (last-writer-wins by ts)",
        )
        self._m_late = m.counter(
            "fedtpu_labels_late_total",
            help="label arrivals timestamped at or before the "
            "completeness watermark",
        )

    # ------------------------------------------------------------- ingestion
    def ingest(self, rid: str, label: int, *, ts: float) -> bool:
        """Journal + apply one ground-truth label.

        ``ts`` is the labeler's timestamp (caller-supplied — nothing in
        this module reads a clock). Returns True when the label changed
        the projection (new rid, or a conflicting newer arrival)."""
        rec = {
            "schema": LABEL_SCHEMA,
            "rid": str(rid),
            "label": int(label),
            "ts": float(ts),
        }
        append_jsonl_line(self.path, json.dumps(rec))
        return self._apply_label(rec)

    def advance_watermark(self, ts: float) -> float:
        """Journal + apply "labels are complete through ``ts``".

        Monotone: an older watermark never rewinds a newer one (the
        record is still journaled — replay sees the same sequence)."""
        rec = {"schema": LABEL_SCHEMA, "watermark": float(ts)}
        append_jsonl_line(self.path, json.dumps(rec))
        self._apply_watermark(rec)
        with self._lock:
            return float(self._watermark or 0.0)

    def _apply_label(self, rec: dict) -> bool:
        rid = str(rec["rid"])
        label = int(rec["label"])
        ts = float(rec["ts"])
        with self._lock:
            if self._watermark is not None and ts <= self._watermark:
                self._late += 1
                self._m_late.inc()
            prev = self._labels.get(rid)
            if prev is None:
                self._labels[rid] = (ts, label)
                self._ingested += 1
                self._m_ingested.inc()
                return True
            if prev[1] == label:
                self._duplicates += 1
                return False
            self._conflicts += 1
            self._m_conflicts.inc()
            if ts >= prev[0]:
                # Last-writer-wins: the newer labeler verdict stands.
                self._labels[rid] = (ts, label)
                return True
            return False

    def _apply_watermark(self, rec: dict) -> None:
        ts = float(rec["watermark"])
        with self._lock:
            if self._watermark is None or ts > self._watermark:
                self._watermark = ts

    def load(self) -> int:
        """Replay the journal from disk (tolerating a torn tail and
        foreign JSONL lines). Returns the number of applied records."""
        applied = 0
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail / foreign writer
                    if not isinstance(rec, dict) or (
                        rec.get("schema") != LABEL_SCHEMA
                    ):
                        continue
                    if "watermark" in rec:
                        self._apply_watermark(rec)
                        applied += 1
                    elif "rid" in rec and "label" in rec and "ts" in rec:
                        self._apply_label(rec)
                        applied += 1
        except OSError:
            return 0
        return applied

    # --------------------------------------------------------------- readers
    @property
    def watermark(self) -> float | None:
        with self._lock:
            return self._watermark

    def get(self, rid: str) -> int | None:
        with self._lock:
            hit = self._labels.get(str(rid))
            return None if hit is None else hit[1]

    def labels_map(self) -> dict[str, int]:
        """rid -> label snapshot, sorted by rid (a deterministic
        iteration order for every downstream join/fold)."""
        with self._lock:
            items = sorted(self._labels.items())
        return {rid: label for rid, (_ts, label) in items}

    def __len__(self) -> int:
        with self._lock:
            return len(self._labels)

    def __iter__(self) -> Iterator[str]:
        with self._lock:
            rids = sorted(self._labels)
        return iter(rids)

    def status(self) -> dict[str, Any]:
        with self._lock:
            return {
                "schema": LABEL_SCHEMA,
                "path": self.path,
                "labels": len(self._labels),
                "watermark": self._watermark,
                "ingested": self._ingested,
                "duplicates": self._duplicates,
                "conflicts": self._conflicts,
                "late": self._late,
            }
