"""Delayed ground-truth plane (ISSUE 18).

A DDoS platform eventually learns which flows were attacks — hours or
days after the scoring tier answered. This package turns that delayed
signal into a first-class control-plane input:

* :mod:`labels.store` — an append-only, atomically-written ground-truth
  journal (``fedtpu-label-v1`` JSONL) keyed by the request ids the
  serving tier stamps, tolerant of late / out-of-order / duplicate /
  conflicting arrivals, with a monotone "labels complete through T"
  watermark;
* :mod:`labels.join` — a deterministic join of scored-request records
  (shadow mirror pairs, serving scored-JSONL) against the journal,
  producing per-model supervised verdicts (accuracy / FPR / FNR,
  per-class counts) with coverage accounting, plus the supervised
  promotion gate (:class:`LabelGate`) the controller stacks on top of
  the unsupervised shadow gate.
"""

from .join import (
    JOINED_SCHEMA,
    LabelGate,
    evaluate_supervised,
    join_records,
    supervised_verdict,
)
from .store import (
    LABEL_SCHEMA,
    LabelStore,
    journal_path,
    labels_dir,
)

__all__ = [
    "JOINED_SCHEMA",
    "LABEL_SCHEMA",
    "LabelGate",
    "LabelStore",
    "evaluate_supervised",
    "join_records",
    "journal_path",
    "labels_dir",
    "supervised_verdict",
]
