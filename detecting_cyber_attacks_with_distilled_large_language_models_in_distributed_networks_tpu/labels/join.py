"""Deterministic join of scored-request records against ground truth.

The serving tier records what each model ANSWERED (the shadow plane's
paired records carry both the incumbent's and the candidate's
probability for the same live flow, keyed by the request id; the
optional scored-JSONL carries the serving answer alone). The journal
(labels/store.py) records what the flow actually WAS. This module joins
the two streams by request id and turns the intersection into the
supervised evidence the unsupervised plane cannot produce:

* per-model verdicts — accuracy, FPR, FNR over the joined set, plus
  per-class ground-truth counts (the K-class plane: class 0 is benign,
  any other class is an attack, so the binary decision arithmetic holds
  for every K);
* **coverage accounting** — joined / total scored records. Delayed
  labels mean the join is always partial; a gate that ruled on three
  joined flows out of ten thousand would be noise wearing a verdict's
  clothes, so :func:`evaluate_supervised` FAILS CLOSED below a floor;
* the supervised promotion rung — :class:`LabelGate` reads a
  candidate's mirror pairs and the journal from the registry directory
  (the control plane's one coordination surface) and rules
  candidate-vs-serving error. A candidate that flips nothing (clean
  flip-rate/PSI) but is WRONG where the incumbent was right is exactly
  the regression flip-rate cannot see — both models confidently agree
  on the wrong answer only when the candidate never disagrees, so the
  supervised rung compares each side against truth instead of against
  each other.

Everything here is pure (records in, verdicts out) and sits inside the
determinism-rule scope: same journal + same pairs file -> bit-identical
report, no clock reads in the join arithmetic.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Iterable, Mapping, Sequence

from ..utils.logging import get_logger
from .store import LabelStore, journal_path

log = get_logger()

#: Schema tag on rendered join reports.
JOINED_SCHEMA = "fedtpu-labeljoin-v1"


def supervised_verdict(
    joined: Sequence[tuple[int, int]],
) -> dict[str, Any]:
    """Binary decision metrics over ``(pred, label)`` pairs.

    ``label`` may be K-class: class 0 is benign, everything else is an
    attack, so TP/FP/FN/TN reduce over ``label != 0`` while
    ``per_class`` keeps the full K-class ground-truth histogram."""
    tp = fp = fn = tn = 0
    per_class: dict[int, int] = {}
    for pred, label in joined:
        attack = int(label) != 0
        per_class[int(label)] = per_class.get(int(label), 0) + 1
        if pred and attack:
            tp += 1
        elif pred and not attack:
            fp += 1
        elif not pred and attack:
            fn += 1
        else:
            tn += 1
    n = tp + fp + fn + tn
    return {
        "n": n,
        "tp": tp,
        "fp": fp,
        "fn": fn,
        "tn": tn,
        "accuracy": ((tp + tn) / n) if n else None,
        "error": ((fp + fn) / n) if n else None,
        "fpr": (fp / (fp + tn)) if (fp + tn) else None,
        "fnr": (fn / (fn + tp)) if (fn + tp) else None,
        "per_class": {str(k): per_class[k] for k in sorted(per_class)},
    }


def join_records(
    records: Iterable[Mapping[str, Any]],
    labels: Mapping[str, int],
    *,
    threshold: float = 0.5,
    sides: Mapping[str, str] = (
        ("serving", "serving_prob"),
        ("candidate", "shadow_prob"),
    ),
) -> dict[str, Any]:
    """Join scored records against a rid -> label map.

    ``sides`` names each model's probability field on the record
    (shadow pair records carry ``serving_prob``/``shadow_prob``; the
    serving tier's scored-JSONL carries ``prob`` alone — pass
    ``sides={"serving": "prob"}``). A record joins when it carries a
    ``rid`` present in ``labels`` and at least one side's probability.
    Records without a rid count toward ``total`` (they were scored; the
    serving tier just wasn't exporting ids) — coverage is honest about
    the whole scored population, not the joinable subset."""
    side_items = (
        tuple(sides.items()) if isinstance(sides, Mapping) else tuple(sides)
    )
    thr = float(threshold)
    total = 0
    joined_n = 0
    per_side: dict[str, list[tuple[int, int]]] = {
        name: [] for name, _key in side_items
    }
    per_candidate: dict[str, int] = {}
    for rec in records:
        total += 1
        rid = rec.get("rid")
        if rid is None:
            continue
        label = labels.get(str(rid))
        if label is None:
            continue
        hit = False
        for name, key in side_items:
            prob = rec.get(key)
            if prob is None:
                continue
            per_side[name].append((int(float(prob) >= thr), int(label)))
            hit = True
        if hit:
            joined_n += 1
            cand = rec.get("cand")
            if cand is not None:
                per_candidate[str(cand)] = per_candidate.get(str(cand), 0) + 1
    report: dict[str, Any] = {
        "schema": JOINED_SCHEMA,
        "total": total,
        "joined": joined_n,
        "coverage": (joined_n / total) if total else 0.0,
        "threshold": thr,
        "models": {
            name: supervised_verdict(per_side[name])
            for name, _key in side_items
        },
    }
    if per_candidate:
        report["per_candidate_joined"] = {
            k: per_candidate[k] for k in sorted(per_candidate)
        }
    return report


def evaluate_supervised(
    report: Mapping[str, Any],
    *,
    min_joined: int,
    coverage_floor: float,
    max_regression: float,
) -> tuple[bool, str]:
    """The supervised gate's verdict arithmetic over one join report —
    a pure function shared by the in-process and cross-process gates.
    Fails closed: too few joined flows, coverage under the floor, or an
    uncomputable error on either side are all refusals."""
    joined = int(report.get("joined", 0) or 0)
    if joined < int(min_joined):
        return False, (
            f"insufficient ground truth: {joined} joined flow(s) < "
            f"min_joined={min_joined}"
        )
    coverage = float(report.get("coverage", 0.0) or 0.0)
    if coverage < float(coverage_floor):
        return False, (
            f"label coverage {coverage:.4f} < floor={coverage_floor} "
            f"over {int(report.get('total', 0) or 0)} scored record(s)"
        )
    models = report.get("models") or {}
    serving_err = (models.get("serving") or {}).get("error")
    candidate_err = (models.get("candidate") or {}).get("error")
    if serving_err is None or candidate_err is None:
        return False, (
            "supervised error uncomputable on "
            f"{'serving' if serving_err is None else 'candidate'} side "
            f"over {joined} joined flow(s)"
        )
    if float(candidate_err) > float(serving_err) + float(max_regression):
        return False, (
            f"supervised regression: candidate error "
            f"{float(candidate_err):.4f} > serving "
            f"{float(serving_err):.4f} + {max_regression} over "
            f"{joined} joined flow(s)"
        )
    return True, (
        f"supervised agreement: candidate error "
        f"{float(candidate_err):.4f} <= serving "
        f"{float(serving_err):.4f} + {max_regression} over "
        f"{joined} joined flow(s) at coverage {coverage:.4f}"
    )


def read_pair_records(path: str) -> list[dict]:
    """The shadow plane's paired records, tolerating torn tails and
    foreign lines (same reader discipline as the journal replay)."""
    from ..shadow.compare import PAIR_SCHEMA

    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and rec.get("schema") == PAIR_SCHEMA:
                    out.append(rec)
    except OSError:
        return []
    return out


class LabelGate:
    """The supervised promotion rung over a candidate's mirror pairs.

    ``evaluate(aid)`` loads the ground-truth journal, joins it against
    ``<registry>/shadow/<aid>.pairs.jsonl``, and rules candidate-vs-
    serving error — returning ``(ok, verdict)`` exactly like
    ``ShadowGate.wait``, so the controller stacks the two rungs. The
    whole decision is a file read + pure arithmetic: no polling loop —
    by the time this gate runs, the shadow gate has already waited for
    the pairs to exist; labels either cover them or the gate refuses."""

    def __init__(
        self,
        registry_root: str,
        *,
        journal: str | None = None,
        threshold: float = 0.5,
        min_joined: int = 32,
        coverage_floor: float = 0.05,
        max_regression: float = 0.0,
        tracer=None,
    ):
        if int(min_joined) < 1:
            raise ValueError(f"min_joined={min_joined} must be >= 1")
        if not 0.0 <= float(coverage_floor) <= 1.0:
            raise ValueError(
                f"coverage_floor={coverage_floor} must be in [0, 1]"
            )
        if float(max_regression) < 0.0:
            raise ValueError(
                f"max_regression={max_regression} must be >= 0"
            )
        self.registry_root = os.path.abspath(registry_root)
        self.journal = journal or journal_path(self.registry_root)
        self.threshold = float(threshold)
        self.min_joined = int(min_joined)
        self.coverage_floor = float(coverage_floor)
        self.max_regression = float(max_regression)
        self.tracer = tracer

    def join(self, aid: str) -> dict[str, Any]:
        """The join report for one shadow-state candidate's pairs."""
        from ..shadow.gate import pairs_path

        store = LabelStore(self.journal)
        store.load()
        records = read_pair_records(pairs_path(self.registry_root, aid))
        # Secondary ranked candidates tag their pairs with "cand" — the
        # gated verdict covers the primary candidate's pairs only.
        records = [r for r in records if not r.get("cand")]
        # fedtpu: allow(determinism): span timestamps only — the join
        # arithmetic below is pure (records + journal in, report out).
        t_unix = time.time()
        t0 = time.monotonic()
        report = join_records(
            records, store.labels_map(), threshold=self.threshold
        )
        report["watermark"] = store.watermark
        if self.tracer is not None:
            self.tracer.record(
                "label-join",
                t_start=t_unix,
                dur_s=time.monotonic() - t0,
                artifact=aid,
                total=report["total"],
                joined=report["joined"],
                coverage=round(report["coverage"], 6),
            )
        return report

    def evaluate(self, aid: str) -> tuple[bool, dict]:
        """(ok, verdict) for one candidate — the supervised analogue of
        ``ShadowGate.wait`` (no wait: rules on the evidence as it sits)."""
        # fedtpu: allow(determinism): span timestamps only.
        t_unix = time.time()
        t0 = time.monotonic()
        report = self.join(aid)
        ok, reason = evaluate_supervised(
            report,
            min_joined=self.min_joined,
            coverage_floor=self.coverage_floor,
            max_regression=self.max_regression,
        )
        models = report.get("models") or {}
        verdict = {
            "ok": bool(ok),
            "reason": reason,
            "joined": report["joined"],
            "total": report["total"],
            "coverage": round(report["coverage"], 6),
            "watermark": report.get("watermark"),
            "serving_error": (models.get("serving") or {}).get("error"),
            "candidate_error": (models.get("candidate") or {}).get("error"),
            "min_joined": self.min_joined,
            "coverage_floor": self.coverage_floor,
            "max_regression": self.max_regression,
        }
        if self.tracer is not None:
            self.tracer.record(
                "label-gate",
                t_start=t_unix,
                dur_s=time.monotonic() - t0,
                artifact=aid,
                passed=bool(ok),
                joined=verdict["joined"],
                coverage=verdict["coverage"],
                serving_error=verdict["serving_error"],
                candidate_error=verdict["candidate_error"],
            )
        log.info(
            f"[LABELS] supervised gate verdict for {aid}: "
            f"{'PASS' if ok else 'FAIL'} ({reason})"
        )
        return ok, verdict
