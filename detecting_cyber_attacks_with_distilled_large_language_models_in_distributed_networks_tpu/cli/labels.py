"""fedtpu labels — the delayed ground-truth plane's operator surface.

``ingest`` appends labeler verdicts (a JSONL file of
``{"rid", "label", "ts"}`` records, or one ``--rid/--label`` pair) into
the registry's append-only journal and optionally advances the
completeness watermark. ``status`` replays the journal into its
projection counters (labels, duplicates, conflicts, late arrivals,
watermark). ``report`` runs the deterministic join of scored-request
records — a shadow candidate's mirror pairs, or a serving tier's
scored-JSONL — against the journal and prints the supervised verdicts
the label gate rules on, inspectable after the fact exactly like a
registry event.
"""

from __future__ import annotations

import json

from ..utils.logging import get_logger

log = get_logger()


def _journal(args) -> str:
    from ..labels import journal_path

    return getattr(args, "journal", None) or journal_path(args.registry_dir)


def _iter_ingest_records(path: str):
    """JSONL label records from a labeler export; non-dict and foreign
    lines are skipped (counted for the operator)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                yield None
                continue
            yield rec if isinstance(rec, dict) else None


def cmd_labels(args) -> int:
    from ..labels import LabelStore

    if args.action == "ingest":
        store = LabelStore(_journal(args))
        store.load()
        applied = skipped = 0
        if getattr(args, "rid", None) is not None:
            if getattr(args, "label", None) is None:
                raise SystemExit("labels ingest --rid needs --label")
            store.ingest(
                args.rid,
                int(args.label),
                ts=float(getattr(args, "ts", None) or 0.0),
            )
            applied += 1
        elif getattr(args, "file", None):
            default_ts = getattr(args, "ts", None)
            try:
                records = list(_iter_ingest_records(args.file))
            except OSError as e:
                raise SystemExit(f"cannot read {args.file}: {e}") from None
            for rec in records:
                if rec is None or "rid" not in rec or "label" not in rec:
                    skipped += 1
                    continue
                ts = rec.get("ts", default_ts)
                store.ingest(
                    str(rec["rid"]),
                    int(rec["label"]),
                    ts=float(ts) if ts is not None else 0.0,
                )
                applied += 1
        elif getattr(args, "watermark", None) is None:
            raise SystemExit(
                "labels ingest needs --file, --rid/--label, or --watermark"
            )
        if getattr(args, "watermark", None) is not None:
            store.advance_watermark(float(args.watermark))
        s = store.status()
        if args.json:
            print(json.dumps({**s, "applied": applied, "skipped": skipped}))
            return 0
        print(
            f"ingested {applied} record(s)"
            + (f", skipped {skipped} malformed" if skipped else "")
            + f"; journal now holds {s['labels']} label(s) "
            f"(conflicts {s['conflicts']}, late {s['late']}, watermark "
            + (
                f"{s['watermark']:.3f}"
                if s["watermark"] is not None
                else "unset"
            )
            + ")"
        )
        return 0

    if args.action == "status":
        store = LabelStore(_journal(args))
        store.load()
        s = store.status()
        if args.json:
            print(json.dumps(s))
            return 0
        print(f"journal: {s['path']}")
        print(
            f"labels {s['labels']}  duplicates {s['duplicates']}  "
            f"conflicts {s['conflicts']}  late {s['late']}  watermark "
            + (
                f"{s['watermark']:.3f}"
                if s["watermark"] is not None
                else "unset"
            )
        )
        return 0

    if args.action == "report":
        from ..labels import LabelGate, join_records
        from ..registry import ModelRegistry, RegistryError

        if getattr(args, "scored", None):
            # Serving-tier scored-JSONL: one model, "prob" field.
            store = LabelStore(_journal(args))
            store.load()
            records = [
                r
                for r in _iter_ingest_records(args.scored)
                if r is not None and r.get("schema") == "fedtpu-scored-v1"
            ]
            report = join_records(
                records,
                store.labels_map(),
                threshold=args.threshold,
                sides={"serving": "prob"},
            )
            report["watermark"] = store.watermark
        else:
            registry = ModelRegistry(args.registry_dir)
            aid = getattr(args, "artifact", None)
            if not aid:
                try:
                    info = registry.shadow_info()
                except RegistryError as e:
                    raise SystemExit(str(e)) from None
                aid = info.get("artifact") if info else None
            if aid is None:
                raise SystemExit(
                    "nothing under shadow evaluation and no --artifact "
                    "or --scored given — name the evidence to join"
                )
            gate = LabelGate(
                args.registry_dir,
                journal=getattr(args, "journal", None),
                threshold=args.threshold,
            )
            report = gate.join(aid)
            report["artifact"] = aid
        if args.json:
            print(json.dumps(report))
            return 0
        if report.get("artifact"):
            print(f"label join for {report['artifact']}:")
        print(
            f"  {report['joined']}/{report['total']} scored record(s) "
            f"joined (coverage {report['coverage']:.4f}, watermark "
            + (
                f"{report['watermark']:.3f}"
                if report.get("watermark") is not None
                else "unset"
            )
            + ")"
        )
        for name, v in report.get("models", {}).items():
            if not v.get("n"):
                print(f"  {name}: no joined evidence")
                continue
            print(
                f"  {name}: n={v['n']} accuracy="
                + (
                    f"{v['accuracy']:.4f}"
                    if v["accuracy"] is not None
                    else "n/a"
                )
                + " fpr="
                + (f"{v['fpr']:.4f}" if v["fpr"] is not None else "n/a")
                + " fnr="
                + (f"{v['fnr']:.4f}" if v["fnr"] is not None else "n/a")
                + f" per_class={v['per_class']}"
            )
        return 0

    raise SystemExit(f"unknown labels action {args.action!r}")
