"""fedtpu federated — N clients on one TPU mesh: SPMD local epochs +
pmean FedAvg, multi-round, checkpoint/resume (the TPU-native deployment)."""

from __future__ import annotations

import os

import numpy as np

from ..utils.logging import get_logger, phase
from .common import (
    _load_client_splits,
    _obs_setup,
    _resolve_with_pretrained,
    _write_reports,
)

log = get_logger()


def cmd_federated(args) -> int:
    import jax

    from ..data import stack_clients_ragged, tokenize_client
    from ..train.federated import FederatedTrainer

    # Multi-host bootstrap must precede the first backend touch
    # (jax.devices()/process_count()); config resolution and data loading
    # are backend-free so their order doesn't matter.
    mesh = None
    local_sl = None
    # multihost.initialize owns ALL the configuration logic (flag/env
    # resolution, single-process no-op, TPU-pod autodetect); the CLI only
    # converts its failures into actionable messages.
    from ..parallel.multihost import initialize

    try:
        initialize(
            getattr(args, "coordinator", None),
            getattr(args, "num_processes", None),
            getattr(args, "process_id", None),
        )
    except Exception as e:
        raise SystemExit(
            f"multi-host bootstrap failed: {e}\n"
            "Pass --coordinator HOST:PORT --num-processes N --process-id I "
            "together (every process the same coordinator), or none of them "
            "on a platform where jax.distributed autodetects."
        )

    # Fail fast on an unfittable data axis — knowable from argv + device
    # count alone, before any (potentially large) HF checkpoint load.
    # Client-axis fitting itself lives in FederatedTrainer (replica
    # stacking), serving library callers too.
    if (
        jax.process_count() == 1
        and getattr(args, "data_parallel", None)
        and args.data_parallel > len(jax.devices())
    ):
        raise SystemExit(
            f"--data-parallel {args.data_parallel} exceeds the "
            f"{len(jax.devices())} available devices"
        )

    tok, cfg, pretrained = _resolve_with_pretrained(args)
    C = cfg.fed.num_clients
    if jax.process_count() > 1:
        from ..parallel.multihost import (
            local_client_slice,
            make_global_mesh,
            make_global_seq_mesh,
        )

        if C != cfg.mesh.clients:
            raise SystemExit(
                f"multi-host runs need one mesh row per client "
                f"(num_clients={C}, mesh.clients={cfg.mesh.clients})"
            )
        if cfg.mesh.seq > 1:
            # --seq-parallel multi-host: clients over DCN, each client's
            # seq ring (and data psum) inside one host's ICI domain.
            mesh = make_global_seq_mesh(
                cfg.mesh.clients, cfg.mesh.data, cfg.mesh.seq
            )
        else:
            mesh = make_global_mesh(
                cfg.mesh.clients, cfg.mesh.data, axis_names=cfg.mesh.axis_names
            )
        local_sl = local_client_slice(mesh)
        log.info(
            f"[FED] process {jax.process_index()}/{jax.process_count()} owns "
            f"clients [{local_sl.start}, {local_sl.stop})"
        )

    if getattr(args, "stream", False):
        if not getattr(args, "csv", None):
            raise SystemExit("--stream needs --csv (chunked two-pass reader)")
        from ..data import stream_client_tokens_for

        # Works multi-host: every process computes the identical global
        # plan (same label scan), materializes tokens only for ITS clients,
        # and learns every client's split sizes for the stacked shapes and
        # FedAvg weights.
        stream_ids = (
            list(range(C))
            if local_sl is None
            else list(range(local_sl.start, local_sl.stop))
        )
        with phase(f"streaming {args.csv} for clients {stream_ids}", tag="DATA"):
            clients, sizes = stream_client_tokens_for(
                args.csv, cfg.data, C, tok, stream_ids, max_len=cfg.model.max_len
            )
        train_sizes = [s["train"] for s in sizes]
        eval_rows_global = max(s["test"] for s in sizes)
        val_rows_global = max(s["val"] for s in sizes)
    else:
        # Partitioning runs over the full fleet on every host (it must be
        # globally consistent); tokenization — the host-side hot loop — runs
        # only for this process's clients. Global row counts for the stacked
        # train/eval feeds come from the (cheap) split lengths, so every host
        # agrees on batch counts without seeing other hosts' token arrays.
        splits = _load_client_splits(args, cfg, C)
        local_ids = (
            range(C) if local_sl is None else range(local_sl.start, local_sl.stop)
        )
        with phase(f"tokenize clients {list(local_ids)}", tag="DATA"):
            clients = [
                tokenize_client(splits[c], tok, max_len=cfg.model.max_len)
                for c in local_ids
            ]
        eval_rows_global = max(len(s.test) for s in splits)
        val_rows_global = max(len(s.val) for s in splits)
        train_sizes = [len(s.train) for s in splits]
    # Ragged stack to the GLOBAL fleet-max row count: no client's rows are
    # truncated (the reference's N independent processes each train on all
    # their own samples), and every host agrees on the stacked shape.
    stacked_train = stack_clients_ragged(
        [c.train for c in clients],
        pad_id=tok.pad_id,
        target_rows=max(train_sizes),
    )
    if cfg.mesh.seq > 1:
        # --seq-parallel N: the 3-axis clients x data x seq composition
        # (ring attention per client) behind the identical trainer surface
        # — eval, reports, checkpointing, DP all flow through unchanged.
        from ..train.seqfed import FedSeqTrainer

        trainer = FedSeqTrainer(cfg, pad_id=tok.pad_id, mesh=mesh)
    else:
        trainer = FederatedTrainer(cfg, pad_id=tok.pad_id, mesh=mesh)
    # Obs spans for the mesh tier: per-round client-local / agg phase
    # timers land on this process's events-JSONL (no wire here — the
    # round boundary is a collective, so one proc covers the fleet).
    trainer.tracer, _metrics = _obs_setup(args, proc="fed", cfg=cfg)

    ckpt = None
    start_round = 0
    state = trainer.init_state(params=pretrained)
    if cfg.checkpoint_dir:
        # Works multi-host too: every process participates in save/restore
        # (orbax coordinates through the jax.distributed runtime; the state
        # template carries the global shardings).
        from ..train.checkpoint import Checkpointer, maybe_warm_start

        restored, step = maybe_warm_start(cfg.checkpoint_dir, state)
        if restored is not None:
            state, start_round = restored, int(step)
            log.info(f"[FED] resumed from round {start_round}")
            # Checkpoints are written BEFORE the per-round optimizer reset
            # (cmd loop below); apply the reset a continuous run would have
            # done so the resumed trajectory matches it exactly.
            if start_round < cfg.fed.rounds and cfg.fed.reset_optimizer_each_round:
                state = trainer.reset_optimizer(state)
        ckpt = Checkpointer(cfg.checkpoint_dir)

    # FedAvg weights are the GLOBAL per-client sample counts (known from the
    # cheap split phase on every host, reference semantics: weight by data).
    # weighted=None (the default) auto-weights; --unweighted forces the
    # reference's literal uniform mean.
    weights = (
        np.array(train_sizes, np.float64) if cfg.fed.resolve_weighted() else None
    )
    # Under a uniform mean (--unweighted, or DP's forced uniform), zero-row
    # clients would average their never-trained round-start params in with
    # full 1/C weight; mask them out as permanently dropped clients (same
    # rule as FederatedTrainer.run). train_sizes is global, so every host
    # builds the identical mask.
    base_mask = None
    if weights is None:
        empty = np.asarray(train_sizes) == 0
        if empty.any():
            base_mask = (~empty).astype(np.float64)
            log.warning(
                f"[FED] clients {np.flatnonzero(empty).tolist()} have zero "
                "train rows; excluding them from the uniform mean"
            )
    from ..utils.profiling import trace

    prepared = trainer.prepare_eval(
        [c.test for c in clients], target_rows=eval_rows_global
    )
    # Validation metrics every phase, like the reference (it evaluates val
    # AND test at each of local/aggregated, client1.py:383-385,398-400).
    prepared_val = trainer.prepare_eval(
        [c.val for c in clients], target_rows=val_rows_global
    )
    history = []
    with trace(getattr(args, "profile_dir", None)):
        for r in range(start_round, cfg.fed.rounds):
            anchor = trainer.round_anchor(state)
            with phase(f"round {r + 1}/{cfg.fed.rounds}", tag="FED"):
                state, losses = trainer.fit_local(
                    state, stacked_train, epoch_offset=r * cfg.train.epochs_per_round
                )
                local_val = trainer.evaluate_clients(
                    state.params, prepared=prepared_val
                )
                local = trainer.evaluate_clients(state.params, prepared=prepared)
                # Shared sampling/gating/aggregation (incl. the Poisson
                # empty-cohort no-op round, train/federated.py).
                state = trainer.round_aggregate(
                    state,
                    round_index=r,
                    weights=weights,
                    base_mask=base_mask,
                    anchor=anchor,
                )
                aggregated_val = trainer.evaluate_clients(
                    state.params, prepared=prepared_val
                )
                aggregated = trainer.evaluate_clients(state.params, prepared=prepared)
            history.append((r, local, aggregated))
            for c in range(C):
                log.info(
                    f"[FED] round {r + 1} client {c}: local val/test acc "
                    f"{local_val[c]['Accuracy']:.4f}/{local[c]['Accuracy']:.4f}"
                    f" -> aggregated "
                    f"{aggregated_val[c]['Accuracy']:.4f}/"
                    f"{aggregated[c]['Accuracy']:.4f}"
                )
            if getattr(args, "metrics_jsonl", None) and jax.process_index() == 0:
                from ..reporting import append_metrics_jsonl

                for c in range(C):
                    for phase_name, split_name, m in (
                        ("local", "val", local_val[c]),
                        ("local", "test", local[c]),
                        ("aggregated", "val", aggregated_val[c]),
                        ("aggregated", "test", aggregated[c]),
                    ):
                        append_metrics_jsonl(
                            args.metrics_jsonl,
                            {
                                "round": r + 1,
                                "client": c,
                                "phase": phase_name,
                                "split": split_name,
                                **m,
                            },
                        )
            if ckpt is not None:
                ckpt.save(
                    r + 1,
                    state,
                    meta={
                        "round": r + 1,
                        "kind": "federated",
                        "config": cfg.to_dict(),
                    },
                )
            if getattr(args, "registry_dir", None) and jax.process_index() == 0:
                # Registry-aware checkpointing: every finished round also
                # becomes an immutable CANDIDATE artifact with its
                # fleet-mean validation metrics (model-selection data —
                # never test), so `fedtpu registry promote` / the control
                # plane can gate what serves without touching raw orbax
                # steps. Replica 0 is the global model (FedAvg replicates
                # its output across the clients axis).
                from ..registry import ModelRegistry

                params0 = jax.tree.map(
                    lambda x: np.asarray(x)[0], trainer._host(state.params)
                )
                fleet_val = {
                    k: float(np.mean([m[k] for m in aggregated_val]))
                    for k in ("Accuracy", "Loss", "Precision", "Recall", "F1-Score")
                    if all(k in m for m in aggregated_val)
                }
                ModelRegistry(args.registry_dir).add(
                    params0,
                    round_index=r + 1,
                    metrics=fleet_val,
                    model_config=cfg.model,
                    extra={"tier": "mesh", "clients": C},
                )
            if r + 1 < cfg.fed.rounds and cfg.fed.reset_optimizer_each_round:
                state = trainer.reset_optimizer(state)
    if ckpt is not None:
        ckpt.wait()
        ckpt.close()

    if cfg.fed.dp_clip > 0.0 and cfg.fed.dp_noise_multiplier > 0.0:
        from ..parallel.dp import dp_epsilon_both

        # Only the rounds executed THIS launch are known to have run under
        # this DP config; a resumed checkpoint's earlier rounds may have
        # been trained without noise, so the guarantee must not cover them.
        dp_rounds = cfg.fed.rounds - start_round
        # participation < 1: the subsampled-Gaussian accountant credits
        # privacy amplification (parallel/dp.py::sgm_rdp). Under the
        # Poisson sampler (the default with DP on) q is the exact
        # Bernoulli rate; under the fixed sampler it is the EFFECTIVE
        # cohort_size/C approximation.
        q, q_exact = cfg.fed.dp_sampling_rate()
        eps_zeroed, eps_replace = dp_epsilon_both(
            dp_rounds, cfg.fed.dp_noise_multiplier, 1e-5, sampling_rate=q
        )
        caveat = (
            ""
            if start_round == 0
            else (
                f" — covers rounds {start_round + 1}..{cfg.fed.rounds} only; "
                f"the {start_round} resumed round(s) carry whatever DP "
                "config they were run with"
            )
        )
        # Both adjacency bounds, every run: the zeroed-contribution figure
        # alone reads ~4x stronger than the same noise under the stricter
        # replace-one adjacency (parallel/dp.py module docstring).
        if q >= 1.0:
            sampling_note = ""
        elif q_exact:
            sampling_note = f"; Poisson sampling q={q:.3g} (accountant exact)"
        else:
            sampling_note = (
                f"; fixed-size cohort accounted as Poisson sampling "
                f"q={q:.3g} (approximation — use "
                f"--participation-mode poisson for an exact bound)"
            )
        log.info(
            f"[DP] client-level guarantee for {dp_rounds} round(s): "
            f"({eps_zeroed:.3g}, 1e-05)-DP under zeroed-contribution "
            f"adjacency; ({eps_replace:.3g}, 1e-05)-DP under replace-one "
            f"adjacency (clip {cfg.fed.dp_clip}, "
            f"noise x{cfg.fed.dp_noise_multiplier}{sampling_note})"
            f"{caveat}"
        )

    # Final reporting with probs for ROC/PR curves. Under multi-host,
    # evaluate_clients gathers every client's probs/labels process-major
    # (device replication + host allgather), so process 0 writes the FULL
    # artifact set — ROC/PR included — for all clients.
    final_local = history[-1][1] if history else None
    multihost = jax.process_count() > 1
    final_agg = trainer.evaluate_clients(
        state.params, prepared=prepared, collect_probs=True
    )
    final_pers = None
    if cfg.fed.personalize_epochs > 0:
        # FedAvg + local fine-tuning: each client adapts the aggregate on
        # its own shard (scope 'head' = FedPer) — evaluated as a third
        # phase; the aggregate itself (already evaluated above) is NOT
        # touched, so the standard artifact set stays comparable.
        with phase(
            f"personalization ({cfg.fed.personalize_epochs} epoch(s), "
            f"scope {cfg.fed.personalize_scope})",
            tag="FED",
        ):
            pstate, _ = trainer.personalize(state, stacked_train)
            final_pers = trainer.evaluate_clients(
                pstate.params, prepared=prepared
            )
        for c in range(C):
            log.info(
                f"[FED] client {c}: aggregated test acc "
                f"{final_agg[c]['Accuracy']:.4f} -> personalized "
                f"{final_pers[c]['Accuracy']:.4f}"
            )
        if getattr(args, "metrics_jsonl", None) and jax.process_index() == 0:
            from ..reporting import append_metrics_jsonl

            for c in range(C):
                append_metrics_jsonl(
                    args.metrics_jsonl,
                    {
                        "round": cfg.fed.rounds,
                        "client": c,
                        "phase": "personalized",
                        "split": "test",
                        **final_pers[c],
                    },
                )
    if not multihost or jax.process_index() == 0:
        if final_local is None:
            # No round trained this launch (e.g. relaunching a completed
            # checkpointed run): there ARE no local-model metrics — write
            # aggregated artifacts only rather than mislabeling.
            log.info(
                "[FED] all rounds already complete; writing aggregated "
                "reports only"
            )
            _save_phase_csvs(final_agg, "aggregated", cfg.output_dir)
        else:
            for c in range(C):
                _write_reports(c, final_local[c], final_agg[c], cfg.output_dir)
        if final_pers is not None:
            _save_phase_csvs(final_pers, "personalized", cfg.output_dir)
    return 0


def _save_phase_csvs(metrics: list, phase_name: str, out_dir: str) -> None:
    """One `client{c}_{phase}_metrics.csv` per client (reference schema)."""
    from .. import reporting

    os.makedirs(out_dir, exist_ok=True)
    for c, m in enumerate(metrics):
        reporting.save_metrics(
            m, os.path.join(out_dir, f"client{c}_{phase_name}_metrics.csv")
        )
