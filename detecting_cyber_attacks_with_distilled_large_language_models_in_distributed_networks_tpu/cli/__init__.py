"""Command-line orchestration package — the reference's three
``main()``s unified behind one ``fedtpu`` CLI. The subcommand map and
deployment-shape documentation live in :mod:`.parser`; each command is its
own module (common plumbing in :mod:`.common`)."""

from .comm import _auth_key, cmd_client, cmd_serve  # noqa: F401
from .common import (  # noqa: F401
    _load_client_splits,
    _load_clients,
    _preset_model,
    _resolve_with_pretrained,
    _write_reports,
    resolve_config,
)
from .distill import cmd_distill  # noqa: F401
from .federated import cmd_federated  # noqa: F401
from .local import cmd_local  # noqa: F401
from .parser import build_parser, cmd_export_config, main  # noqa: F401
from .predict import (  # noqa: F401
    _restore_predict_params,
    cmd_export_hf,
    cmd_predict,
)
