"""Keep ``python -m <pkg>.cli`` working now that cli is a package."""

import sys

from .parser import main

sys.exit(main())
