"""Keep ``python -m <pkg>.cli`` working now that cli is a package."""

import sys

from .parser import main

if __name__ == "__main__":
    sys.exit(main())
