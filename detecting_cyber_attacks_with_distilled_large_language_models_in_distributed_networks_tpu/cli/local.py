"""fedtpu local — one client: train -> eval -> metrics CSV + plots
(reference client1.py minus the sockets)."""

from __future__ import annotations

from ..utils.logging import get_logger, phase
from .common import _load_clients, _resolve_with_pretrained, _write_reports

log = get_logger()


def cmd_local(args) -> int:
    from ..train.engine import Trainer

    tok, cfg, pretrained = _resolve_with_pretrained(args)
    client = _load_clients(args, cfg, tok, max(args.client_id + 1, 1))[args.client_id]
    trainer = Trainer(
        cfg.model, cfg.train, pad_id=tok.pad_id,
        drop_remainder=cfg.data.drop_remainder,
    )
    state = trainer.init_state(params=pretrained)
    from ..utils.profiling import trace

    with phase(f"client {args.client_id} local training", tag="TRAIN"), trace(
        getattr(args, "profile_dir", None)
    ):
        state, losses = trainer.fit(
            state,
            client.train,
            batch_size=cfg.data.batch_size,
            tag=f"[CLIENT {args.client_id}] ",
        )
    with phase("validation evaluation", tag="EVAL"):
        val = trainer.evaluate(state.params, client.val, batch_size=cfg.data.eval_batch_size)
    with phase("test evaluation", tag="EVAL"):
        test = trainer.evaluate(state.params, client.test, batch_size=cfg.data.eval_batch_size)
    log.info(
        f"[CLIENT {args.client_id}] val acc {val['Accuracy']:.4f} | "
        f"test acc {test['Accuracy']:.4f} f1 {test['F1-Score']:.4f}"
    )
    if getattr(args, "metrics_jsonl", None):
        from ..reporting import append_metrics_jsonl

        for phase_name, m in (("val", val), ("test", test)):
            append_metrics_jsonl(
                args.metrics_jsonl,
                {"client": args.client_id, "phase": phase_name, **m},
            )
    _write_reports(args.client_id, test, None, cfg.output_dir)
    if cfg.checkpoint_dir:
        from ..train.checkpoint import Checkpointer

        with Checkpointer(cfg.checkpoint_dir) as ckpt:
            ckpt.save(
                int(state.step),
                state,
                meta={
                    "client_id": args.client_id,
                    "kind": "local",
                    "config": cfg.to_dict(),
                },
            )
            ckpt.wait()
    return 0
