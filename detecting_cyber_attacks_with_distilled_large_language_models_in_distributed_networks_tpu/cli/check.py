"""fedtpu check — run the invariant-aware static-analysis passes.

    fedtpu check                      # scan the repo, human-readable
    fedtpu check --json               # machine-readable (bench/CI)
    fedtpu check --rules determinism,unguarded
    fedtpu check --baseline ANALYSIS_BASELINE.json
    fedtpu check --list-rules

Exit codes: 0 = clean (pragma'd/baselined findings allowed), 1 = at
least one NON-baselined finding, 2 = usage/internal error. The tier-1
verify recipe runs this next to the fast lane; bench.py's ``check``
record asserts ``check_findings_new == 0`` (exit 3 on regression).

Suppression is always reviewed: a per-line
``# fedtpu: allow(<rule>): reason`` pragma at the site, or an entry
with a ``reason`` in the repo-root ``ANALYSIS_BASELINE.json``. Stale
baseline entries (findings since fixed) are reported for cleanup but
never fail the check.
"""

from __future__ import annotations

import json
import os
import sys

from ..analysis import all_rules, run_check
from ..analysis.core import BASELINE_NAME, prune_baseline


def _default_root() -> str:
    """The repo root: the parent of the package directory this module
    lives in (cli/ -> package -> root)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def cmd_check(args) -> int:
    if getattr(args, "list_rules", False):
        for name, rule in sorted(all_rules().items()):
            print(f"{name:24s} {rule.description}")
        return 0
    root = getattr(args, "root", None) or _default_root()
    rules = None
    if getattr(args, "rules", None):
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        result = run_check(
            root,
            rules=rules,
            baseline_path=getattr(args, "baseline", None),
        )
    except (ValueError, OSError) as e:
        print(f"fedtpu check: {e}", file=sys.stderr)
        return 2

    if getattr(args, "prune_baseline", False):
        # The remediation path for stale entries: rewrite the baseline
        # minus findings that no longer fire. Resolve the path exactly
        # as run_check did (explicit --baseline, else the scanned
        # root's ANALYSIS_BASELINE.json when present).
        bpath = getattr(args, "baseline", None)
        if bpath is None:
            candidate = os.path.join(os.path.abspath(root), BASELINE_NAME)
            bpath = candidate if os.path.isfile(candidate) else None
        if bpath is None:
            print(
                "fedtpu check: --prune-baseline found no baseline file "
                "to prune",
                file=sys.stderr,
            )
            return 2
        removed = (
            prune_baseline(bpath, result.stale_baseline)
            if result.stale_baseline
            else 0
        )
        print(
            f"fedtpu check: pruned {removed} stale baseline entr"
            f"{'y' if removed == 1 else 'ies'} from {bpath}",
            # --json consumers parse stdout as ONE JSON document; the
            # human-facing prune notice must not corrupt it.
            file=sys.stderr if getattr(args, "json", False) else sys.stdout,
        )
        result.stale_baseline = []

    if getattr(args, "json", False):
        json.dump(result.to_dict(), sys.stdout, indent=2)
        sys.stdout.write("\n")
        return result.exit_code

    for f in result.new:
        print(f.render())
    summary = (
        f"fedtpu check: {len(result.new)} new finding(s), "
        f"{len(result.baselined)} baselined, {result.allowed} "
        f"pragma-allowed across {result.modules_scanned} modules "
        f"({result.runtime_s:.2f}s)"
    )
    print(summary)
    if result.stale_baseline:
        print(
            f"note: {len(result.stale_baseline)} stale baseline entr"
            f"{'y' if len(result.stale_baseline) == 1 else 'ies'} "
            "(finding no longer fires — prune when convenient):"
        )
        for entry in result.stale_baseline:
            print(f"  [{entry['rule']}] {entry['path']}: {entry['message']}")
    return result.exit_code
