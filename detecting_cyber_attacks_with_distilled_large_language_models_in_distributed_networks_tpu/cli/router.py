"""fedtpu route / fedtpu fleet — the serving replica tier (router/).

``route`` runs the thin model-free router standalone over already-
running ``infer-serve`` backends (cross-host deployments: replicas on
their own machines, one router in front). ``fleet`` is the one-command
local shape: spawn N in-process replicas from the registry's promoted
artifact, put the router in front, and follow the serving pointer with
**rolling hot-reload** — on every promotion the manager drains and
swaps one replica at a time, so the pointer move never drops a request
(the PR-3 promotion ladder's zero-downtime deploy path).
"""

from __future__ import annotations

import time

from ..utils.logging import get_logger
from .common import _obs_setup, _resolve_with_pretrained

log = get_logger()


def _parse_backends(specs) -> list[tuple[str, int]]:
    backends = []
    for spec in specs or ():
        host, sep, port = str(spec).rpartition(":")
        if not sep or not port.isdigit():
            raise SystemExit(
                f"--backend {spec!r}: want HOST:PORT (e.g. 127.0.0.1:12380)"
            )
        backends.append((host or "127.0.0.1", int(port)))
    if not backends:
        raise SystemExit(
            "fedtpu route needs at least one --backend HOST:PORT "
            "(repeatable) — the infer-serve replicas to route across"
        )
    return backends


def _auth_key_or_exit(args) -> bytes | None:
    if not getattr(args, "auth", False):
        return None
    from .comm import _auth_key

    auth_key = _auth_key()
    if auth_key is None:
        raise SystemExit(
            "--auth needs the shared secret in the FEDTPU_SECRET env var "
            "(same value on the router, every replica, and every client)"
        )
    return auth_key


def _log_router_stats(tag: str, s: dict) -> None:
    ups = ", ".join(
        f"r{b['replica']}"
        f"{'' if b['healthy'] else ' DOWN'}"
        f"{' draining' if b['draining'] else ''}"
        f"(inflight {b['inflight']}, round {b['round']})"
        for b in s["backends"]
    )
    log.info(
        f"[{tag}] forwarded {s['forwarded']}, rejects {s['rejects_total']}, "
        f"{s['healthy']}/{len(s['backends'])} replicas up: {ups}"
    )


def cmd_route(args) -> int:
    from ..router import ScoringRouter

    backends = _parse_backends(getattr(args, "backend", None))
    auth_key = _auth_key_or_exit(args)
    tracer, _metrics = _obs_setup(args, proc="router", metrics_host=args.host)
    router = ScoringRouter(
        backends,
        host=args.host,
        port=args.port,
        auth_key=auth_key,
        probe_interval_s=args.probe_interval,
        probe_timeout_s=args.probe_timeout,
        max_inflight_per_replica=args.max_inflight,
        tracer=tracer,
        trace_sample=(
            args.trace_sample
            if getattr(args, "trace_sample", None) is not None
            else 1.0
        ),
    )
    with router:
        log.info(
            f"[ROUTER] fronting {len(backends)} replica(s) on "
            f"{args.host}:{router.port} (auth "
            f"{'on' if auth_key else 'off — open port'})"
        )
        try:
            while True:
                time.sleep(30.0)
                _log_router_stats("ROUTER", router.stats())
        except KeyboardInterrupt:
            log.info("[ROUTER] interrupted; draining")
    return 0


def cmd_fleet(args) -> int:
    from ..config import ModelConfig
    from ..data.datasets import get_dataset
    from ..registry import ModelRegistry
    from ..router import FleetReplica, ServingFleet
    from .serving import _parse_buckets

    tok, cfg, _pretrained = _resolve_with_pretrained(
        args, load_weights=False
    )
    auth_key = _auth_key_or_exit(args)
    buckets = _parse_buckets(args.buckets)
    n = int(args.replicas) if args.replicas else cfg.router.replicas
    if n < 1:
        raise SystemExit(f"--replicas {n}: a fleet needs at least one")
    # Pointer-following only: a fleet exists to make PROMOTIONS
    # zero-downtime, and promotions are a registry concept.
    registry = ModelRegistry(args.registry_dir)
    info = registry.serving_info()
    if info is None:
        raise SystemExit(
            f"registry {args.registry_dir} has no serving artifact yet — "
            "run `fedtpu controller` (or `fedtpu registry promote`) to "
            "promote one first"
        )
    manifest = registry.manifest(info["artifact"])
    model_cfg = cfg.model
    if manifest.get("model_config"):
        model_cfg = ModelConfig(**manifest["model_config"])
    if model_cfg.vocab_size != len(tok.vocab):
        raise SystemExit(
            f"serving artifact's model vocab ({model_cfg.vocab_size}) != "
            f"tokenizer vocab ({len(tok.vocab)}); pass the matching "
            "--hf-dir / vocab"
        )
    params = registry.load_params(info["artifact"])
    round_id = int(manifest.get("round", 0))
    tracer, _metrics = _obs_setup(args, proc="fleet", cfg=cfg, metrics_host=args.host)
    log.info(
        f"[FLEET] spawning {n} replica(s) of artifact {info['artifact']} "
        f"(round {round_id}) from registry {args.registry_dir}"
    )
    spec = get_dataset(cfg.data.dataset)
    replicas = [
        FleetReplica(
            i,
            model_cfg,
            params,
            tok,
            spec=spec,
            round_id=round_id,
            buckets=buckets,
            max_queue=args.max_queue,
            gather_window_s=args.max_wait_ms / 1e3,
            threshold=args.threshold,
            auth_key=auth_key,
            tracer=tracer,
        ).start()
        for i in range(n)
    ]
    shadow_sample = (
        args.shadow_sample
        if getattr(args, "shadow_sample", None) is not None
        else cfg.shadow.sample
    )
    shadow_factory = None
    if shadow_sample >= 1:
        # The shadow replica: one more FleetReplica built exactly like
        # the serving ones (same buckets/auth/tracer), spun up/down by
        # the fleet manager as artifacts enter/leave the shadow state —
        # and never handed to the router's pick set.
        def shadow_factory(s_params, *, round_id):
            return FleetReplica(
                n,  # one past the serving fleet: distinct stats identity
                model_cfg,
                s_params,
                tok,
                spec=spec,
                round_id=round_id,
                buckets=buckets,
                max_queue=args.max_queue,
                gather_window_s=args.max_wait_ms / 1e3,
                threshold=args.threshold,
                auth_key=auth_key,
                tracer=tracer,
            ).start()

        log.info(
            f"[FLEET] shadow plane enabled: mirroring 1/{shadow_sample} "
            "of live traffic onto shadow-state artifacts"
        )
    fleet = ServingFleet(
        replicas,
        registry=registry,
        auth_key=auth_key,
        router_host=args.host,
        router_port=args.port,
        probe_interval_s=cfg.router.probe_interval_s,
        probe_timeout_s=cfg.router.probe_timeout_s,
        drain_timeout_s=cfg.router.drain_timeout_s,
        reload_poll_s=args.reload_poll,
        max_inflight_per_replica=cfg.router.max_inflight_per_replica,
        tracer=tracer,
        shadow_factory=shadow_factory,
        shadow_sample=shadow_sample,
        shadow_threshold=cfg.shadow.threshold,
        shadow_bins=cfg.shadow.bins,
        shadow_queue=cfg.shadow.queue,
    )
    try:
        with fleet:
            log.info(
                f"[FLEET] scoring {cfg.data.dataset} flows on "
                f"{args.host}:{fleet.port} ({n} replicas, rolling reload "
                f"on promotion; auth {'on' if auth_key else 'off'})"
            )
            try:
                while True:
                    time.sleep(30.0)
                    s = fleet.stats()
                    _log_router_stats("FLEET", s)
                    log.info(
                        f"[FLEET] serving {s['serving_artifact']} "
                        f"(rounds {s['replica_rounds']}, "
                        f"{s['reloads']} rolling reload(s))"
                    )
            except KeyboardInterrupt:
                log.info("[FLEET] interrupted; draining")
    finally:
        for rep in replicas:
            rep.close()
    return 0
