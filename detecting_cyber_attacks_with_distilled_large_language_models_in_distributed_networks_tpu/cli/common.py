"""Shared CLI plumbing: config resolution, pretrained/tokenizer
resolution, data loading, and per-client report writing (split out of the
original monolithic cli module; see package docstring in .parser)."""

from __future__ import annotations

import argparse
import copy
import dataclasses
import json
import os
from typing import Any

import numpy as np

from ..config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
)
from ..utils.logging import get_logger, phase

log = get_logger()


# ------------------------------------------------------------- observability
def _obs_setup(
    args,
    *,
    proc: str,
    cfg: ExperimentConfig | None = None,
    install_global: bool = True,
    metrics_host: str = "127.0.0.1",
):
    """One call per CLI process: build this process's span Tracer (from
    --trace-jsonl, falling back to the config's obs.trace_jsonl), install
    it as the global tracer (the mesh-tier trainers' fallback hook), and
    start the /metrics endpoint when --metrics-port (or obs.metrics_port)
    asks for one. Returns ``(tracer | None, metrics_server | None)``.

    ``install_global=False`` (the TCP client): the round loop measures
    its own client-local phase through FederatedClient.note_local_phase,
    so the inner trainer's fallback hook must stay disarmed — a
    --seq-parallel client's embedded fedseq trainer would otherwise emit
    a SECOND client-local span per round and double the timeline's
    compute attribution.

    ``--flight-dir`` (or obs.flight_dir) additionally installs the
    process failure flight recorder (obs/flight.py): the daemon keeps a
    bounded ring of recent spans and dumps a postmortem bundle there on
    round failure / replica eject storm / SLO page."""
    from ..obs import (
        FlightRecorder,
        Tracer,
        maybe_start_metrics_server,
        set_global_recorder,
        set_global_tracer,
    )
    from ..obs.trace import set_run_id

    obs_cfg = cfg.obs if cfg is not None else None
    if obs_cfg is not None and obs_cfg.run_id:
        # Pin BEFORE the first span/metrics record: every stream this
        # process writes then carries the configured run identity.
        set_run_id(obs_cfg.run_id)
    trace_path = getattr(args, "trace_jsonl", None) or (
        obs_cfg.trace_jsonl if obs_cfg else None
    )
    tracer = None
    if trace_path:
        tracer = Tracer(trace_path, proc=proc)
        log.info(f"[OBS] {proc}: appending spans to {trace_path}")
    # Unconditional: an invocation WITHOUT tracing must clear any tracer
    # a previous in-process invocation installed (tests drive several CLI
    # commands per process; a stale global tracer would keep appending to
    # a dead path).
    set_global_tracer(tracer if install_global else None)
    flight_dir = getattr(args, "flight_dir", None) or (
        obs_cfg.flight_dir if obs_cfg else None
    )
    recorder = None
    if flight_dir:
        recorder = FlightRecorder(
            flight_dir,
            proc=proc,
            ring=obs_cfg.flight_ring if obs_cfg else 256,
            # The bundle's config section: what this process was
            # actually running with — the first thing a postmortem
            # reader checks against their expectations.
            config={
                "proc": proc,
                **({"experiment": cfg.to_dict()} if cfg is not None else {}),
            },
            tracer=tracer,
        )
        log.info(
            f"[OBS] {proc}: flight recorder armed, postmortem bundles "
            f"-> {flight_dir}"
        )
    # Same unconditional rule as the tracer: clear a previous in-process
    # invocation's recorder when this one doesn't ask for one.
    set_global_recorder(recorder)
    # Device performance plane (obs/profile.py): install the step-
    # profiling stride process-wide — unconditional, like the tracer,
    # so a previous in-process invocation's stride never leaks into a
    # run that didn't ask for profiling. Trainers/engines built before
    # this call re-check the stride at fit time.
    from ..obs.profile import set_profile_stride

    stride = getattr(args, "profile_stride", None)
    if stride is None and obs_cfg is not None:
        stride = obs_cfg.profile_stride
    set_profile_stride(stride or 0)
    if stride:
        log.info(
            f"[OBS] {proc}: step profiling armed (every {stride}th step "
            "fenced into host/dispatch/device)"
        )
    port = getattr(args, "metrics_port", None) or (
        obs_cfg.metrics_port if obs_cfg else 0
    )
    # The endpoint is unauthenticated: bind no wider than the tier
    # itself (server commands pass their own --host; everything else
    # stays loopback).
    server = maybe_start_metrics_server(port, host=metrics_host)
    if server is not None:
        log.info(
            f"[OBS] {proc}: Prometheus /metrics on "
            f"{metrics_host}:{server.port}"
        )
    return tracer, server


# ------------------------------------------------------------------ config
def _preset_model(preset: str, vocab_size: int) -> ModelConfig:
    # One registry (models/presets.py) behind every entrypoint's
    # --preset; adding a scale point is a registry entry, not an
    # if-chain edit here.
    from ..models.presets import model_preset

    try:
        return model_preset(preset, vocab_size=vocab_size)
    except ValueError as e:
        raise SystemExit(f"--preset: {e}") from None


def _resolve_mesh(args, cfg: ExperimentConfig, n: int) -> MeshConfig:
    """Mesh axes from flags: ``is None`` checks (an explicit 0 must reach
    MeshConfig's own validation, not silently fall back to the config
    default), and validation errors surface as operator messages."""
    dp = getattr(args, "data_parallel", None)
    sp = getattr(args, "seq_parallel", None)
    fsdp = getattr(args, "fsdp", None)
    try:
        return MeshConfig(
            clients=n,
            data=cfg.mesh.data if dp is None else dp,
            seq=cfg.mesh.seq if sp is None else sp,
            # store_true default is False; the config file wins unless
            # the flag was actually given.
            fsdp=cfg.mesh.fsdp or bool(fsdp),
        )
    except ValueError as e:
        raise SystemExit(str(e)) from None


def resolve_config(args: argparse.Namespace, *, vocab_size: int) -> ExperimentConfig:
    """defaults <- --config file <- flags."""
    if getattr(args, "config", None):
        with open(args.config) as f:
            cfg = ExperimentConfig.from_dict(json.load(f))
    else:
        preset = getattr(args, "preset", "tiny")
        model = _preset_model(preset, vocab_size)
        cfg = ExperimentConfig(
            model=model,
            data=DataConfig(max_len=model.max_len),
        )

    model_kw: dict[str, Any] = {}
    if getattr(args, "max_len", None):
        model_kw.update(max_len=args.max_len)
    if getattr(args, "gelu", None):
        model_kw.update(gelu=args.gelu)
    if getattr(args, "attention_impl", None):
        model_kw.update(attention_impl=args.attention_impl)
    if getattr(args, "attention_dropout", None) is not None:
        # Explicit 0 must reach the config (ring requires it).
        model_kw.update(attention_dropout=args.attention_dropout)
    if getattr(args, "remat", None) is not None:
        # Tri-state: --remat / --no-remat / absent (config wins).
        model_kw.update(remat=args.remat)
    try:
        new_model = cfg.model.replace(**model_kw) if model_kw else cfg.model
    except ValueError as e:
        # Operator error (e.g. --attention-impl ring with the default
        # attention_dropout): surface the config validation message, not
        # a traceback.
        raise SystemExit(str(e)) from None

    # model and data must change together: ExperimentConfig.__post_init__
    # checks data.max_len == model.max_len on every replace.
    data_kw: dict[str, Any] = {"max_len": new_model.max_len}
    if getattr(args, "dataset", None):
        data_kw.update(dataset=args.dataset)
    if getattr(args, "batch_size", None):
        data_kw.update(batch_size=args.batch_size, eval_batch_size=args.batch_size)
    if getattr(args, "data_fraction", None):
        data_kw.update(data_fraction=args.data_fraction)
    if getattr(args, "partition", None):
        data_kw.update(partition=args.partition)
    if getattr(args, "dirichlet_alpha", None) is not None:
        # Explicit 0 must reach DataConfig's own validation, not silently
        # fall back to the default.
        data_kw.update(dirichlet_alpha=args.dirichlet_alpha)
    cfg = dataclasses.replace(
        cfg, model=new_model, data=dataclasses.replace(cfg.data, **data_kw)
    )

    train_kw: dict[str, Any] = {}
    if getattr(args, "epochs", None):
        train_kw.update(epochs_per_round=args.epochs)
    if getattr(args, "learning_rate", None):
        train_kw.update(learning_rate=args.learning_rate)
    if getattr(args, "warmup_steps", None) is not None:
        train_kw.update(warmup_steps=args.warmup_steps)
    if getattr(args, "seed", None) is not None:
        train_kw.update(seed=args.seed)
    if getattr(args, "prox_mu", None) is not None:
        # The TCP client's local phase reads TrainConfig.prox_mu (the
        # engine's FedProx step); the mesh tier reads FedConfig.prox_mu
        # (resolved below). One flag feeds whichever tier runs.
        train_kw.update(prox_mu=args.prox_mu)
    if train_kw:
        cfg = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, **train_kw))

    if hasattr(args, "num_clients"):
        n = args.num_clients or cfg.fed.num_clients
        participation = (
            cfg.fed.participation
            if getattr(args, "participation", None) is None
            else args.participation
        )
        # --participation implies the survivor floor can't exceed the
        # sampling rate; clamp ONLY the untouched default floor so an
        # explicitly configured floor still collides loudly in FedConfig
        # validation instead of being silently weakened.
        min_frac = cfg.fed.min_client_fraction
        if participation < min_frac and min_frac == FedConfig().min_client_fraction:
            min_frac = participation
        cfg = dataclasses.replace(
            cfg,
            fed=dataclasses.replace(
                cfg.fed,
                num_clients=n,
                rounds=getattr(args, "rounds", None) or cfg.fed.rounds,
                weighted=(
                    True
                    if getattr(args, "weighted", False)
                    else False
                    if getattr(args, "unweighted", False)
                    else cfg.fed.weighted
                ),
                prox_mu=(
                    cfg.fed.prox_mu
                    if getattr(args, "prox_mu", None) is None
                    else args.prox_mu
                ),
                participation=participation,
                participation_mode=(
                    getattr(args, "participation_mode", None)
                    or cfg.fed.participation_mode
                ),
                min_client_fraction=min_frac,
                dp_clip=(
                    cfg.fed.dp_clip
                    if getattr(args, "dp_clip", None) is None
                    else args.dp_clip
                ),
                dp_noise_multiplier=(
                    cfg.fed.dp_noise_multiplier
                    if getattr(args, "dp_noise_multiplier", None) is None
                    else args.dp_noise_multiplier
                ),
                server_opt=getattr(args, "server_opt", None) or cfg.fed.server_opt,
                server_lr=(
                    cfg.fed.server_lr
                    if getattr(args, "server_lr", None) is None
                    else args.server_lr
                ),
                server_momentum=(
                    cfg.fed.server_momentum
                    if getattr(args, "server_momentum", None) is None
                    else args.server_momentum
                ),
                personalize_epochs=(
                    cfg.fed.personalize_epochs
                    if getattr(args, "personalize_epochs", None) is None
                    else args.personalize_epochs
                ),
                personalize_scope=(
                    getattr(args, "personalize_scope", None)
                    or cfg.fed.personalize_scope
                ),
            ),
            mesh=_resolve_mesh(args, cfg, n),
        )
    if getattr(args, "output_dir", None):
        cfg = dataclasses.replace(cfg, output_dir=args.output_dir)
    if getattr(args, "checkpoint_dir", None):
        cfg = dataclasses.replace(cfg, checkpoint_dir=args.checkpoint_dir)
    return cfg


# --------------------------------------------------------------- pretrained
def _resolve_with_pretrained(args, *, load_weights: bool = True):
    """(tokenizer, resolved config, initial params or None).

    ``load_weights=False`` skips the (full) HF/.pth weight load while still
    resolving tokenizer + architecture from ``--hf-dir`` — for callers
    whose weights come from elsewhere (e.g. distill --teacher-checkpoint).

    With ``--hf-dir`` (the reference's required ``./distilbert-base-uncased``
    directory, client1.py:357,360-361): vocab from its ``vocab.txt``,
    architecture from its ``config.json``, initial encoder weights from its
    checkpoint (fresh head, as at reference client1.py:58). Without it:
    the domain tokenizer and random init.
    """
    hf_dir = getattr(args, "hf_dir", None)
    if getattr(args, "pth", None) and not hf_dir:
        raise SystemExit(
            "--pth needs --hf-dir alongside it: the .pth holds only weights; "
            "the tokenizer and architecture come from the HF checkpoint dir "
            "(the reference requires the same directory, client1.py:357)"
        )
    if not hf_dir:
        from ..data import default_tokenizer

        tok = default_tokenizer()
        return tok, resolve_config(args, vocab_size=len(tok.vocab)), None

    from ..data import WordPieceTokenizer
    from ..models.hf_convert import config_from_hf_dir, load_hf_dir

    tok = WordPieceTokenizer.from_vocab_file(os.path.join(hf_dir, "vocab.txt"))
    # Resolve WITHOUT --max-len: the preset model this produces is discarded
    # below, and validating the flag against its (irrelevant) position table
    # would reject lengths the checkpoint actually supports.
    args_sans_len = copy.copy(args)
    args_sans_len.max_len = None
    cfg = resolve_config(args_sans_len, vocab_size=len(tok.vocab))
    # Architecture comes from the checkpoint; every non-architecture knob
    # (dtypes, dropouts, attention impl, head size) carries over from the
    # resolved config so --config files keep working under --hf-dir.
    # Sequence length defaults to min(128, the checkpoint's position table)
    # — the reference's 128 (client1.py:27) — unless --max-len says else.
    m = cfg.model
    overrides: dict[str, Any] = dict(
        dropout=m.dropout,
        attention_dropout=m.attention_dropout,
        head_dropout=m.head_dropout,
        n_classes=m.n_classes,
        compute_dtype=m.compute_dtype,
        param_dtype=m.param_dtype,
        attention_impl=m.attention_impl,
        ring_axis=m.ring_axis,
        remat=m.remat,
        fused_qkv=m.fused_qkv,
    )
    # Activation precedence: --gelu flag > --config file's model section >
    # the checkpoint's declared activation (config.json) > library default.
    # The config file only wins when it actually SAYS gelu — a file saved
    # before the field existed must not inject today's library default over
    # the checkpoint's declared activation (same legacy rule as
    # ExperimentConfig.from_checkpoint_dict).
    if getattr(args, "gelu", None):
        overrides["gelu"] = args.gelu
    elif getattr(args, "config", None):
        with open(args.config) as f:
            if "gelu" in json.load(f).get("model", {}):
                overrides["gelu"] = m.gelu
    if getattr(args, "max_len", None):
        overrides["max_len"] = args.max_len
    model_cfg = config_from_hf_dir(hf_dir, **overrides)
    if len(tok.vocab) != model_cfg.vocab_size:
        raise SystemExit(
            f"--hf-dir vocab.txt has {len(tok.vocab)} entries but config.json "
            f"says vocab_size={model_cfg.vocab_size}"
        )
    cfg = dataclasses.replace(
        cfg,
        model=model_cfg,
        data=dataclasses.replace(cfg.data, max_len=model_cfg.max_len),
    )
    if not load_weights:
        return tok, cfg, None
    if getattr(args, "pth", None):
        # The reference's own trained artifact: --hf-dir supplies the
        # tokenizer + architecture (exactly as the reference requires that
        # directory, client1.py:56,357), the .pth supplies the weights —
        # mirroring its DDoSClassifier(path) + load_state_dict flow
        # (client1.py:374-377).
        from ..models.hf_convert import load_reference_pth

        with phase(f"loading reference .pth {args.pth}", tag="MODEL"):
            try:
                params = load_reference_pth(args.pth, model_cfg)
            except Exception as e:
                # KeyError = architecture mismatch vs --hf-dir's config.json,
                # FileNotFoundError = bad path, ValueError = headless dict —
                # all operator errors, none deserving a raw traceback.
                raise SystemExit(
                    f"--pth {args.pth}: {type(e).__name__}: {e} — expected "
                    "the reference's DDoSClassifier state dict matching "
                    "--hf-dir's architecture (client1.py:53-58,388)"
                ) from None
        return tok, cfg, params
    with phase(f"loading HF checkpoint {hf_dir}", tag="MODEL"):
        params, _ = load_hf_dir(
            hf_dir, cfg=model_cfg, head_rng=np.random.default_rng(cfg.train.seed)
        )
    return tok, cfg, params


# -------------------------------------------------------------------- data
def _load_client_splits(args, cfg: ExperimentConfig, num_clients: int):
    """CSV / mixed corpus / synthetic -> per-client text splits (host-side
    pandas/numpy only; tokenization is a separate phase so multi-host
    processes can tokenize just their own clients)."""
    from ..data import (
        load_flow_csv,
        load_mixed_corpus,
        make_all_client_splits,
        make_all_client_splits_from_corpus,
        make_synthetic,
        parse_source_arg,
    )

    # Partition manifest (data/partition.py): the non-IID schemes record
    # each client's label histogram next to the run outputs, on BOTH
    # deployment tiers (every tier's loader funnels through here).
    manifest_path = (
        os.path.join(cfg.output_dir, "partition_manifest.json")
        if cfg.data.partition != "sample" and cfg.output_dir
        else None
    )
    if getattr(args, "source", None):
        if getattr(args, "csv", None):
            raise SystemExit("--csv and --source are mutually exclusive")
        # --dataset pins the schema for unprefixed --source entries; entries
        # without either fall back to schema auto-detection.
        default_name = getattr(args, "dataset", None)
        entries = [
            (name or default_name, path)
            for name, path in map(parse_source_arg, args.source)
        ]
        with phase(f"loading {len(entries)}-source mixed corpus", tag="DATA"):
            corpus = load_mixed_corpus(entries)
        with phase("partition/split", tag="DATA"):
            return make_all_client_splits_from_corpus(
                corpus, num_clients, cfg.data, manifest_path=manifest_path
            )
    if getattr(args, "csv", None):
        with phase(f"loading {args.csv}", tag="DATA"):
            df = load_flow_csv(args.csv)
    else:
        n = getattr(args, "synthetic", None) or 2400
        with phase(f"generating {n} synthetic {cfg.data.dataset} flows", tag="DATA"):
            df = make_synthetic(cfg.data.dataset, n, seed=cfg.data.seed_base)
    with phase("partition/split", tag="DATA"):
        return make_all_client_splits(
            df, num_clients, cfg.data, manifest_path=manifest_path
        )


def _load_clients(args, cfg: ExperimentConfig, tok, num_clients: int):
    """Full path: text splits -> tokenized static-shape arrays, all clients."""
    from ..data import tokenize_client

    if getattr(args, "stream", False):
        if not getattr(args, "csv", None):
            raise SystemExit("--stream needs --csv (chunked two-pass reader)")
        from ..data import stream_client_tokens

        with phase(f"streaming {args.csv}", tag="DATA"):
            return stream_client_tokens(
                args.csv, cfg.data, num_clients, tok, max_len=cfg.model.max_len
            )
    splits = _load_client_splits(args, cfg, num_clients)
    with phase("tokenize", tag="DATA"):
        return [tokenize_client(s, tok, max_len=cfg.model.max_len) for s in splits]


# --------------------------------------------------------------- reporting
def _write_reports(
    client_id: int,
    local: dict,
    aggregated: dict | None,
    output_dir: str,
) -> None:
    """The reference's per-client artifact set: one-row metrics CSVs named
    ``client{N}_{local,aggregated}_metrics.csv`` (client1.py:386,401) and the
    plot set under ``client{N}_plots/`` (client1.py:153-225)."""
    from .. import reporting

    os.makedirs(output_dir, exist_ok=True)
    reporting.save_metrics(
        local, os.path.join(output_dir, f"client{client_id}_local_metrics.csv")
    )
    if aggregated is not None:
        reporting.save_metrics(
            aggregated,
            os.path.join(output_dir, f"client{client_id}_aggregated_metrics.csv"),
        )
    written = reporting.plot_evaluation(
        local,
        aggregated,
        os.path.join(output_dir, f"client{client_id}_plots"),
        client_id=client_id,
    )
    log.info(
        f"[CLIENT {client_id}] wrote metrics CSVs and {len(written)} plots "
        f"under {output_dir}"
    )
