"""fedtpu obs — merge per-process span JSONLs into round timelines.

The read side of the obs/ subsystem: every tier (server, clients,
controller, registry, infer-serve) appends spans to its own events-JSONL
(``--trace-jsonl``); this command merges them on the shared
(trace, round) identity and answers "where did round N's wall-clock go".

    fedtpu obs timeline --trace-dir runs/obs
    fedtpu obs timeline --trace server.jsonl --trace client0.jsonl --json
    fedtpu obs export --trace-dir runs/obs --out trace.json
        # load trace.json in chrome://tracing or ui.perfetto.dev
"""

from __future__ import annotations

import json
import sys

from ..obs import (
    export_chrome_trace,
    load_spans,
    round_summaries,
    timeline_table,
)


def cmd_obs(args) -> int:
    paths = list(getattr(args, "trace", None) or [])
    trace_dir = getattr(args, "trace_dir", None)
    if not paths and not trace_dir:
        raise SystemExit(
            "fedtpu obs needs span inputs: --trace-dir DIR (merges every "
            "*.jsonl) and/or --trace FILE (repeatable)"
        )
    spans = load_spans(paths, trace_dir=trace_dir)
    if not spans:
        raise SystemExit(
            "no obs spans found (are these files written by --trace-jsonl "
            "/ obs.trace.Tracer? metrics-JSONL streams are a different "
            "schema)"
        )
    if args.action == "export":
        out = getattr(args, "out", None)
        if not out:
            raise SystemExit("obs export needs --out <chrome_trace.json>")
        path = export_chrome_trace(spans, out)
        print(
            f"wrote {path} ({len(spans)} spans; load in chrome://tracing "
            "or ui.perfetto.dev)"
        )
        return 0
    if args.action == "timeline":
        round_filter = getattr(args, "round", None)
        if getattr(args, "json", False):
            rounds = round_summaries(spans)
            if round_filter is not None:
                rounds = [r for r in rounds if r["round"] == round_filter]
            json.dump(rounds, sys.stdout, indent=2)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(
                timeline_table(spans, round_filter=round_filter)
            )
        return 0
    raise SystemExit(f"unknown obs action {args.action!r}")
