"""fedtpu obs — merge per-process span JSONLs into round timelines.

The read side of the obs/ subsystem: every tier (server, clients,
controller, registry, infer-serve) appends spans to its own events-JSONL
(``--trace-jsonl``); this command merges them on the shared
(trace, round) identity and answers "where did round N's wall-clock go".

    fedtpu obs timeline --trace-dir runs/obs
    fedtpu obs timeline --trace server.jsonl --trace client0.jsonl --json
    fedtpu obs export --trace-dir runs/obs --out trace.json
        # load trace.json in chrome://tracing or ui.perfetto.dev
    fedtpu obs tail --trace-dir runs/obs --round 3
        # live follow mode: one line per span as processes append them
        # (--trace-id/--round filter; --from-start replays history first)
"""

from __future__ import annotations

import json
import sys
import time

from ..obs import (
    export_chrome_trace,
    load_spans,
    round_summaries,
    tail_spans,
    timeline_table,
)


def _tail_line(rec: dict) -> str:
    """One human-readable line per span (the tail format): local time,
    proc, span, duration, identity, then every extra attribute."""
    ts = time.strftime("%H:%M:%S", time.localtime(rec["ts"]))
    head = (
        f"{ts} {str(rec.get('proc', '?')):<12} {rec['span']:<15} "
        f"{rec['dur_s'] * 1e3:9.1f}ms"
    )
    ident = []
    if rec.get("trace") is not None:
        ident.append(f"trace={rec['trace']}")
    if rec.get("round") is not None:
        ident.append(f"round={rec['round']}")
    skip = {"schema", "run_id", "proc", "span", "ts", "dur_s", "trace", "round"}
    attrs = [f"{k}={rec[k]}" for k in rec if k not in skip]
    return " ".join([head] + ident + attrs)


def _cmd_tail(args, paths, trace_dir) -> int:
    """Live follow mode over the events-JSONL set. Unlike the batch
    actions, an empty/missing input is NOT an error — tailing a
    directory that processes will write into shortly is the point."""
    trace_filter = getattr(args, "trace_id", None)
    round_filter = getattr(args, "round", None)
    max_seconds = getattr(args, "max_seconds", None)
    deadline = (
        time.monotonic() + float(max_seconds)
        if max_seconds is not None
        else None
    )
    stop = (
        (lambda: time.monotonic() >= deadline)
        if deadline is not None
        else None
    )
    try:
        for rec in tail_spans(
            paths,
            trace_dir=trace_dir,
            poll_s=getattr(args, "poll", None) or 0.5,
            from_start=getattr(args, "from_start", False),
            stop=stop,
        ):
            if trace_filter is not None and rec.get("trace") != trace_filter:
                continue
            if round_filter is not None and rec.get("round") != round_filter:
                continue
            print(_tail_line(rec), flush=True)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_obs(args) -> int:
    paths = list(getattr(args, "trace", None) or [])
    trace_dir = getattr(args, "trace_dir", None)
    if not paths and not trace_dir:
        raise SystemExit(
            "fedtpu obs needs span inputs: --trace-dir DIR (merges every "
            "*.jsonl) and/or --trace FILE (repeatable)"
        )
    if args.action == "tail":
        return _cmd_tail(args, paths, trace_dir)
    spans = load_spans(paths, trace_dir=trace_dir)
    if not spans:
        raise SystemExit(
            "no obs spans found (are these files written by --trace-jsonl "
            "/ obs.trace.Tracer? metrics-JSONL streams are a different "
            "schema)"
        )
    if args.action == "export":
        out = getattr(args, "out", None)
        if not out:
            raise SystemExit("obs export needs --out <chrome_trace.json>")
        path = export_chrome_trace(spans, out)
        print(
            f"wrote {path} ({len(spans)} spans; load in chrome://tracing "
            "or ui.perfetto.dev)"
        )
        return 0
    if args.action == "timeline":
        round_filter = getattr(args, "round", None)
        if getattr(args, "json", False):
            rounds = round_summaries(spans)
            if round_filter is not None:
                rounds = [r for r in rounds if r["round"] == round_filter]
            json.dump(rounds, sys.stdout, indent=2)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(
                timeline_table(spans, round_filter=round_filter)
            )
        return 0
    raise SystemExit(f"unknown obs action {args.action!r}")
