"""fedtpu obs — timelines, live tailing, fleet health, postmortems.

The read side of the obs/ subsystem: every tier (server, clients,
controller, registry, infer-serve) appends spans to its own events-JSONL
(``--trace-jsonl``); this command merges them on the shared
(trace, round) identity and answers "where did round N's wall-clock go".

    fedtpu obs timeline --trace-dir runs/obs
    fedtpu obs timeline --trace server.jsonl --trace client0.jsonl --json
    fedtpu obs export --trace-dir runs/obs --out trace.json
        # load trace.json in chrome://tracing or ui.perfetto.dev
    fedtpu obs tail --trace-dir runs/obs --round 3
        # live follow mode: one line per span as processes append them
        # (--trace-id/--round filter; --from-start replays history first)
    fedtpu obs health --target serve=127.0.0.1:9100 \\
                      --target route=127.0.0.1:9102
        # one scrape pass over every daemon's /metrics.json + the SLO
        # burn-rate verdicts, rendered as a one-screen fleet view
        # (--slo FILE for custom objectives, --alerts-jsonl /
        # --snapshot-jsonl to persist alerts + fleet snapshots)
    fedtpu obs watch --target ... --interval 2
        # the live-refresh twin (`health --watch` is the same loop)
    fedtpu obs postmortem --flight-dir runs/flight [--bundle NAME]
        # list flight-recorder bundles / inspect one (--json full dump)
    fedtpu obs profile --preset tiny --steps 12 [--capture DIR]
        # device performance plane (obs/profile.py): compile ledger by
        # site, recompile flags, fenced host/dispatch/device step
        # split, memory watermarks, analytic-vs-XLA FLOPs cross-check
    fedtpu obs sentinel --canaries tests/data/canary_flows.jsonl \\
                        --serve 127.0.0.1:9000 --registry-dir runs/reg \\
                        --scored-jsonl runs/scored.jsonl \\
                        --labels-journal runs/reg/labels/journal.jsonl \\
                        --reference-error 0.05 --ring-jsonl runs/ring.jsonl
        # the sentinel watch daemon (obs/sentinel.py): known-truth
        # canary probes through the live serving chain (pointer +
        # bit-stability + latency), continuous journal-tailing
        # supervised drift between gates (--verdicts-jsonl feeds the
        # controller's --sentinel-jsonl poke), and the long-horizon
        # retention ring's pinned-baseline regression verdicts
        # (--json = ONE tick, machine-readable, exit 1 on any finding)
"""

from __future__ import annotations

import json
import sys
import time

from ..obs import (
    ScrapeHub,
    Tracer,
    default_slos,
    export_chrome_trace,
    health_verdict,
    list_bundles,
    load_bundle,
    load_spans,
    parse_target,
    round_summaries,
    slos_from_spec,
    tail_spans,
    timeline_table,
)


def _tail_line(rec: dict) -> str:
    """One human-readable line per span (the tail format): local time,
    proc, span, duration, identity, then every extra attribute."""
    ts = time.strftime("%H:%M:%S", time.localtime(rec["ts"]))
    head = (
        f"{ts} {str(rec.get('proc', '?')):<12} {rec['span']:<15} "
        f"{rec['dur_s'] * 1e3:9.1f}ms"
    )
    ident = []
    if rec.get("trace") is not None:
        ident.append(f"trace={rec['trace']}")
    if rec.get("round") is not None:
        ident.append(f"round={rec['round']}")
    skip = {"schema", "run_id", "proc", "span", "ts", "dur_s", "trace", "round"}
    attrs = [f"{k}={rec[k]}" for k in rec if k not in skip]
    return " ".join([head] + ident + attrs)


def _cmd_tail(args, paths, trace_dir) -> int:
    """Live follow mode over the events-JSONL set. Unlike the batch
    actions, an empty/missing input is NOT an error — tailing a
    directory that processes will write into shortly is the point."""
    trace_filter = getattr(args, "trace_id", None)
    round_filter = getattr(args, "round", None)
    max_seconds = getattr(args, "max_seconds", None)
    deadline = (
        time.monotonic() + float(max_seconds)
        if max_seconds is not None
        else None
    )
    stop = (
        (lambda: time.monotonic() >= deadline)
        if deadline is not None
        else None
    )
    try:
        for rec in tail_spans(
            paths,
            trace_dir=trace_dir,
            poll_s=getattr(args, "poll", None) or 0.5,
            from_start=getattr(args, "from_start", False),
            stop=stop,
        ):
            if trace_filter is not None and rec.get("trace") != trace_filter:
                continue
            if round_filter is not None and rec.get("round") != round_filter:
                continue
            print(_tail_line(rec), flush=True)
    except KeyboardInterrupt:
        pass
    return 0


def _build_hub(args) -> ScrapeHub:
    specs = getattr(args, "target", None) or []
    if not specs:
        raise SystemExit(
            "fedtpu obs health/watch needs at least one "
            "--target TIER=HOST:PORT[,events=PATH] (the daemon's "
            "--metrics-port endpoint; /metrics.json is served there)"
        )
    try:
        targets = [parse_target(s) for s in specs]
    except ValueError as e:
        raise SystemExit(str(e)) from None
    slos = None
    slo_path = getattr(args, "slo", None)
    if slo_path:
        with open(slo_path) as f:
            spec = json.load(f)
        try:
            slos = slos_from_spec(spec)
        except (TypeError, ValueError) as e:
            raise SystemExit(f"--slo {slo_path}: {e}") from None
    else:
        slos = default_slos()
    tracer = None
    if getattr(args, "trace_jsonl", None):
        tracer = Tracer(args.trace_jsonl, proc="obs-hub")
    recorder = None
    if getattr(args, "flight_dir", None):
        # The hub is where SLO evaluation actually happens, so the hub
        # is where a page-severity fire can dump a postmortem — the
        # daemons' own recorders live in other processes and never
        # learn of the page.
        from ..obs import FlightRecorder

        recorder = FlightRecorder(
            args.flight_dir, proc="obs-hub", tracer=tracer
        )
    alert_interval = getattr(args, "alert_interval", None)
    try:
        return ScrapeHub(
            targets,
            slos=slos,
            alerts_jsonl=getattr(args, "alerts_jsonl", None),
            snapshot_jsonl=getattr(args, "snapshot_jsonl", None),
            snapshot_max_mb=getattr(args, "snapshot_max_mb", None),
            scrape_timeout_s=getattr(args, "scrape_timeout", None) or 2.0,
            tracer=tracer,
            recorder=recorder,
            alert_cmd=getattr(args, "alert_cmd", None),
            # is-None, not falsy-or: an explicit --alert-interval 0
            # means "spawn on every page fire", not the 30 s default.
            alert_cmd_interval_s=(
                30.0 if alert_interval is None else alert_interval
            ),
        )
    except ValueError as e:
        raise SystemExit(str(e)) from None


def _cmd_health(args) -> int:
    """One scrape pass (or the --watch loop) + the fleet status screen."""
    hub = _build_hub(args)
    if getattr(args, "watch", False) or args.action == "watch":
        hub.watch(
            interval_s=getattr(args, "interval", None) or 2.0,
            max_seconds=getattr(args, "max_seconds", None),
        )
        return 0
    # TWO spaced polls, not one: burn rates and round cadence are
    # DELTAS of cumulative counters — a single scrape has no baseline,
    # so a one-shot pass could never report a firing SLO and the
    # cron-able exit code would only ever detect down targets.
    hub.poll()
    time.sleep(getattr(args, "interval", None) or 2.0)
    snapshot = hub.poll()
    if getattr(args, "json", False):
        # The schema-versioned VERDICT (fedtpu-health-v1), not the raw
        # snapshot: cron/CI consumers parse one stable judgement shape;
        # the raw per-poll records live in --snapshot-jsonl.
        json.dump(health_verdict(snapshot), sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(hub.render_status(snapshot))
    firing = sum(1 for s in snapshot["slo"] if s["firing"])
    down = sum(1 for t in snapshot["targets"] if not t["up"])
    # Exit code is the health verdict (cron-able): 0 healthy, 1 not.
    return 1 if (firing or down) else 0


def _cmd_postmortem(args) -> int:
    """List/inspect flight-recorder bundles."""
    flight_dir = getattr(args, "flight_dir", None)
    if not flight_dir:
        raise SystemExit("fedtpu obs postmortem needs --flight-dir DIR")
    bundle_name = getattr(args, "bundle", None)
    if bundle_name:
        import os

        path = (
            bundle_name
            if os.path.sep in bundle_name
            else os.path.join(flight_dir, bundle_name)
        )
        b = load_bundle(path)
        if b is None:
            raise SystemExit(f"no readable postmortem bundle at {path}")
        if getattr(args, "json", False):
            json.dump(b, sys.stdout, indent=2)
            sys.stdout.write("\n")
            return 0
        ts = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(b["ts"]))
        print(f"bundle   {path}")
        print(f"proc     {b['proc']}")
        print(f"reason   {b['reason']}  ({ts})")
        if b.get("extra"):
            print(f"context  {json.dumps(b['extra'])}")
        alerts = b.get("alerts") or []
        print(f"alerts   {len(alerts)}")
        for a in alerts[-5:]:
            print(
                f"  {a.get('event')} {a.get('slo')} on "
                f"{a.get('instance')} burn={a.get('burn')}"
            )
        spans = b.get("spans") or []
        print(f"spans    {len(spans)} (newest last)")
        for s in spans[-10:]:
            print("  " + _tail_line(s))
        return 0
    bundles = list_bundles(flight_dir)
    if getattr(args, "json", False):
        json.dump(bundles, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 0
    if not bundles:
        print(f"(no postmortem bundles under {flight_dir})")
        return 0
    for b in bundles:
        ts = time.strftime(
            "%Y-%m-%d %H:%M:%S", time.localtime(b["ts"] or 0)
        )
        print(
            f"{ts}  {b['proc']:<12} {b['reason']:<16} "
            f"{b['spans']:>4} span(s) {b['alerts']:>3} alert(s)  "
            f"{b['name']}"
        )
    return 0


def _cmd_profile(args) -> int:
    """Run the device performance plane end-to-end on real train steps
    (obs/profile.py run_profile_session) and render the report. Exit 1
    when a recompile was flagged or the FLOPs ratio broke tolerance —
    the cron-able "device plane healthy" verdict."""
    from ..config import ModelConfig, TrainConfig
    from ..obs.profile import render_profile_report, run_profile_session

    preset = getattr(args, "preset", None) or "tiny"
    presets = {
        "tiny": ModelConfig.tiny,
        "distilbert": ModelConfig,
        "bert": ModelConfig.bert_base,
        "bert-large": ModelConfig.bert_large,
    }
    if preset not in presets:
        raise SystemExit(
            f"unknown --preset {preset!r} (tiny|distilbert|bert|bert-large)"
        )
    # `is None` checks, not `or`: an explicit `--stride 0` is the
    # documented fence-nothing value and must reach the session as 0.
    steps = getattr(args, "steps", None)
    batch_size = getattr(args, "batch_size", None)
    stride = getattr(args, "stride", None)
    report = run_profile_session(
        presets[preset](),
        TrainConfig(),
        steps=12 if steps is None else steps,
        batch_size=8 if batch_size is None else batch_size,
        stride=1 if stride is None else stride,
        capture_dir=getattr(args, "capture", None),
    )
    if getattr(args, "json", False):
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_profile_report(report))
    broken = bool(report["recompiles"]) or not report["flops_ratio_ok"]
    srv = report.get("serving")
    if srv is not None and srv["recompiles"]:
        broken = True
    return 1 if broken else 0


def _cmd_sentinel(args) -> int:
    """Assemble + run the sentinel daemon (obs/sentinel.py): canary
    probes, journal-tailing supervised drift, long-horizon ring. Any
    rung may be absent; at least one must be configured. ``--json``
    runs ONE tick and prints the machine-readable report (exit 1 when
    the tick surfaced any incident); default is the watch loop."""
    from ..control.drift import ErrorRateMonitor
    from ..obs.sentinel import (
        CanaryProber,
        JournalTail,
        RetentionRing,
        Sentinel,
        load_canary_flows,
    )

    tracer = None
    if getattr(args, "trace_jsonl", None):
        tracer = Tracer(args.trace_jsonl, proc="sentinel")
    recorder = None
    if getattr(args, "flight_dir", None):
        from ..obs import FlightRecorder

        recorder = FlightRecorder(
            args.flight_dir, proc="sentinel", tracer=tracer
        )
    prober = None
    if getattr(args, "canaries", None):
        serve = getattr(args, "serve", None)
        if not serve or ":" not in serve:
            raise SystemExit(
                "sentinel --canaries needs --serve HOST:PORT (the "
                "scoring endpoint the probes dial)"
            )
        host, _, port_s = serve.rpartition(":")
        try:
            flows = load_canary_flows(
                args.canaries, preset=getattr(args, "canary_preset", None)
            )
        except (OSError, ValueError) as e:
            raise SystemExit(f"--canaries {args.canaries}: {e}") from None
        registry = None
        if getattr(args, "registry_dir", None):
            from ..registry import ModelRegistry

            registry = ModelRegistry(args.registry_dir)
        prober = CanaryProber(
            flows,
            host,
            int(port_s),
            registry=registry,
            tracer=tracer,
            recorder=recorder,
        )
    tail = None
    scored = getattr(args, "scored_jsonl", None)
    journal = getattr(args, "labels_journal", None)
    if scored or journal:
        if not (scored and journal):
            raise SystemExit(
                "sentinel journal tailing needs BOTH --scored-jsonl and "
                "--labels-journal (the join has two sides)"
            )
        ref = getattr(args, "reference_error", None)
        if ref is None:
            raise SystemExit(
                "sentinel journal tailing needs --reference-error (the "
                "promoted model's error the continuous monitor compares "
                "against — the registry manifest's eval error)"
            )
        monitor = ErrorRateMonitor(
            reference_error=ref,
            margin=getattr(args, "error_margin", None) or 0.05,
            min_joined=getattr(args, "error_min_joined", None) or 64,
        )
        tail = JournalTail(
            scored,
            journal,
            monitor=monitor,
            verdicts_jsonl=getattr(args, "verdicts_jsonl", None),
            tracer=tracer,
        )
    ring = RetentionRing(
        getattr(args, "ring_jsonl", None),
        max_records=getattr(args, "ring_records", None) or 512,
        stride=getattr(args, "ring_stride", None) or 1,
        baseline_n=getattr(args, "baseline_n", None) or 8,
        window_n=getattr(args, "window_n", None) or 8,
    )
    # Per-deployment trend fields (--trend-field NAME[:direction]): merge
    # BEFORE the --regression-ratio rewrite, so a custom ratio applies to
    # the custom fields exactly as it does to the stock ones.
    for spec in getattr(args, "trend_field", None) or ():
        from ..obs.sentinel import parse_trend_field_spec

        try:
            name, entry = parse_trend_field_spec(spec)
        except ValueError as e:
            raise SystemExit(str(e)) from None
        ring.trend_fields[name] = entry
    ratio = getattr(args, "regression_ratio", None)
    if ratio is not None:
        if ratio <= 1.0:
            raise SystemExit(
                f"--regression-ratio {ratio} must be > 1 (it multiplies "
                "the baseline mean)"
            )
        ring.trend_fields = {
            f: (float(ratio), floor, direction)
            for f, (_, floor, direction) in ring.trend_fields.items()
        }
    hub = None
    if getattr(args, "target", None):
        hub = _build_hub(args)
    if prober is None and tail is None:
        raise SystemExit(
            "fedtpu obs sentinel needs at least one rung: --canaries + "
            "--serve (canary probes) and/or --scored-jsonl + "
            "--labels-journal + --reference-error (supervised drift); "
            "the retention ring rides whichever signals exist"
        )
    sentinel = Sentinel(
        prober=prober,
        tail=tail,
        ring=ring,
        hub=hub,
        alerts_jsonl=getattr(args, "alerts_jsonl", None),
        tracer=tracer,
        recorder=recorder,
    )
    if getattr(args, "json", False):
        report = sentinel.tick()
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write("\n")
        c = report["counters"]
        bad = (
            c["canary_flips"] + c["drift_fires"] + c["regression_fires"]
        ) or (report["canary"] or {}).get("failures", 0)
        return 1 if bad else 0
    sentinel.watch(
        interval_s=getattr(args, "interval", None) or 5.0,
        max_seconds=getattr(args, "max_seconds", None),
    )
    return 0


def cmd_obs(args) -> int:
    if args.action in ("health", "watch"):
        return _cmd_health(args)
    if args.action == "sentinel":
        return _cmd_sentinel(args)
    if args.action == "postmortem":
        return _cmd_postmortem(args)
    if args.action == "profile":
        return _cmd_profile(args)
    paths = list(getattr(args, "trace", None) or [])
    trace_dir = getattr(args, "trace_dir", None)
    if not paths and not trace_dir:
        raise SystemExit(
            "fedtpu obs needs span inputs: --trace-dir DIR (merges every "
            "*.jsonl) and/or --trace FILE (repeatable)"
        )
    if args.action == "tail":
        return _cmd_tail(args, paths, trace_dir)
    spans = load_spans(paths, trace_dir=trace_dir)
    if not spans:
        raise SystemExit(
            "no obs spans found (are these files written by --trace-jsonl "
            "/ obs.trace.Tracer? metrics-JSONL streams are a different "
            "schema)"
        )
    if args.action == "export":
        out = getattr(args, "out", None)
        if not out:
            raise SystemExit("obs export needs --out <chrome_trace.json>")
        path = export_chrome_trace(spans, out)
        print(
            f"wrote {path} ({len(spans)} spans; load in chrome://tracing "
            "or ui.perfetto.dev)"
        )
        return 0
    if args.action == "timeline":
        round_filter = getattr(args, "round", None)
        if getattr(args, "json", False):
            rounds = round_summaries(spans)
            if round_filter is not None:
                rounds = [r for r in rounds if r["round"] == round_filter]
            json.dump(rounds, sys.stdout, indent=2)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(
                timeline_table(spans, round_filter=round_filter)
            )
        return 0
    raise SystemExit(f"unknown obs action {args.action!r}")
