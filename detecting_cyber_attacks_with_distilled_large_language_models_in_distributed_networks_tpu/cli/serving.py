"""fedtpu infer-serve — the online scoring service (serving/).

The deployment step after ``predict``: instead of a one-shot CSV pass,
stand up a TCP detector that answers live flow queries through the
dynamic micro-batcher, picks up new federated checkpoints between
batches, and sheds load explicitly when over capacity. ``serve`` remains
the FL *aggregation* server; this is the *inference* server the ROADMAP
north star ("serves heavy traffic") was missing.
"""

from __future__ import annotations

import time

from ..utils.logging import get_logger
from .common import _resolve_with_pretrained

log = get_logger()


def _parse_buckets(spec: str) -> tuple[int, ...]:
    try:
        buckets = tuple(sorted({int(b) for b in spec.split(",") if b.strip()}))
    except ValueError:
        raise SystemExit(
            f"--buckets {spec!r}: want a comma-separated int list, e.g. "
            "1,8,32,128"
        ) from None
    if not buckets or buckets[0] < 1:
        raise SystemExit(f"--buckets {spec!r}: bucket sizes must be >= 1")
    return buckets


def cmd_infer_serve(args) -> int:
    from ..data.datasets import get_dataset
    from ..serving import (
        CheckpointWatcher,
        MicroBatcher,
        ScoreEngine,
        ScoringServer,
    )
    from ..serving.reload import checkpoint_restorer

    tok, cfg, pretrained = _resolve_with_pretrained(args)
    buckets = _parse_buckets(args.buckets)
    if args.max_queue < buckets[-1]:
        # Validate BEFORE the (slow) checkpoint restore, and as an
        # operator-facing message like every other flag check here.
        raise SystemExit(
            f"--max-queue {args.max_queue} is smaller than the largest "
            f"bucket {buckets[-1]}: the queue could never fill one batch"
        )
    if not cfg.checkpoint_dir and pretrained is None:
        raise SystemExit(
            "infer-serve needs trained weights: pass --checkpoint-dir (a "
            "local or federated training checkpoint; also enables hot "
            "reload) or --hf-dir (a fine-tuned classifier checkpoint)"
        )
    watcher = None
    if cfg.checkpoint_dir:
        from ..serving.reload import latest_finalized_step

        # One restore path for the initial load AND every hot reload —
        # the round-id derivation (meta "round", step fallback) must not
        # exist twice and drift.
        restore = checkpoint_restorer(cfg, tok)
        step = latest_finalized_step(cfg.checkpoint_dir)
        model_cfg, params, round_id = restore(step)
        watcher = CheckpointWatcher(
            cfg.checkpoint_dir, restore, poll_interval_s=args.reload_poll
        )
        # Prime with the step just restored (never a fresh directory
        # scan): a round finalized between restore and server start must
        # count as NEW on the first poll, not be marked already-seen.
        watcher.prime(step)
    else:
        model_cfg, params, round_id = cfg.model, pretrained, 0
    engine = ScoreEngine(
        model_cfg,
        params,
        pad_id=tok.pad_id,
        buckets=buckets,
        round_id=round_id,
    )
    batcher = MicroBatcher(
        max_batch=buckets[-1],
        max_queue=args.max_queue,
        gather_window_s=args.max_wait_ms / 1e3,
    )
    server = ScoringServer(
        engine,
        tok,
        host=args.host,
        port=args.port,
        spec=get_dataset(cfg.data.dataset),
        threshold=args.threshold,
        batcher=batcher,
        watcher=watcher,
        default_deadline_s=(
            args.default_deadline_ms / 1e3
            if args.default_deadline_ms is not None
            else None
        ),
        metrics_jsonl=getattr(args, "metrics_jsonl", None),
    )
    with server:
        log.info(
            f"[SERVE] scoring {cfg.data.dataset} flows on "
            f"{args.host}:{server.port} (model round {engine.round_id}; "
            f"hot reload {'on' if watcher else 'off — no --checkpoint-dir'})"
        )
        try:
            while True:
                time.sleep(60.0)
                s = server.stats()
                log.info(
                    f"[SERVE] {s['scored']} flows served "
                    f"({s['flows_per_sec']:.1f}/s), p50 {s['p50_ms']:.2f} ms "
                    f"p99 {s['p99_ms']:.2f} ms, round {s['round']}, "
                    f"rejects {s['rejects']}"
                )
        except KeyboardInterrupt:
            log.info("[SERVE] interrupted; draining")
    return 0
