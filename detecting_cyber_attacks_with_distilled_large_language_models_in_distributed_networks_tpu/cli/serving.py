"""fedtpu infer-serve — the online scoring service (serving/).

The deployment step after ``predict``: instead of a one-shot CSV pass,
stand up a TCP detector that answers live flow queries through the
dynamic micro-batcher, picks up new federated checkpoints between
batches, and sheds load explicitly when over capacity. ``serve`` remains
the FL *aggregation* server; this is the *inference* server the ROADMAP
north star ("serves heavy traffic") was missing.
"""

from __future__ import annotations

import time

from ..utils.logging import get_logger
from .common import _obs_setup, _resolve_with_pretrained

log = get_logger()


def _parse_buckets(spec: str) -> tuple[int, ...]:
    try:
        buckets = tuple(sorted({int(b) for b in spec.split(",") if b.strip()}))
    except ValueError:
        raise SystemExit(
            f"--buckets {spec!r}: want a comma-separated int list, e.g. "
            "1,8,32,128"
        ) from None
    if not buckets or buckets[0] < 1:
        raise SystemExit(f"--buckets {spec!r}: bucket sizes must be >= 1")
    return buckets


def cmd_infer_serve(args) -> int:
    from ..data.datasets import get_dataset
    from ..serving import (
        CheckpointWatcher,
        MicroBatcher,
        RegistryWatcher,
        ScoreEngine,
        ScoringServer,
    )
    from ..serving.reload import checkpoint_restorer

    tok, cfg, pretrained = _resolve_with_pretrained(args)
    buckets = _parse_buckets(args.buckets)
    # Sharded scorer (--data-parallel N --fsdp): params live split
    # per-leaf across this host's chips and every bucket program
    # all-gathers them at use — serving a model bigger than one chip.
    # The mesh is built BEFORE the restore so checkpoint leaves scatter
    # straight onto their shards (never one full-size copy per chip).
    mesh = None
    n_dp = int(getattr(args, "data_parallel", None) or 0)
    if getattr(args, "fsdp", None):
        if n_dp < 2:
            raise SystemExit(
                "--fsdp shards the model over the serving mesh: pass "
                "--data-parallel N with N >= 2"
            )
        from ..parallel.mesh import make_host_mesh

        mesh = make_host_mesh(n_dp)
    elif n_dp > 1:
        raise SystemExit(
            "infer-serve uses --data-parallel only for --fsdp sharding "
            "(replicated data-parallel serving is the fleet tier: "
            "`fedtpu fleet --replicas N`)"
        )
    if args.max_queue < buckets[-1]:
        # Validate BEFORE the (slow) checkpoint restore, and as an
        # operator-facing message like every other flag check here.
        raise SystemExit(
            f"--max-queue {args.max_queue} is smaller than the largest "
            f"bucket {buckets[-1]}: the queue could never fill one batch"
        )
    auth_key = None
    if getattr(args, "auth", False):
        from .comm import _auth_key

        auth_key = _auth_key()
        if auth_key is None:
            raise SystemExit(
                "--auth needs the shared secret in the FEDTPU_SECRET env "
                "var (same value on server and every scoring client)"
            )
    registry_dir = getattr(args, "registry_dir", None)
    if registry_dir and cfg.checkpoint_dir:
        raise SystemExit(
            "--registry-dir and --checkpoint-dir are two different reload "
            "sources (eval-gated pointer vs raw latest step); pass one"
        )
    if not registry_dir and not cfg.checkpoint_dir and pretrained is None:
        raise SystemExit(
            "infer-serve needs trained weights: pass --registry-dir (serve "
            "the control plane's PROMOTED artifact, hot-swapped on "
            "promotion), --checkpoint-dir (a local or federated training "
            "checkpoint; hot reload of the latest step) or --hf-dir (a "
            "fine-tuned classifier checkpoint)"
        )
    watcher = None
    if registry_dir:
        from ..registry import ModelRegistry

        # Pointer-following deployment: the initial load AND every swap
        # come from the registry's serving pointer — this process can only
        # ever score with an artifact the eval gate promoted.
        registry = ModelRegistry(registry_dir)
        info = registry.serving_info()
        if info is None:
            raise SystemExit(
                f"registry {registry_dir} has no serving artifact yet — "
                "run `fedtpu controller` (or `fedtpu registry promote`) "
                "to promote one first"
            )
        manifest = registry.manifest(info["artifact"])
        model_cfg = cfg.model
        if manifest.get("model_config"):
            from ..config import ModelConfig

            model_cfg = ModelConfig(**manifest["model_config"])
        if model_cfg.vocab_size != len(tok.vocab):
            raise SystemExit(
                f"serving artifact's model vocab ({model_cfg.vocab_size}) "
                f"!= tokenizer vocab ({len(tok.vocab)}); pass the matching "
                "--hf-dir / vocab"
            )
        params = registry.load_params(info["artifact"])
        round_id = int(manifest.get("round", 0))
        watcher = RegistryWatcher(
            registry, poll_interval_s=args.reload_poll
        )
        watcher.prime(info["artifact"])
        log.info(
            f"[SERVE] serving promoted artifact {info['artifact']} "
            f"(round {round_id}) from registry {registry_dir}"
        )
    elif cfg.checkpoint_dir:
        from ..serving.reload import latest_finalized_step

        # One restore path for the initial load AND every hot reload —
        # the round-id derivation (meta "round", step fallback) must not
        # exist twice and drift.
        restore = checkpoint_restorer(cfg, tok, mesh=mesh)
        step = latest_finalized_step(cfg.checkpoint_dir)
        model_cfg, params, round_id = restore(step)
        watcher = CheckpointWatcher(
            cfg.checkpoint_dir, restore, poll_interval_s=args.reload_poll
        )
        # Prime with the step just restored (never a fresh directory
        # scan): a round finalized between restore and server start must
        # count as NEW on the first poll, not be marked already-seen.
        watcher.prime(step)
    else:
        model_cfg, params, round_id = cfg.model, pretrained, 0
    engine = ScoreEngine(
        model_cfg,
        params,
        pad_id=tok.pad_id,
        buckets=buckets,
        round_id=round_id,
        mesh=mesh,
    )
    if mesh is not None:
        log.info(
            f"[SERVE] sharded scorer: params split over {n_dp} chips "
            "(gathered at use inside each warm bucket program)"
        )
    batcher = MicroBatcher(
        max_batch=buckets[-1],
        max_queue=args.max_queue,
        gather_window_s=args.max_wait_ms / 1e3,
    )
    tracer, _metrics = _obs_setup(
        args, proc="serve", cfg=cfg, metrics_host=args.host
    )
    server = ScoringServer(
        engine,
        tok,
        host=args.host,
        port=args.port,
        spec=get_dataset(cfg.data.dataset),
        threshold=args.threshold,
        batcher=batcher,
        watcher=watcher,
        default_deadline_s=(
            args.default_deadline_ms / 1e3
            if args.default_deadline_ms is not None
            else None
        ),
        metrics_jsonl=getattr(args, "metrics_jsonl", None),
        scored_jsonl=getattr(args, "scored_jsonl", None),
        auth_key=auth_key,
        # The drift contract: serving-score histograms and the promoted
        # artifact's eval reference must bin identically (ControlConfig).
        score_bins=cfg.control.score_bins,
        tracer=tracer,
        # serve-batch span sampling for high-rate streams: --trace-sample
        # overrides the config's obs.trace_sample (both default 1.0).
        trace_sample=(
            args.trace_sample
            if getattr(args, "trace_sample", None) is not None
            else cfg.obs.trace_sample
        ),
    )
    reload_src = (
        "registry pointer"
        if registry_dir
        else ("checkpoint dir" if cfg.checkpoint_dir else "off")
    )
    with server:
        log.info(
            f"[SERVE] scoring {cfg.data.dataset} flows on "
            f"{args.host}:{server.port} (model round {engine.round_id}; "
            f"hot reload: {reload_src}; auth "
            f"{'on' if auth_key else 'off — open port'})"
        )
        try:
            while True:
                time.sleep(60.0)
                s = server.stats()
                log.info(
                    f"[SERVE] {s['scored']} flows served "
                    f"({s['flows_per_sec']:.1f}/s), p50 {s['p50_ms']:.2f} ms "
                    f"p99 {s['p99_ms']:.2f} ms, round {s['round']}, "
                    f"rejects {s['rejects']}"
                )
        except KeyboardInterrupt:
            log.info("[SERVE] interrupted; draining")
    return 0
