"""fedtpu distill — teacher -> student knowledge distillation (the
recipe behind the reference's pre-distilled encoder, client1.py:56)."""

from __future__ import annotations

import dataclasses
import os

from ..utils.logging import get_logger, phase
from .common import _load_clients, _resolve_with_pretrained
from .predict import _restore_predict_params

log = get_logger()


def cmd_distill(args) -> int:
    """Teacher -> student knowledge distillation — the recipe that produced
    the reference's pretrained DistilBERT (client1.py:56).

    Teacher sources, in precedence order: ``--teacher-checkpoint`` (a model
    trained here, e.g. a federated aggregate), ``--pth`` + ``--hf-dir``
    (a model the REFERENCE trained), or a fresh teacher trained in-run
    (2x student depth by default). ``--student-layers`` shrinks the student
    below the resolved model depth (e.g. distill a migrated 6-layer
    reference model into 3 layers)."""
    from .. import reporting
    from ..train.distill import DistillTrainer
    from ..train.engine import Trainer

    if getattr(args, "teacher_checkpoint", None) and getattr(args, "pth", None):
        raise SystemExit(
            "--teacher-checkpoint and --pth are both teacher sources; pass one"
        )
    if getattr(args, "pth", None) and args.teacher_layers is not None:
        raise SystemExit(
            "--teacher-layers has no effect when --pth supplies the "
            "teacher (its depth comes from --hf-dir's config.json)"
        )
    if getattr(args, "student_layers", None) is not None and args.student_layers < 1:
        raise SystemExit(f"--student-layers {args.student_layers} must be >= 1")
    # --teacher-checkpoint supplies the weights; skip the (full) --hf-dir
    # weight load in that case — only tokenizer + architecture are needed.
    tok, cfg, pretrained = _resolve_with_pretrained(
        args, load_weights=not getattr(args, "teacher_checkpoint", None)
    )
    # Flags override the config only where given; invalid values (e.g.
    # --temperature 0) flow into DistillConfig validation rather than being
    # silently replaced, and --no-teacher-init can only turn the init OFF.
    d = cfg.distill
    cfg = dataclasses.replace(
        cfg,
        distill=dataclasses.replace(
            d,
            temperature=d.temperature if args.temperature is None else args.temperature,
            alpha=d.alpha if args.alpha is None else args.alpha,
            init_from_teacher=d.init_from_teacher and not args.no_teacher_init,
        ),
    )
    client = _load_clients(args, cfg, tok, 1)[0]

    from ..utils.profiling import trace

    student_cfg = (
        cfg.model
        if getattr(args, "student_layers", None) is None
        else cfg.model.replace(n_layers=args.student_layers)
    )
    teacher_layers = (
        2 * student_cfg.n_layers
        if args.teacher_layers is None
        else args.teacher_layers
    )
    # ModelConfig validates n_layers >= 1; enforce deeper-than-student here so
    # a degenerate teacher fails before the training budget is spent.
    if teacher_layers < student_cfg.n_layers:
        raise SystemExit(
            f"--teacher-layers {teacher_layers} is shallower than the "
            f"{student_cfg.n_layers}-layer student"
        )
    teacher_cfg = cfg.model.replace(n_layers=teacher_layers)

    def _check_teacher(tc):
        if tc.n_layers < student_cfg.n_layers:
            raise SystemExit(
                f"teacher has {tc.n_layers} layers — shallower than the "
                f"{student_cfg.n_layers}-layer student"
            )
        if (tc.dim, tc.n_heads, tc.hidden_dim) != (
            student_cfg.dim, student_cfg.n_heads, student_cfg.hidden_dim,
        ):
            raise SystemExit(
                f"teacher width (dim {tc.dim}, heads {tc.n_heads}, ffn "
                f"{tc.hidden_dim}) != student (dim {student_cfg.dim}, heads "
                f"{student_cfg.n_heads}, ffn {student_cfg.hidden_dim}): "
                "depth-only distillation"
            )

    with trace(getattr(args, "profile_dir", None)):
        if getattr(args, "teacher_checkpoint", None):
            # Distill a model trained elsewhere — e.g. the aggregate of a
            # federated BERT-base fleet — into a small deployable student:
            # the end-to-end "distilled LLMs in distributed networks" story.
            teacher_cfg_hint = teacher_cfg
            t_trainer = Trainer(teacher_cfg_hint, cfg.train, pad_id=tok.pad_id)
            teacher_cfg, teacher_params = _restore_predict_params(
                cfg, tok, t_trainer, ckpt_dir=args.teacher_checkpoint
            )
            _check_teacher(teacher_cfg)
            if teacher_cfg != teacher_cfg_hint:
                t_trainer = Trainer(teacher_cfg, cfg.train, pad_id=tok.pad_id)
            log.info(
                f"[DISTILL] teacher from {args.teacher_checkpoint} "
                f"({teacher_cfg.n_layers} layers)"
            )
        elif getattr(args, "pth", None):
            # The migrated reference model IS the (already-trained) teacher.
            teacher_cfg, teacher_params = cfg.model, pretrained
            _check_teacher(teacher_cfg)
            t_trainer = Trainer(teacher_cfg, cfg.train, pad_id=tok.pad_id)
            log.info(
                f"[DISTILL] teacher from reference .pth {args.pth} "
                f"({teacher_cfg.n_layers} layers)"
            )
        else:
            t_trainer = Trainer(teacher_cfg, cfg.train, pad_id=tok.pad_id)
            # A bare --hf-dir encoder warm-starts the fresh teacher when the
            # depths line up (the reference's own pretrained-start pattern).
            warm = pretrained if teacher_cfg == cfg.model else None
            if pretrained is not None and warm is None:
                log.info(
                    f"[DISTILL] --hf-dir encoder ({cfg.model.n_layers} "
                    f"layers) cannot warm-start the {teacher_cfg.n_layers}-"
                    f"layer teacher; pass --teacher-layers "
                    f"{cfg.model.n_layers} to use it"
                )
            t_state = t_trainer.init_state(params=warm)
            with phase(
                f"teacher training ({teacher_cfg.n_layers} layers)", tag="DISTILL"
            ):
                t_state, _ = t_trainer.fit(
                    t_state, client.train, batch_size=cfg.data.batch_size,
                    tag="[TEACHER] ",
                )
            teacher_params = t_state.params
        teacher_metrics = t_trainer.evaluate(teacher_params, client.test)

        d_trainer = DistillTrainer(
            student_cfg, teacher_cfg, cfg.train, cfg.distill, pad_id=tok.pad_id
        )
        s_state = d_trainer.init_student_state(teacher_params)
        with phase(
            f"distilling into {student_cfg.n_layers}-layer student", tag="DISTILL"
        ):
            s_state, _ = d_trainer.distill(
                s_state,
                teacher_params,
                client.train,
                batch_size=cfg.data.batch_size,
                epochs=args.distill_epochs,
                tag="[STUDENT] ",
            )
        student_metrics = d_trainer.evaluate(s_state.params, client.test)

    log.info(
        f"[DISTILL] teacher acc {teacher_metrics['Accuracy']:.4f} -> "
        f"student acc {student_metrics['Accuracy']:.4f} "
        f"({teacher_cfg.n_layers} -> {student_cfg.n_layers} layers)"
    )
    os.makedirs(cfg.output_dir, exist_ok=True)
    reporting.save_metrics(
        teacher_metrics, os.path.join(cfg.output_dir, "teacher_metrics.csv")
    )
    reporting.save_metrics(
        student_metrics, os.path.join(cfg.output_dir, "student_metrics.csv")
    )
    reporting.plot_metrics_comparison(
        teacher_metrics,
        student_metrics,
        "Teacher vs Distilled Student (test)",
        os.path.join(cfg.output_dir, "distillation_comparison.png"),
        labels=("Teacher", "Student"),
    )
    if cfg.checkpoint_dir:
        from ..train.checkpoint import Checkpointer

        with Checkpointer(cfg.checkpoint_dir) as ckpt:
            # Provenance records the STUDENT architecture (what the saved
            # params actually are), not the resolved teacher-sized model.
            student_experiment = dataclasses.replace(cfg, model=student_cfg)
            ckpt.save(
                int(s_state.step),
                s_state,
                meta={
                    "distilled": True,
                    "kind": "local",
                    "config": student_experiment.to_dict(),
                },
            )
            ckpt.wait()
    return 0
