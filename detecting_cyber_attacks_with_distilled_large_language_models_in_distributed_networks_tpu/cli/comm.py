"""fedtpu serve / client / relay — the TCP demo-parity mode (the
reference's socket deployment shape, server.py + client1.py end-to-end,
plus the hierarchical fold tree's intermediate aggregator)."""

from __future__ import annotations

import os

import numpy as np

from ..utils.logging import get_logger, phase
from .common import (
    _load_clients,
    _obs_setup,
    _resolve_with_pretrained,
    _write_reports,
)

log = get_logger()


def _step_attrs(trainer) -> dict:
    """The trainer's sampled step-profile attrs for the client-local
    span (obs/profile.py StepProfiler.span_attrs); {} when profiling is
    off or the trainer shape has no profiler."""
    fn = getattr(trainer, "step_profile_attrs", None)
    return fn() if fn is not None else {}


def _auth_key() -> bytes | None:
    """Shared-secret HMAC key for the TCP demo-parity mode, from the
    FEDTPU_SECRET env var (never argv — process listings leak flags). The
    reference's protocol accepts weights from anyone who can connect
    (server.py:57-65); with a secret set, unauthenticated or tampered
    messages are rejected."""
    secret = os.environ.get("FEDTPU_SECRET")
    return secret.encode() if secret else None


def _client_identity_key() -> bytes | None:
    """This client's OWN DH-identity secret (FEDTPU_CLIENT_SECRET): with
    it set, the secure-agg key exchange binds the hello to this id —
    no other group member can impersonate it (comm/secure.py)."""
    secret = os.environ.get("FEDTPU_CLIENT_SECRET")
    return secret.encode() if secret else None


def _server_client_keys() -> dict[int, bytes] | None:
    """Server-side registry of per-client identity secrets
    (FEDTPU_CLIENT_SECRETS='0:alpha,1:bravo'): ids not listed are
    refused in the secure key exchange."""
    raw = os.environ.get("FEDTPU_CLIENT_SECRETS")
    if not raw:
        return None
    keys: dict[int, bytes] = {}
    for entry in raw.split(","):
        cid, _, secret = entry.partition(":")
        try:
            keys[int(cid.strip())] = secret.encode()
        except ValueError:
            raise SystemExit(
                f"malformed FEDTPU_CLIENT_SECRETS entry {entry!r} "
                "(want 'id:secret,id:secret,...')"
            ) from None
        if not secret:
            raise SystemExit(
                f"empty secret for client {cid} in FEDTPU_CLIENT_SECRETS"
            )
    return keys


# Secure aggregation needs no provisioned secret anymore: per-pair mask
# keys come from fresh ephemeral Diffie-Hellman exchanges each round
# (comm/secure.py), relayed through the server. The old FEDTPU_MASK_SECRET
# single shared secret (any one client could unmask every pair) is gone.


def cmd_serve(args) -> int:
    from ..comm import AggregationServer

    dp_clip = float(getattr(args, "dp_clip", 0.0) or 0.0)
    dp_noise = float(getattr(args, "dp_noise_multiplier", 0.0) or 0.0)
    _dp_q_arg = getattr(args, "dp_participation", None)
    # No `or 1.0` coercion: an explicit 0 must be rejected, not silently
    # become full participation. Validate before the banner math — the
    # accountant would otherwise crash first with an internal-parameter
    # traceback.
    dp_q = 1.0 if _dp_q_arg is None else float(_dp_q_arg)
    if not 0.0 < dp_q <= 1.0:
        raise SystemExit(f"--dp-participation {dp_q} must be in (0, 1]")
    rounds = args.rounds or 1
    if dp_clip > 0.0 and dp_noise > 0.0:
        # Same dual-adjacency accountant banner as the mesh tier
        # (cli/federated.py). With --dp-participation q < 1 the server
        # runs the Poisson cohort sampler — exactly the sampler the
        # subsampled-Gaussian accountant assumes, so the reported epsilon
        # is exact WITH privacy amplification; at q = 1 the bound is the
        # plain Gaussian-mechanism RDP composition, also exact.
        from ..parallel.dp import dp_epsilon_both

        eps_zeroed, eps_replace = dp_epsilon_both(
            rounds, dp_noise, 1e-5, sampling_rate=dp_q
        )
        sampling_note = (
            "full participation, accountant exact"
            if dp_q >= 1.0
            else (
                f"Poisson cohort sampling q={dp_q:.3g} (accountant "
                "exact; sampled sets are kept out of replies — "
                "amplification assumes a hidden cohort)"
            )
        )
        secure_note = ""
        if bool(getattr(args, "secure_agg", False)):
            # Masked uploads are uniform ring elements: the server CANNOT
            # re-clip them, so the sensitivity bound (and with it the
            # epsilon above) holds only if every client applies its own
            # clip — standard for secure-agg DP, but it must be said.
            secure_note = (
                ". Secure-agg caveat: clipping is HONEST-CLIENT-ONLY "
                "(masked uploads cannot be re-clipped server-side); one "
                "dishonest client can widen the mechanism's sensitivity"
            )
        log.info(
            f"[DP] client-level guarantee for {rounds} round(s): "
            f"({eps_zeroed:.3g}, 1e-05)-DP under zeroed-contribution "
            f"adjacency; ({eps_replace:.3g}, 1e-05)-DP under replace-one "
            f"adjacency (clip {dp_clip}, noise x{dp_noise}; "
            f"{sampling_note}). Noise caveat: float32 Gaussian draws "
            "(OS-entropy Philox) — not hardened against the Mironov "
            "floating-point precision attack (no discrete Gaussian)"
            f"{secure_note}"
        )
    elif dp_clip > 0.0:
        log.warning(
            "[DP] --dp-clip without --dp-noise-multiplier clips uploads "
            "but adds NO noise: no (epsilon, delta) guarantee"
        )
    tracer, _metrics = _obs_setup(
        args, proc="server", metrics_host=args.host
    )
    from ..comm import wire as _wire

    stream_chunk_bytes = _wire.stream_chunk_bytes_from_mb(
        getattr(args, "stream_chunk_mb", None)
    )
    with AggregationServer(
        host=args.host,
        port=args.port,
        num_clients=args.num_clients,
        weighted=args.weighted,
        min_clients=args.min_clients,
        timeout=args.timeout,
        compression=args.compression,
        auth_key=_auth_key(),
        secure_agg=bool(getattr(args, "secure_agg", False)),
        dp_clip=dp_clip,
        dp_noise_multiplier=dp_noise,
        client_keys=_server_client_keys(),
        secure_protocol=getattr(args, "secure_protocol", "double"),
        secure_threshold=getattr(args, "secure_threshold", None),
        dp_participation=dp_q,
        dp_history_path=getattr(args, "dp_history_file", None),
        tracer=tracer,
        stream_chunk_bytes=stream_chunk_bytes,
        strategy=getattr(args, "strategy", None),
        strategy_state_path=getattr(args, "strategy_state_file", None),
        reply_dtype=getattr(args, "reply_dtype", "fp32"),
    ) as server:
        log.info(f"[SERVER] listening on {args.host}:{server.port}")
        server.serve(rounds=rounds)
    return 0


def cmd_relay(args) -> int:
    """``fedtpu relay`` — one intermediate aggregator of the hierarchical
    fold tree (comm/relay.py): terminate ``--num-clients`` subtree client
    connections, fold their (streamed or dense) uploads into a partial
    weighted mean as chunks land, forward ONE streamed upload per round
    to ``--parent-host:--parent-port``, and fan the root's aggregate back
    out to the subtree. Clients point at the relay exactly as they would
    at a root server; run the ROOT ``fedtpu serve`` with ``--weighted``
    so subtree means recombine by their sample mass."""
    from ..comm import wire as _wire
    from ..comm.relay import RelayAggregator

    tracer, _metrics = _obs_setup(
        args, proc=f"relay-{args.relay_id}", metrics_host=args.host
    )
    stream_chunk_bytes = _wire.stream_chunk_bytes_from_mb(
        getattr(args, "stream_chunk_mb", None)
    )
    with RelayAggregator(
        args.host,
        args.port,
        parent_host=args.parent_host,
        parent_port=args.parent_port,
        relay_id=args.relay_id,
        num_clients=args.num_clients,
        min_clients=args.min_clients,
        timeout=args.timeout,
        compression=args.compression,
        auth_key=_auth_key(),
        stream_chunk_bytes=stream_chunk_bytes,
        stream=bool(getattr(args, "stream_upload", True)),
        subtree_deadline_factor=getattr(
            args, "subtree_deadline_factor", 0.5
        ),
        tracer=tracer,
        strategy=getattr(args, "strategy", "fedavg") or "fedavg",
        upward_topk=getattr(args, "upward_topk", None),
    ) as relay:
        log.info(
            f"[RELAY {args.relay_id}] listening on {args.host}:{relay.port}"
            f" -> parent {args.parent_host}:{args.parent_port}"
        )
        relay.serve(rounds=args.rounds or 1)
    return 0


def cmd_client(args) -> int:
    """The reference client1.py end-to-end: (warm start ->) train -> eval ->
    exchange over TCP -> load aggregate -> re-eval -> CSVs + plots; degrades
    to local-only reports when the exchange fails (client1.py:405-410).

    ``--checkpoint-dir`` is the reference's ``client{N}_model.pth`` pattern
    (save after local training and after applying the aggregate, auto-load
    on the next launch, client1.py:375-377,388,403 — its only multi-round
    mechanism), upgraded to full Orbax state. ``--rounds R`` runs the
    re-launch loop in-process instead (the server must be serving at least
    as many rounds).

    ``--data-parallel N`` / ``--seq-parallel M`` train the LOCAL phase over
    this host's own device mesh (train/client_mesh.py): batch rows shard
    over N chips (threefry-identical trajectory to the single-device
    client), sequences ring over M. The wire exchange is untouched —
    params gather to host as one replica, the aggregate scatters back onto
    the mesh — so --secure-agg and --dp compose unchanged."""
    from ..comm import FederatedClient, SecureAggError
    from ..train.client_mesh import make_client_trainer

    tok, cfg, pretrained = _resolve_with_pretrained(args)
    client_data = _load_clients(args, cfg, tok, cfg.fed.num_clients)[args.client_id]
    try:
        trainer = make_client_trainer(cfg, pad_id=tok.pad_id)
    except ValueError as e:
        # Operator error (axes vs local devices / batch / max_len), not a
        # traceback: --data-parallel 4 on a 2-chip host etc.
        raise SystemExit(str(e)) from None
    if cfg.mesh.data > 1 or cfg.mesh.seq > 1:
        log.info(
            f"[CLIENT {args.client_id}] local mesh: data={cfg.mesh.data}"
            + (f" x seq={cfg.mesh.seq}" if cfg.mesh.seq > 1 else "")
            + f" over {cfg.mesh.data * cfg.mesh.seq} local device(s)"
            + (
                " — FSDP shard-at-rest (params+opt ~1/N per chip, "
                "gather-at-use)"
                if cfg.mesh.fsdp
                else ""
            )
        )
    state = trainer.init_state(params=pretrained)
    ckpt = None
    if cfg.checkpoint_dir:
        from ..train.checkpoint import Checkpointer, maybe_warm_start

        restored, step = maybe_warm_start(cfg.checkpoint_dir, state)
        if restored is not None:
            state = restored
            log.info(
                f"[CLIENT {args.client_id}] warm start from "
                f"{cfg.checkpoint_dir} (step {step})"
            )
        from ..obs.profile import note_memory

        # Device-memory watermark at the restore boundary
        # (obs/profile.py; graceful no-op on stats-less backends).
        note_memory("post-restore")
        ckpt = Checkpointer(cfg.checkpoint_dir)

    import jax

    client_tracer, _metrics = _obs_setup(
        args, proc=f"client-{args.client_id}", cfg=cfg, install_global=False
    )
    # Persona mode (faults/personas.py): client-side misbehavior (lazy
    # epochs, stale round skips) plus a deterministic in-process fault
    # proxy for the wire-side personas — the client dials the proxy, the
    # proxy dials the REAL server and injects the persona's seeded
    # faults (--fault-seed). One caveat, stated where it bites: behind
    # the proxy the dial-probe succeeds even while the server is down,
    # so start the server first.
    persona = proxy = None
    server_host, server_port = args.host, args.port
    # Ranked parent list (--parent HOST:PORT, repeatable): the first
    # entry is the primary — it overrides --host/--port — and the rest
    # are the fallbacks the client re-homes through when the primary's
    # dial budget runs out or its connection dies mid-exchange.
    fallback_parents = None
    parent_args = getattr(args, "parent", None)
    if parent_args:
        parsed = []
        for entry in parent_args:
            host_s, sep, port_s = str(entry).rpartition(":")
            if not sep or not host_s or not port_s.isdigit():
                raise SystemExit(
                    f"malformed --parent {entry!r} (want HOST:PORT)"
                )
            parsed.append((host_s, int(port_s)))
        server_host, server_port = parsed[0]
        fallback_parents = parsed[1:] or None
    if getattr(args, "persona", None):
        from ..faults.personas import get_persona, start_persona_proxy

        persona = get_persona(args.persona)
        # The proxy fronts the PRIMARY parent only; fallback parents are
        # dialed directly (a re-home is already the failure path).
        proxy = start_persona_proxy(
            persona, server_host, server_port,
            fault_seed=getattr(args, "fault_seed", 0) or 0,
            client_id=args.client_id,
        )
        if proxy is not None:
            server_host, server_port = proxy.host, proxy.port
        log.info(
            f"[CLIENT {args.client_id}] persona '{persona.name}' "
            f"(fault seed {getattr(args, 'fault_seed', 0) or 0})"
            + (
                f": wire faults via proxy {proxy.host}:{proxy.port}"
                if proxy is not None
                else ": client-side behavior only"
            )
        )
    fed = FederatedClient(
        server_host, server_port, client_id=args.client_id,
        timeout=args.timeout, compression=args.compression,
        auth_key=_auth_key(),
        secure_agg=bool(getattr(args, "secure_agg", False)),
        num_clients=cfg.fed.num_clients,
        dp=bool(getattr(args, "dp", False)),
        client_key=_client_identity_key(),
        min_participants=getattr(args, "min_participants", None),
        secure_protocol=getattr(args, "secure_protocol", "double"),
        secure_threshold=getattr(args, "secure_threshold", None),
        tracer=client_tracer,
        stream=bool(getattr(args, "stream_upload", True)),
        fallback_parents=fallback_parents,
        # No `or 8.0` coercion: an explicit invalid value (e.g. 0) must
        # surface FederatedClient's validation error, not silently
        # become the default.
        rehome_dial_budget=getattr(args, "rehome_dial_budget", 8.0),
        wire_dtype=getattr(args, "wire_dtype", "fp32") or "fp32",
    )
    sink = getattr(trainer, "reply_leaf_sink", None)
    if sink is not None:
        # Meshed client (train/client_mesh.py): streamed-reply leaves
        # scatter onto the local device mesh as their chunks land, so
        # adopt_aggregate never waits for a full host-side tree.
        fed.reply_leaf_sink = sink
    rounds = max(1, getattr(args, "rounds", None) or 1)
    local = agg_metrics = None
    E = cfg.train.epochs_per_round
    # Orbax step ids must be unique and increasing, and a duplicate save is
    # SILENTLY skipped — two saves per round (post-train, post-aggregate)
    # need their own sequence, seeded past the previous run's ids on warm
    # start (state.step alone can lag them).
    save_seq = int(state.step)
    if ckpt is not None:
        save_seq = max(save_seq, ckpt.latest_step() or 0)
    for r in range(rounds):
        if persona is not None and persona.skips_round(r):
            # Stale persona: offline for this round — no training, no
            # exchange; the next exchanged round adopts the fleet's
            # aggregate (in DP mode, through the server's resync path).
            # WAIT the round window out before continuing: without the
            # sleep, a fast next-round training would upload while the
            # server is still inside the skipped round's deadline and
            # be aggregated into the very round this persona is
            # supposed to miss. --timeout is the client-side bound on
            # that window (the server's deadline is its own --timeout;
            # run both ends with matching values, the documented
            # contract).
            log.info(
                f"[CLIENT {args.client_id}] persona "
                f"'{persona.name}': sitting out round {r + 1}/{rounds}"
                + (
                    f" (offline for {args.timeout:.0f}s — the round "
                    "window)"
                    if r + 1 < rounds
                    else ""
                )
            )
            if r + 1 < rounds:
                import time as _time

                _time.sleep(args.timeout)
            continue
        # Central DP: the round base is what THIS round's training starts
        # from — the shared init in round 1 (every client must launch from
        # the same weights; the server enforces crc equality), the adopted
        # aggregate afterwards. host_params gathers the trainer's wire
        # form (one replica of a meshed state); np.array(copy=True), NOT
        # the gathered view: the jitted train step donates its input
        # buffers, and a zero-copy view would silently alias the
        # POST-training params (zero delta).
        round_base = (
            jax.tree.map(
                lambda x: np.array(x, copy=True), trainer.host_params(state)
            )
            if fed.dp
            else None
        )
        import time as _time

        t_local = _time.time()
        with phase(
            f"client {args.client_id} round {r + 1}/{rounds} training",
            tag="TRAIN",
        ) as tinfo:
            state, _ = trainer.fit(
                state, client_data.train, batch_size=cfg.data.batch_size,
                # Lazy persona: a fraction of the configured epochs
                # (floored at 1) — the under-resourced client.
                epochs=(
                    persona.scaled(E) if persona is not None else None
                ),
                epoch_offset=r * E, tag=f"[CLIENT {args.client_id}] ",
            )
        # Buffered until the exchange reveals the round's trace id —
        # the span then lands with the server's (trace, round) identity.
        # Step-profile attrs (obs/profile.py, --profile-stride) ride the
        # span so the timeline can render this client's device-vs-host
        # split; {} when profiling is off.
        fed.note_local_phase(
            t_local,
            tinfo["seconds"],
            client=args.client_id,
            **_step_attrs(trainer),
        )
        local = trainer.evaluate_state(state, client_data.test)
        if ckpt is not None:
            # Post-train save — the reference's client1.py:388.
            save_seq += 1
            ckpt.save(
                save_seq,
                state,
                meta={
                    "client_id": args.client_id,
                    "kind": "local",
                    "config": cfg.to_dict(),
                },
            )
        host_params = trainer.host_params(state)
        # Hide reply latency behind next-round input-pipeline work: the
        # next round's first batch gathers (permutation + row copies) run
        # on a background thread WHILE the exchange below blocks on the
        # aggregate reply. Same iterator, same seed — the batch sequence
        # is identical prefetched or not (pinned by tests).
        prefetch = (
            trainer.prefetch_epoch(
                client_data.train, (r + 1) * E, cfg.data.batch_size
            )
            if r + 1 < rounds
            else None
        )
        try:
            with phase("federated exchange", tag="COMM"):
                aggregated = fed.exchange(
                    host_params,
                    n_samples=len(client_data.train),
                    round_base=round_base,
                )
            if prefetch is not None and prefetch.ready():
                # The input-pipeline seconds that ran under the reply
                # wait — buffered like client-local, stamped with the
                # round's (trace, round) identity on the NEXT exchange.
                fed.note_phase(
                    "batch-prefetch",
                    prefetch.t_unix,
                    prefetch.busy_s,
                    client=args.client_id,
                    batches=prefetch.n_prefetched,
                )
            with phase("aggregated evaluation", tag="EVAL"):
                agg_metrics = trainer.evaluate(aggregated, client_data.test)
            log.info(
                f"[CLIENT {args.client_id}] round {r + 1}: local acc "
                f"{local['Accuracy']:.4f} -> aggregated acc "
                f"{agg_metrics['Accuracy']:.4f}"
            )
            if getattr(args, "metrics_jsonl", None):
                from ..reporting import append_metrics_jsonl

                for phase_name, m in (("local", local), ("aggregated", agg_metrics)):
                    append_metrics_jsonl(
                        args.metrics_jsonl,
                        {
                            "round": r + 1,
                            "client": args.client_id,
                            "phase": phase_name,
                            **m,
                        },
                    )
            # Continue the next round FROM the aggregate with a fresh Adam
            # (every reference re-launch constructs a new optimizer,
            # client1.py:380) but a continuing step counter (LR warmup);
            # a meshed trainer scatters the aggregate onto its device mesh
            # here, with no intermediate full-replica state.
            state = trainer.adopt_aggregate(state, aggregated)
            from ..obs.profile import note_memory as _note_memory

            # Adopt-aggregate boundary watermark: the meshed/FSDP
            # trainers materialize fresh (sharded) Adam moments HERE —
            # the stamp the FSDP memory story is proven on (PR-12
            # residual: this boundary was unstamped).
            _note_memory("post-aggregate")
            if ckpt is not None:
                # Post-aggregate save — the reference's client1.py:403.
                save_seq += 1
                ckpt.save(
                    save_seq,
                    state,
                    meta={
                        "client_id": args.client_id,
                        "kind": "local",
                        "config": cfg.to_dict(),
                        "aggregated": True,
                    },
                )
            # End-of-round watermark, AFTER the checkpoint enqueue — a
            # distinct reading from post-aggregate (which brackets the
            # adoption spike the moment the fresh moments land).
            _note_memory("post-round")
        except (ConnectionError, OSError, SecureAggError) as e:
            agg_metrics = None
            log.info(
                f"[CLIENT {args.client_id}] round {r + 1} exchange failed "
                f"({e}); local-only reports"
            )
            break
    if proxy is not None:
        proxy.close()
    if ckpt is not None:
        ckpt.wait()
        ckpt.close()
    _write_reports(args.client_id, local, agg_metrics, cfg.output_dir)
    return 0
