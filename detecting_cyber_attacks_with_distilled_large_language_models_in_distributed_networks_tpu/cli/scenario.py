"""fedtpu scenario — the "federated in the wild" matrix runner.

Sweeps a persona x partition matrix of LIVE loopback federated rounds
(faults/scenario.py): each cell is a real ``AggregationServer`` plus
client threads, with the cell's persona driving wire faults through the
deterministic fault proxy. Prints the comparison grid, writes
``grid.txt`` + ``scenario.jsonl`` (one record per cell, obs-timeline
outcomes inlined) under ``--out-dir``, and exits nonzero when the
robustness contract breaks — any quorum-satisfiable cell's round
failing, or any aggregate not bit-exact with the clean survivor mean.
"""

from __future__ import annotations

import json

from ..utils.logging import get_logger

log = get_logger()


def cmd_scenario(args) -> int:
    from ..faults.personas import get_persona
    from ..faults.scenario import (
        PARTITION_LABELS,
        ScenarioConfig,
        cell_record,
        contract_violations,
        run_matrix,
    )

    personas = tuple(
        p.strip() for p in args.personas.split(",") if p.strip()
    )
    for p in personas:
        get_persona(p)  # argparse-time validation, operator message
    partitions = tuple(
        p.strip() for p in args.partitions.split(",") if p.strip()
    )
    for p in partitions:
        if p not in PARTITION_LABELS:
            raise SystemExit(
                f"unknown partition {p!r} "
                f"(one of {', '.join(PARTITION_LABELS)})"
            )
    if not personas or not partitions:
        raise SystemExit("need at least one persona and one partition")
    strategies: tuple[str, ...] = ()
    if getattr(args, "strategies", None):
        from ..strategies import parse_strategy

        # ';' separates specs (a spec's own params use ',':
        # fedopt:opt=adam,lr=0.1); plain ',' still works for bare names.
        raw = args.strategies
        sep = ";" if ";" in raw or ":" in raw else ","
        strategies = tuple(
            s.strip() for s in raw.split(sep) if s.strip()
        )
        for s in strategies:
            try:
                parse_strategy(s)  # operator message, not a traceback
            except ValueError as e:
                raise SystemExit(str(e)) from None
    cfg = ScenarioConfig(
        num_clients=args.clients,
        rounds=args.rounds,
        personas=personas,
        partitions=partitions,
        dirichlet_alpha=args.dirichlet_alpha,
        seed=args.fault_seed,
        payload_kb=args.payload_kb,
        deadline_s=args.deadline,
        stream_chunk_bytes=0 if args.no_stream else (1 << 15),
        auth_cell=not args.no_auth_cell,
        dead_relay_cell=not getattr(args, "no_dead_relay_cell", False),
        train=args.train,
        strategies=strategies,
    )
    results, grid = run_matrix(cfg, args.out_dir)
    if args.json:
        for res in results:
            print(json.dumps(cell_record(res)))
    else:
        print(grid)
    violations = contract_violations(results)
    if violations:
        for v in violations:
            log.error(f"[SCENARIO] contract violation: {v}")
        return 1
    log.info(
        f"[SCENARIO] {len(results)} cells x {cfg.rounds} rounds: every "
        "quorum-satisfiable round succeeded over survivors, all "
        "aggregates crc-pinned bit-exact to the clean survivor mean "
        f"(outputs under {args.out_dir})"
    )
    return 0
