"""fedtpu predict / export-hf — checkpoint restore for inference, batch
prediction, and export to the HF DistilBERT layout."""

from __future__ import annotations

import json
import os

import numpy as np

from ..config import ExperimentConfig
from ..utils.logging import get_logger, phase
from .common import _resolve_with_pretrained

log = get_logger()


def _restore_predict_params(
    cfg, tok, trainer, *, ckpt_dir=None, step=None, mesh=None
):
    """Trained weights for inference from a checkpoint directory
    (``cfg.checkpoint_dir`` unless ``ckpt_dir`` overrides — distill's
    teacher restore points elsewhere; ``step`` pins a specific saved step
    — serving's hot reload needs params and round metadata read from ONE
    snapshot, not whatever became latest between two reads; ``mesh`` is
    the sharded-serving restore target: local-checkpoint leaves scatter
    STRAIGHT onto their FSDP shards via the orbax sharding-aware template
    — the full-size tree never materializes on one chip — and federated
    replica-0 params are placed onto shards right after the collapse).

    Understands both checkpoint flavors: a ``local``/``client`` TrainState
    (restored against this trainer's template, or the checkpoint's own
    recorded config when present) and a ``federated`` FedState (recognized
    by its metadata; restored on the mesh and collapsed to client 0's
    replica — post-aggregation all replicas are identical). Returns
    ``(model_cfg, params)``; raises instead of silently predicting from
    random weights."""
    from ..train.checkpoint import Checkpointer

    ckpt_dir = cfg.checkpoint_dir if ckpt_dir is None else ckpt_dir
    if not os.path.isdir(ckpt_dir):
        # Read-only path: don't let the manager create a directory at a
        # mistyped location (it would later masquerade as a real run dir).
        raise SystemExit(f"checkpoint dir {ckpt_dir} does not exist")
    with Checkpointer(ckpt_dir) as ckpt:
        if step is None:
            step = ckpt.latest_step()
        if step is None:
            raise SystemExit(f"no checkpoint found in {ckpt_dir}")
        meta = ckpt.restore_meta(step=step)
        import jax

        # "kind" discriminates local TrainState vs federated FedState
        # checkpoints; older federated checkpoints predate it but always
        # carried "round".
        is_fed = (
            meta.get("kind") == "federated" if "kind" in meta else "round" in meta
        )
        if is_fed:
            from ..train.federated import FederatedTrainer

            fed_cfg = ExperimentConfig.from_checkpoint_dict(meta["config"])
            if fed_cfg.model.vocab_size != cfg.model.vocab_size:
                raise SystemExit(
                    f"checkpoint model vocab ({fed_cfg.model.vocab_size}) != "
                    f"tokenizer vocab ({cfg.model.vocab_size}); pass the "
                    "matching --hf-dir / vocab"
                )
            ftr = FederatedTrainer(fed_cfg, pad_id=tok.pad_id)
            # Abstract template + params-only restore: never materializes
            # the C-stacked Adam moments (3x C model copies for a fleet
            # checkpoint); only the [C, ...] params land, and replica 0 is
            # the global model (FedAvg replicates its output).
            template = jax.eval_shape(lambda: ftr.init_state(seed=0))
            stacked = ckpt.restore_params(template, step=step)
            params = jax.tree.map(lambda x: np.asarray(x)[0], stacked)
            if mesh is not None:
                from ..parallel.mesh import fsdp_tree_shardings

                params = jax.device_put(
                    params, fsdp_tree_shardings(params, mesh)
                )
            log.info(
                f"[PREDICT] restored federated checkpoint (round "
                f"{meta.get('round', '?')}, {fed_cfg.fed.num_clients} clients)"
            )
            return fed_cfg.model, params
        # Without recorded config (legacy checkpoints) the caller's trainer
        # IS the architecture claim — return ITS config, not cfg.model
        # (distill passes a deeper-than-student teacher template here).
        model_cfg = trainer.model_cfg
        if "config" in meta:
            # Trust the checkpoint's recorded config over CLI presets —
            # e.g. its gelu variant does not change parameter shapes, so a
            # mismatched preset would restore fine and then run (or
            # export) the wrong activation.
            from ..train.engine import Trainer

            ckpt_cfg = ExperimentConfig.from_checkpoint_dict(meta["config"])
            if ckpt_cfg.model.vocab_size != cfg.model.vocab_size:
                raise SystemExit(
                    f"checkpoint model vocab ({ckpt_cfg.model.vocab_size}) "
                    f"!= tokenizer vocab ({cfg.model.vocab_size}); pass the "
                    "matching --hf-dir / vocab"
                )
            model_cfg = ckpt_cfg.model
            if model_cfg != trainer.model_cfg:
                trainer = Trainer(model_cfg, cfg.train, pad_id=tok.pad_id)
        template = jax.eval_shape(lambda: trainer.init_state(seed=0))
        if mesh is not None:
            # Sharding-aware scatter-restore: the template's params leaves
            # carry their fsdp_spec NamedShardings, so orbax lands each
            # leaf directly on its shards (checkpoint.py _abstract passes
            # template shardings through) — no full-size host/device copy.
            from ..parallel.mesh import shard_template

            template = template._replace(
                params=shard_template(template.params, mesh)
            )
        try:
            params = ckpt.restore_params(template, step=step)
        except Exception as e:
            raise SystemExit(
                f"checkpoint at {ckpt_dir} (step {step}) does not "
                f"match the resolved model ({type(e).__name__}: {e}) — pass "
                "the --preset/--config/--hf-dir the checkpoint was trained "
                "with"
            ) from None
        log.info(f"[PREDICT] restored local checkpoint (step {step})")
        return model_cfg, params


def cmd_predict(args) -> int:
    """Batch inference on new flows — the deployment step the reference
    never ships: it trains and evaluates (client1.py:379-400) but offers no
    way to RUN the detector on unlabeled traffic. Reads a flow CSV (label
    column optional), writes one row per flow: P(attack), the thresholded
    0/1 prediction, and its label name; logs metrics when labels exist."""
    import pandas as pd

    from ..data import get_dataset, load_flow_csv
    from ..data.pipeline import TokenizedSplit
    from ..train.engine import Trainer

    if not getattr(args, "csv", None):
        raise SystemExit("predict needs --csv (the flows to classify)")
    for flag in ("stream", "source", "synthetic"):
        if getattr(args, flag, None):
            raise SystemExit(
                f"--{flag} is a training-data option; predict reads the "
                "flows to classify from --csv only"
            )
    if (
        not getattr(args, "checkpoint_dir", None)
        and getattr(args, "hf_dir", None)
        and not getattr(args, "pth", None)  # .pth supplies the trained head
    ):
        # Gate BEFORE the (expensive) weight conversion: a bare encoder's
        # head would be random noise, so predicting from it is meaningless.
        from ..models.hf_convert import hf_dir_has_head

        if not hf_dir_has_head(args.hf_dir):
            raise SystemExit(
                f"--hf-dir {args.hf_dir} is a bare encoder (no classifier.* "
                "weights): its head would be random noise. Train it first "
                "(local/federated, then --checkpoint-dir), or point --hf-dir "
                "at a checkpoint fine-tuned with this head architecture"
            )
    tok, cfg, pretrained = _resolve_with_pretrained(args)
    if cfg.checkpoint_dir and getattr(args, "pth", None):
        # Checked on the RESOLVED config: checkpoint_dir may come from a
        # --config file, not just the flag.
        raise SystemExit(
            "--pth and a checkpoint_dir are both weight sources; pass one"
        )
    if not cfg.checkpoint_dir and pretrained is None:
        raise SystemExit(
            "predict needs trained weights: pass --checkpoint-dir (a local "
            "or federated training checkpoint) or --hf-dir (a fine-tuned "
            "classifier checkpoint)"
        )
    trainer = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
    if cfg.checkpoint_dir:
        model_cfg, params = _restore_predict_params(cfg, tok, trainer)
        if model_cfg != cfg.model:
            trainer = Trainer(model_cfg, cfg.train, pad_id=tok.pad_id)
    else:
        model_cfg, params = cfg.model, pretrained

    spec = get_dataset(cfg.data.dataset)
    with phase(f"loading {args.csv}", tag="DATA"):
        df = load_flow_csv(args.csv)
        texts = spec.render_texts(df)
        label_col = cfg.data.label_column if spec.label_kind == "positive" else spec.label_column
        labels = None
        if label_col in df.columns:
            from ..data.cicids import _spec_labels

            labels = _spec_labels(df, cfg.data)
    if not texts:
        raise SystemExit(f"--csv {args.csv} has no data rows")
    with phase(f"tokenize {len(texts)} flows", tag="DATA"):
        enc = tok.batch_encode(texts, max_len=model_cfg.max_len)
    split = TokenizedSplit(
        enc["input_ids"],
        enc["attention_mask"],
        (labels if labels is not None else np.zeros(len(texts))).astype(np.int32),
    )
    bs = cfg.data.eval_batch_size
    with phase(f"predict ({len(texts)} flows, bs {bs})", tag="EVAL"):
        # Trainer.evaluate is the one eval pipeline (pad/slice/accumulate);
        # its metrics are ignored here (labels may be dummies) — predict
        # only consumes the per-row P(attack) probs.
        probs = trainer.evaluate(params, split, batch_size=bs)["probs"]
    preds = (probs >= args.threshold).astype(np.int32)
    positive = (
        cfg.data.positive_label if spec.label_kind == "positive" else "attack"
    )
    out = pd.DataFrame(
        {
            "prob_attack": probs,
            "prediction": preds,
            "label_name": np.where(preds == 1, positive, "BENIGN"),
        }
    )
    out.to_csv(args.output, index=False)
    log.info(
        f"[PREDICT] wrote {len(out)} predictions to {args.output} "
        f"({int(preds.sum())} flagged {positive})"
    )
    if labels is not None:
        # Metrics at the SAME threshold the predictions used (sklearn
        # average='binary' semantics, as the reference's evaluate_model).
        y = labels.astype(np.int32)
        tp = int(((preds == 1) & (y == 1)).sum())
        fp = int(((preds == 1) & (y == 0)).sum())
        fn = int(((preds == 0) & (y == 1)).sum())
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        log.info(
            f"[PREDICT] against the CSV's labels (threshold "
            f"{args.threshold}): acc {(preds == y).mean() * 100:.4f} "
            f"prec {prec:.4f} rec {rec:.4f} f1 {f1:.4f}"
        )
    return 0


def cmd_export_hf(args) -> int:
    """Export trained weights to the HF DistilBERT checkpoint layout
    (config.json + model.safetensors + vocab.txt) — the reference's own
    artifact format (its required ``./distilbert-base-uncased`` input dir
    and its ``.pth`` state dicts use the same key space, client1.py:56,388).
    A reference user can load this with ``DistilBertModel.from_pretrained``
    or hand it back to this framework via ``--hf-dir``."""
    import jax

    from ..models.hf_convert import flax_to_hf
    from ..train.engine import Trainer

    tok, cfg, pretrained = _resolve_with_pretrained(args)
    if getattr(args, "pth", None) and cfg.checkpoint_dir:
        # Resolved config: checkpoint_dir may come from a --config file.
        raise SystemExit(
            "--pth and a checkpoint_dir are both weight sources; pass one"
        )
    if cfg.checkpoint_dir:
        trainer = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
        model_cfg, params = _restore_predict_params(cfg, tok, trainer)
    elif getattr(args, "pth", None):
        # Convert a reference-trained .pth straight to the HF layout.
        model_cfg, params = cfg.model, pretrained
    else:
        raise SystemExit(
            "export-hf needs trained weights: --checkpoint-dir, or "
            "--pth + --hf-dir (a reference-trained model)"
        )
    if model_cfg.n_classes != 2 or not isinstance(params, dict) or "encoder" not in params:
        raise SystemExit("checkpoint does not hold a classifier params tree")
    sd = flax_to_hf(jax.tree.map(np.asarray, params), model_cfg)

    out = args.out
    os.makedirs(out, exist_ok=True)
    from safetensors.numpy import save_file

    save_file(sd, os.path.join(out, "model.safetensors"))
    hf_config = {
        "architectures": ["DistilBertModel"],
        "model_type": "distilbert",
        "vocab_size": model_cfg.vocab_size,
        "dim": model_cfg.dim,
        "n_layers": model_cfg.n_layers,
        "n_heads": model_cfg.n_heads,
        "hidden_dim": model_cfg.hidden_dim,
        "max_position_embeddings": model_cfg.max_position_embeddings,
        "dropout": model_cfg.dropout,
        "attention_dropout": model_cfg.attention_dropout,
        "pad_token_id": model_cfg.pad_token_id,
        "initializer_range": model_cfg.initializer_range,
        # Declare the activation the weights were actually trained under:
        # HF's "gelu" is the erf form, "gelu_new" the tanh form.
        "activation": "gelu" if model_cfg.gelu == "exact" else "gelu_new",
        "tie_weights_": True,
    }
    with open(os.path.join(out, "config.json"), "w") as f:
        json.dump(hf_config, f, indent=2)
    tok.save_vocab(os.path.join(out, "vocab.txt"))
    log.info(
        f"[EXPORT] wrote HF checkpoint ({len(sd)} tensors, "
        f"{sum(v.nbytes for v in sd.values()) / 1e6:.1f} MB) to {out}"
    )
    return 0
