"""fedtpu controller / registry — the control plane's operator surface.

``controller`` turns the hand-run three-script round (reference
server.py + client1.py + client2.py, re-launched by a human per round)
into an unattended campaign: it owns the TCP aggregation endpoint,
serves round after round, evaluates every aggregate on a held-out
validation pool, registers it as an immutable candidate, and moves the
registry's serving pointer only through the eval gate. ``registry`` is
the manual override: list artifacts, promote one by hand, roll the
pointer back.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from ..utils.logging import get_logger, phase
from .comm import _auth_key, _server_client_keys
from .common import (
    _load_client_splits,
    _load_clients,
    _obs_setup,
    _resolve_with_pretrained,
)

log = get_logger()


def _gate_val_split(args, cfg, tok, num_clients):
    """Every client's VALIDATION rows, tokenized and pooled, as the
    held-out gate split — val, never test: the gate is model selection,
    and reusing test data to pick what serves would leak the final
    numbers. Only the val rows are tokenized (the controller never
    trains, so paying a full-corpus tokenization pass at every daemon
    start would be pure waste); the --stream reader has no split-level
    entry point, so that path tokenizes everything as before."""
    if getattr(args, "stream", False):
        vals = [
            c.val
            for c in _load_clients(args, cfg, tok, num_clients)
            if len(c.val)
        ]
    else:
        from ..data.pipeline import tokenize_split
        from ..utils.logging import phase as _phase

        splits = _load_client_splits(args, cfg, num_clients)
        with _phase("tokenize validation pools", tag="DATA"):
            vals = [
                tokenize_split(s.val, tok, cfg.model.max_len)
                for s in splits
                if len(s.val)
            ]
    if not vals:
        raise SystemExit(
            "no validation rows for the eval gate (val_fraction too small "
            "for this corpus?)"
        )
    from ..data.pipeline import TokenizedSplit

    return TokenizedSplit(
        np.concatenate([v.input_ids for v in vals]),
        np.concatenate([v.attention_mask for v in vals]),
        np.concatenate([v.labels for v in vals]),
    )


def cmd_controller(args) -> int:
    from ..comm import AggregationServer
    from ..control import Controller, DriftMonitor
    from ..registry import ModelRegistry
    from ..train.engine import Trainer

    tok, cfg, _pretrained = _resolve_with_pretrained(args, load_weights=False)
    C = cfg.fed.num_clients
    ctl = cfg.control
    ctl_kw = {}
    for flag, field_name in (
        ("gate_metric", "gate_metric"),
        ("gate_min_delta", "gate_min_delta"),
        ("interval", "min_interval_s"),
        ("max_interval", "max_interval_s"),
        ("drift_threshold", "drift_threshold"),
        ("drift_min_scores", "drift_min_scores"),
        ("drift_method", "drift_method"),
        ("round_deadline", "round_deadline_s"),
        ("max_artifacts", "max_artifacts"),
        ("slo_deadline_factor", "slo_deadline_factor"),
        ("cohort_min_frac", "cohort_min_frac"),
        ("cohort_max_frac", "cohort_max_frac"),
    ):
        v = getattr(args, flag, None)
        if v is not None:
            ctl_kw[field_name] = v
    if getattr(args, "adaptive_cadence", False):
        ctl_kw["adaptive_cadence"] = True
    if getattr(args, "drift_cohort", False):
        ctl_kw["drift_cohort"] = True
    try:
        ctl = dataclasses.replace(ctl, **ctl_kw) if ctl_kw else ctl
    except ValueError as e:
        raise SystemExit(str(e)) from None
    shw = cfg.shadow
    shw_kw = {}
    for flag, field_name in (
        ("shadow_min_pairs", "min_pairs"),
        ("shadow_timeout", "timeout_s"),
        ("shadow_max_flip_rate", "max_flip_rate"),
        ("shadow_psi_threshold", "psi_threshold"),
    ):
        v = getattr(args, flag, None)
        if v is not None:
            shw_kw[field_name] = v
    try:
        shw = dataclasses.replace(shw, **shw_kw) if shw_kw else shw
    except ValueError as e:
        raise SystemExit(str(e)) from None
    lbl = cfg.labels
    lbl_kw = {}
    for flag, field_name in (
        ("label_journal", "journal"),
        ("label_min_joined", "min_joined"),
        ("label_coverage_floor", "coverage_floor"),
        ("label_max_regression", "max_regression"),
    ):
        v = getattr(args, flag, None)
        if v is not None:
            lbl_kw[field_name] = v
    try:
        lbl = dataclasses.replace(lbl, **lbl_kw) if lbl_kw else lbl
    except ValueError as e:
        raise SystemExit(str(e)) from None

    # The gate's held-out data: the pooled per-client VAL split.
    with phase("loading the eval-gate validation pool", tag="DATA"):
        val = _gate_val_split(args, cfg, tok, C)
    trainer = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
    log.info(
        f"[CONTROLLER] eval gate: {len(val.input_ids)} pooled validation "
        f"rows, metric {ctl.gate_metric} (min delta {ctl.gate_min_delta})"
    )

    def eval_fn(params):
        return trainer.evaluate(
            params, val, batch_size=cfg.data.eval_batch_size
        )

    tracer, _metrics = _obs_setup(
        args, proc="controller", cfg=cfg, metrics_host=args.host
    )
    registry = ModelRegistry(args.registry_dir, tracer=tracer)
    state_path = args.state_jsonl or os.path.join(
        args.registry_dir, "controller_state.jsonl"
    )
    drift = None
    if getattr(args, "drift_jsonl", None):
        drift = DriftMonitor(
            args.drift_jsonl,
            threshold=ctl.drift_threshold,
            min_scores=ctl.drift_min_scores,
            method=ctl.drift_method,
        )
        log.info(
            f"[CONTROLLER] drift-triggered rounds: tailing "
            f"{args.drift_jsonl} ({ctl.drift_method} >= "
            f"{ctl.drift_threshold} over >= {ctl.drift_min_scores} scores"
            + (
                f"; clock fallback every {ctl.max_interval_s:.0f}s"
                if ctl.max_interval_s is not None
                else "; no clock fallback"
            )
            + ")"
        )
    from ..comm import wire as _wire

    stream_mb = getattr(args, "stream_chunk_mb", None)
    with AggregationServer(
        host=args.host,
        port=args.port,
        num_clients=C,
        min_clients=args.min_clients,
        timeout=args.timeout,
        auth_key=_auth_key(),
        secure_agg=bool(getattr(args, "secure_agg", False)),
        client_keys=_server_client_keys(),
        tracer=tracer,
        stream_chunk_bytes=_wire.stream_chunk_bytes_from_mb(stream_mb),
    ) as server:
        shadow_gate = None
        if getattr(args, "shadow_gate", False):
            from ..shadow import ShadowGate

            shadow_gate = ShadowGate(
                args.registry_dir,
                min_pairs=shw.min_pairs,
                max_flip_rate=shw.max_flip_rate,
                psi_threshold=shw.psi_threshold,
                timeout_s=shw.timeout_s,
                poll_s=shw.poll_s,
                tracer=tracer,
            )
            log.info(
                f"[CONTROLLER] shadow gate armed: promote after >= "
                f"{shw.min_pairs} mirrored pair(s) with flip_rate <= "
                f"{shw.max_flip_rate} and psi <= {shw.psi_threshold} "
                f"(fail closed after {shw.timeout_s:.0f}s)"
            )
        label_gate = None
        error_monitor = None
        if getattr(args, "label_gate", False):
            from ..labels import LabelGate

            label_gate = LabelGate(
                args.registry_dir,
                journal=lbl.journal,
                threshold=lbl.threshold,
                min_joined=lbl.min_joined,
                coverage_floor=lbl.coverage_floor,
                max_regression=lbl.max_regression,
                tracer=tracer,
            )
            log.info(
                f"[CONTROLLER] label gate armed: supervised rung over >= "
                f"{lbl.min_joined} joined flow(s) at coverage >= "
                f"{lbl.coverage_floor} (candidate error may exceed "
                f"serving by <= {lbl.max_regression}; fails closed)"
            )
            if getattr(args, "error_drift", False):
                from ..control import ErrorRateMonitor

                error_monitor = ErrorRateMonitor(
                    margin=lbl.error_margin,
                    min_joined=lbl.error_min_joined,
                )
                log.info(
                    f"[CONTROLLER] supervised drift armed: serving error "
                    f"rising {lbl.error_margin} past its promoted "
                    f"reference over >= {lbl.error_min_joined} joined "
                    "flow(s) triggers a round"
                )
        sentinel_link = None
        if getattr(args, "sentinel_jsonl", None):
            from ..control import SentinelLink

            sentinel_link = SentinelLink(args.sentinel_jsonl)
            log.info(
                f"[CONTROLLER] sentinel link armed: supervised-drift "
                f"verdicts appended to {args.sentinel_jsonl} trigger "
                "corrective rounds (existing verdicts skipped)"
            )
        actuator = None
        if getattr(args, "slo_alerts_jsonl", None):
            from ..control import SloActuator

            actuator = SloActuator(
                args.slo_alerts_jsonl, factor=ctl.slo_deadline_factor
            )
            log.info(
                f"[CONTROLLER] SLO actuation armed: round-duration fire "
                f"on {args.slo_alerts_jsonl} tightens the straggler "
                f"deadline x{ctl.slo_deadline_factor}"
            )
        controller = Controller(
            server,
            registry,
            eval_fn,
            control=ctl,
            state_path=state_path,
            drift_monitor=drift,
            model_config=cfg.model,
            tracer=tracer,
            shadow_gate=shadow_gate,
            slo_actuator=actuator,
            label_gate=label_gate,
            error_monitor=error_monitor,
            sentinel_link=sentinel_link,
        )
        max_rounds = args.rounds if args.rounds and args.rounds > 0 else None
        log.info(
            f"[CONTROLLER] round endpoint {args.host}:{server.port} "
            f"({C} clients, quorum {server.min_clients}); campaign: "
            + (f"{max_rounds} round(s)" if max_rounds else "until stopped")
        )
        try:
            controller.run(max_rounds=max_rounds)
        except KeyboardInterrupt:
            log.info("[CONTROLLER] interrupted; campaign state saved")
    s = controller.summary()
    log.info(f"[CONTROLLER] campaign summary: {s}")
    return 0


def cmd_registry(args) -> int:
    from ..registry import ModelRegistry, RegistryError

    registry = ModelRegistry(args.registry_dir)
    try:
        if args.action == "list":
            serving = registry.serving_info()
            serving_id = serving["artifact"] if serving else None
            rows = registry.list()
            if not rows:
                print(f"(registry {args.registry_dir} is empty)")
                return 0
            for m in rows:
                metrics = m.get("metrics") or {}
                headline = ", ".join(
                    f"{k}={v:.4f}"
                    for k, v in sorted(metrics.items())
                    if isinstance(v, float)
                )
                marker = " <- serving" if m["id"] == serving_id else ""
                print(
                    f"{m['id']}  round={m.get('round')}  "
                    f"state={m.get('state')}  {headline}{marker}"
                )
            return 0
        if args.action == "promote":
            if not args.artifact:
                raise SystemExit("registry promote needs --artifact <id>")
            m = registry.promote(args.artifact, to=args.to)
            print(f"{m['id']} -> {m['state']}")
            return 0
        if args.action == "rollback":
            m = registry.rollback()
            print(f"serving pointer -> {m['id']} (round {m.get('round')})")
            return 0
        if args.action == "gc":
            if args.max_artifacts is None:
                raise SystemExit("registry gc needs --max-artifacts N")
            removed = registry.gc(max_artifacts=args.max_artifacts)
            if removed:
                for aid in removed:
                    print(f"pruned {aid}")
            print(
                f"{len(removed)} artifact(s) pruned, "
                f"{len(registry.list())} kept"
            )
            return 0
    except RegistryError as e:
        raise SystemExit(str(e)) from None
    raise SystemExit(f"unknown registry action {args.action!r}")
