"""Command-line orchestration — the reference's three ``main()``s unified.

The reference's entry points are three scripts with hard-coded paths, ports,
seeds, and client count (reference client1.py:353-415, client2.py:332-392,
server.py:116-140); adding a client means copy-pasting a file. Here one CLI
covers every deployment shape, parameterized by client id / count:

  local       one client, train -> eval -> metrics CSV + plots
              (reference client1.py minus the sockets)
  federated   N clients on one TPU mesh: SPMD local epochs + pmean FedAvg,
              multi-round, checkpoint/resume (the TPU-native deployment)
  predict     batch inference: flow CSV -> per-row P(attack) CSV, from a
              local/federated checkpoint or a fine-tuned --hf-dir (the
              deployment step the reference never ships)
  infer-serve online inference: TCP scoring service with dynamic
              micro-batching (bucketed warm jit paths), bounded-queue
              admission control, and hot reload of new federated
              checkpoints between batches (serving/)
  distill     teacher -> student knowledge distillation (the recipe behind
              the reference's pre-distilled encoder)
  serve       TCP aggregation server (demo-parity mode, reference server.py)
  client      TCP client: train locally, exchange with a serve process,
              re-evaluate the aggregate (reference client1.py end-to-end)
  relay       intermediate aggregator of the hierarchical fold tree
              (comm/relay.py): terminate a subtree of client connections,
              fold them into a partial weighted mean as chunks land, and
              forward one streamed upload per round to the parent — how a
              round scales past one server process to 64-256-client
              cohorts (run the root serve with --weighted)
  route       serving router: load-balance the scoring protocol across N
              infer-serve replicas (router/) — least-in-flight pick,
              in-band stats health probes, eject/readmit on failure,
              HMAC auth passed through end-to-end
  fleet       local replica fleet: N infer-serve replicas behind the
              router, following the registry serving pointer with
              ROLLING hot-reload — promotions drain and swap one replica
              at a time, so the pointer move never drops traffic
  controller  control plane: unattended continuous federated rounds with
              an eval-gated model registry — round -> held-out eval ->
              candidate artifact -> promote (or reject on regression) ->
              the serving tier follows the promoted pointer; rounds fire
              on serving-score drift instead of a fixed clock (control/)
  registry    inspect/operate the model registry: list artifacts, promote
              one by hand, roll the serving pointer back (registry/)
  shadow      shadow evaluation plane: what is under live shadow
              evaluation (status) and the paired serving/shadow
              disagreement evidence behind a gate verdict (report)
  scenario    "federated in the wild": sweep a client-persona x data-
              partition matrix of live loopback rounds with wire-level
              fault injection (faults/), assert every quorum-satisfiable
              round converges bit-exactly over survivors, and emit the
              comparison grid from the obs timeline
  export-config   print the full default config as JSON (there is no config
                  file in the reference to copy from)

Config resolution: defaults <- --config JSON <- explicit flags.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Sequence

from .check import cmd_check
from .comm import cmd_client, cmd_relay, cmd_serve
from .common import resolve_config
from .control import cmd_controller, cmd_registry
from .distill import cmd_distill
from .federated import cmd_federated
from .labels import cmd_labels
from .local import cmd_local
from .obs import cmd_obs
from .predict import cmd_export_hf, cmd_predict
from .router import cmd_fleet, cmd_route
from .scenario import cmd_scenario
from .serving import cmd_infer_serve
from .shadow import cmd_shadow


def _wire_compression(spec: str) -> str:
    """argparse type for the client's --compression: validates
    none|bf16|int8|topk[:frac] (wire.parse_compression) so a typo fails at
    parse time, not mid-round."""
    from ..comm import wire

    try:
        wire.parse_compression(spec)
    except wire.WireError as e:
        raise argparse.ArgumentTypeError(str(e)) from None
    return spec


def _reply_compression(spec: str) -> str:
    """argparse type for the server's --compression: like
    :func:`_wire_compression` but rejects topk at parse time too — the
    reply is an absolute aggregate, sparse round deltas are upload-only."""
    spec = _wire_compression(spec)
    if spec.startswith("topk"):
        raise argparse.ArgumentTypeError(
            "topk is an upload-side (sparse round-delta) compression; "
            "the reply is an absolute aggregate — use none/bf16/int8"
        )
    return spec


def cmd_export_config(args) -> int:
    from ..data import default_tokenizer

    cfg = resolve_config(args, vocab_size=len(default_tokenizer().vocab))
    json.dump(cfg.to_dict(), sys.stdout, indent=2)
    sys.stdout.write("\n")
    return 0


# ------------------------------------------------------------------ parser
def _add_flight_dir(p: argparse.ArgumentParser) -> None:
    """The daemons' shared failure-flight-recorder flag (serve | relay |
    infer-serve | route | fleet | controller)."""
    p.add_argument(
        "--flight-dir",
        default=None,
        help="arm the failure flight recorder (obs/flight.py): keep a "
        "bounded in-memory ring of recent spans and dump a postmortem "
        "bundle (ring + config + /metrics snapshot) to this directory "
        "on round failure, replica eject storm, or scoring-dispatch "
        "failure; SLO pages dump from the process that evaluates them "
        "— `fedtpu obs health|watch --flight-dir`. Inspect with "
        "`fedtpu obs postmortem`",
    )


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", help="JSON config file (ExperimentConfig.to_dict shape)")
    p.add_argument(
        "--preset", default="tiny", help="tiny|distilbert|bert|bert-large"
    )
    p.add_argument(
        "--gelu",
        choices=["exact", "tanh"],
        help="FFN activation: tanh (default, ~20%% faster on TPU, within a "
        "few bf16 ulps of erf) or exact (HF's erf form, fp32 parity)",
    )
    p.add_argument(
        "--hf-dir",
        help="HF DistilBERT checkpoint dir (config.json + vocab.txt + "
        "model.safetensors|pytorch_model.bin) — the reference's required "
        "./distilbert-base-uncased; pretrained encoder + fresh head",
    )
    p.add_argument(
        "--pth",
        help="a reference-run .pth state dict (its DDoSClassifier / "
        "aggregated model) as the weights, with --hf-dir supplying "
        "tokenizer + architecture — direct migration of a model the "
        "reference trained",
    )
    p.add_argument("--csv", help="flow CSV path (schema set by --dataset)")
    p.add_argument(
        "--dataset",
        help="registered dataset schema: cicids2017|cicddos2019|unswnb15",
    )
    p.add_argument(
        "--source",
        action="append",
        metavar="[DATASET=]PATH",
        help="mixed-corpus CSV source (repeatable); dataset auto-detected "
        "from the schema when omitted",
    )
    p.add_argument("--synthetic", type=int, metavar="N", help="use N synthetic flows")
    p.add_argument(
        "--stream",
        action="store_true",
        help="two-pass chunked CSV reader (corpora larger than RAM); "
        "index-based sampling semantics",
    )
    p.add_argument("--output-dir", default=None)
    p.add_argument("--batch-size", type=int)
    p.add_argument("--epochs", type=int, help="epochs per round")
    p.add_argument("--learning-rate", type=float)
    p.add_argument(
        "--warmup-steps",
        type=int,
        help="linear LR warmup steps (global step count; 0 = constant)",
    )
    p.add_argument(
        "--attention-impl",
        choices=["dot", "flash", "ring"],
        help="attention path: dot (XLA fused, default), flash (Pallas "
        "kernel — the long-context choice, O(L·D) memory both directions), "
        "ring (sequence-parallel over a mesh axis; needs "
        "--attention-dropout 0)",
    )
    p.add_argument(
        "--attention-dropout",
        type=float,
        help="attention-weight dropout rate (default from the preset/"
        "config; ring requires 0)",
    )
    p.add_argument(
        "--remat",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="rematerialize transformer blocks in the backward pass "
        "(trade FLOPs for activation memory; long-context / big-batch "
        "runs); --no-remat overrides a config file's remat=true",
    )
    p.add_argument("--max-len", type=int)
    p.add_argument("--data-fraction", type=float)
    p.add_argument("--seed", type=int)
    p.add_argument(
        "--profile-dir",
        help="write a jax.profiler trace of the training phase here "
        "(view with xprof/tensorboard)",
    )
    p.add_argument(
        "--metrics-jsonl",
        help="append one structured JSON record per (round, client, phase) "
        "here — machine-readable observability the reference's prints/CSVs "
        "lack (pd.read_json(..., lines=True))",
    )
    p.add_argument(
        "--trace-jsonl",
        help="append obs spans (round/client-local/wire/agg/... with the "
        "round's shared trace id) to this events-JSONL; give every "
        "process its own file and merge with `fedtpu obs timeline "
        "--trace-dir DIR`",
    )
    p.add_argument(
        "--profile-stride",
        type=int,
        default=None,
        help="device performance plane (obs/profile.py): fence every Nth "
        "train/score step into host/dispatch/device-execute timers "
        "(fedtpu_*_step_seconds on /metrics + step attrs on the "
        "client-local span). 0/absent = off — the hot loops run the "
        "literal unprofiled path",
    )


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="fedtpu",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("local", help="single-client train/eval/report")
    _add_common(p)
    p.add_argument("--client-id", type=int, default=0)
    p.add_argument("--checkpoint-dir")
    p.set_defaults(fn=cmd_local)

    p = sub.add_parser("federated", help="N-client SPMD FedAvg on the TPU mesh")
    _add_common(p)
    p.add_argument("--num-clients", type=int, default=None)  # None: config wins
    p.add_argument("--rounds", type=int)
    p.add_argument("--data-parallel", type=int, help="per-client data-parallel shards")
    p.add_argument(
        "--seq-parallel",
        type=int,
        help="sequence-parallel shards per client (ring attention over a "
        "third 'seq' mesh axis; model.max_len must divide by it)",
    )
    g = p.add_mutually_exclusive_group()
    g.add_argument(
        "--weighted",
        action="store_true",
        help="require sample-count FedAvg weights (the auto default already "
        "weights by sample count when counts are known and DP is off)",
    )
    g.add_argument(
        "--unweighted",
        action="store_true",
        help="force the uniform mean (the reference's server.py:73-76)",
    )
    p.add_argument(
        "--partition", help="sample|disjoint|dirichlet|quantity"
    )
    p.add_argument(
        "--dirichlet-alpha",
        type=float,
        help="skew concentration for --partition dirichlet (label skew) "
        "or quantity (size skew); smaller = more non-IID (default 0.5)",
    )
    p.add_argument(
        "--prox-mu",
        type=float,
        help="FedProx proximal weight (0 = plain FedAvg); stabilizes "
        "non-IID partitions",
    )
    p.add_argument(
        "--personalize-epochs",
        type=int,
        help="after the final round, fine-tune the aggregate on each "
        "client's own shard for this many epochs and report a third "
        "'personalized' evaluation phase (0 = off)",
    )
    p.add_argument(
        "--personalize-scope",
        choices=["full", "head"],
        help="personalization scope: 'full' fine-tunes everything "
        "(FedAvg+FT); 'head' freezes the shared encoder and adapts only "
        "the classifier head (FedPer)",
    )
    p.add_argument(
        "--participation",
        type=float,
        help="fraction of clients aggregated per round (sampled, seeded); "
        "1.0 = everyone (reference behavior)",
    )
    p.add_argument(
        "--participation-mode",
        choices=["auto", "fixed", "poisson"],
        help="cohort sampler under --participation < 1: 'fixed' draws an "
        "exact-size cohort; 'poisson' draws each client independently "
        "(the DP accountant's assumption, making epsilon exact); 'auto' "
        "(default) = poisson when DP is on",
    )
    p.add_argument(
        "--dp-clip",
        type=float,
        help="DP-FedAvg: clip each client's round update to this L2 norm "
        "before aggregation (0 = off)",
    )
    p.add_argument(
        "--dp-noise-multiplier",
        type=float,
        help="DP-FedAvg: Gaussian noise multiplier on the clipped mean "
        "update (std = multiplier * clip / n_participants); requires "
        "--dp-clip",
    )
    p.add_argument(
        "--server-opt",
        choices=["none", "momentum", "adam", "yogi"],
        help="FedOpt server optimizer over the round's mean update: "
        "momentum = FedAvgM, adam = FedAdam, yogi = FedYogi (default "
        "none = plain FedAvg)",
    )
    p.add_argument(
        "--server-lr", type=float, help="server optimizer learning rate (default 1.0)"
    )
    p.add_argument(
        "--server-momentum", type=float, help="FedAvgM momentum (default 0.9)"
    )
    p.add_argument("--checkpoint-dir")
    p.add_argument(
        "--registry-dir",
        help="also publish every round's aggregate to this model registry "
        "as an immutable CANDIDATE artifact (fleet-mean validation "
        "metrics attached) — promotion stays with `fedtpu registry "
        "promote` / the controller's eval gate",
    )
    p.add_argument(
        "--coordinator",
        help="multi-host: coordinator HOST:PORT (every process passes the "
        "same address; also via JAX_COORDINATOR_ADDRESS)",
    )
    p.add_argument("--num-processes", type=int, help="multi-host: process count")
    p.add_argument("--process-id", type=int, help="multi-host: this process's id")
    p.set_defaults(fn=cmd_federated)

    p = sub.add_parser(
        "serve",
        help="TCP aggregation server (demo-parity mode)",
        epilog="Set FEDTPU_SECRET (env var, same value on server and every "
        "client) to require HMAC-SHA256-authenticated, replay-protected "
        "exchanges; unset = the reference's open protocol.",
    )
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=12345)
    p.add_argument("--num-clients", type=int, default=2)
    p.add_argument("--rounds", type=int, default=1)
    p.add_argument("--min-clients", type=int, default=None)
    p.add_argument("--weighted", action="store_true")
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument(
        "--compression",
        default="none",
        type=_reply_compression,
        help="reply encoding: none|bf16|int8 (topk is upload-side only)",
    )
    p.add_argument(
        "--reply-dtype",
        choices=["fp32", "bf16", "int8"],
        default="fp32",
        help="wire dtype for the STREAMED reply leg, capability-"
        "negotiated like the upload leg's --wire-dtype: clients that "
        "advertise the codec get the global streamed bf16 (2x) or "
        "chunked-absmax int8 (~4x) instead of fp32; everyone else "
        "(and dense replies) stays fp32. Lossy dtypes are refused "
        "under --secure-agg and with --compression (one reply "
        "encoding at a time)",
    )
    p.add_argument(
        "--secure-agg",
        action="store_true",
        help="secure aggregation: accept pairwise-masked uploads and "
        "recover only their sum — individual client weights are never "
        "visible to the server",
    )
    p.add_argument(
        "--secure-protocol",
        choices=["double", "reveal"],
        default="double",
        help="dropout recovery: double (default, full Bonawitz "
        "double-masking — Shamir-shared seeds, survives unmask-phase "
        "dropouts, false death claims recover nothing) or reveal "
        "(cheaper; a reveal-phase dropout fails the round). Set "
        "identically on clients",
    )
    p.add_argument(
        "--secure-threshold",
        type=int,
        default=None,
        help="Shamir threshold for double-masking (default: strict "
        "majority of the keyed participants — the value that makes the "
        "either/or share-reveal rule binding). Set identically on clients",
    )
    p.add_argument(
        "--dp-clip",
        type=float,
        default=0.0,
        help="central DP: require clipped round-delta uploads (clients "
        "run with --dp), aggregate mean(clipped deltas) + Gaussian noise, "
        "reply with the noised mean delta — the server never holds "
        "absolute weights; composes with --secure-agg (noise on the "
        "recovered sum)",
    )
    p.add_argument(
        "--dp-noise-multiplier",
        type=float,
        default=0.0,
        help="Gaussian noise std on the mean delta is "
        "multiplier * clip / n_clients; the accountant banner reports "
        "the (epsilon, delta) guarantee for the served rounds",
    )
    p.add_argument(
        "--dp-participation",
        type=float,
        default=1.0,
        help="Poisson cohort sampling rate q: each round samples every "
        "registered client independently with probability q; non-sampled "
        "clients sit the round out (they still receive the reply). "
        "q < 1 buys privacy amplification — the banner's subsampled "
        "accountant is exact for this sampler",
    )
    p.add_argument(
        "--trace-jsonl",
        help="append obs spans (round/agg/wire-reply with each round's "
        "trace id, also stamped into every reply meta) to this "
        "events-JSONL; merge with `fedtpu obs timeline --trace-dir`",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="expose live counters/gauges (rounds, uploads, wire bytes, "
        "per-phase seconds) at http://HOST:PORT/metrics in Prometheus "
        "text format (0 = off, the default)",
    )
    p.add_argument(
        "--stream-chunk-mb",
        type=float,
        default=None,
        help="advertise chunk-streamed uploads at this chunk size (MB): "
        "capable clients pipeline their uploads leaf-by-leaf and the "
        "server folds each chunk into the running mean as it arrives — "
        "bit-exact with the barrier mean, lower round latency and O(model)"
        " peak memory instead of O(clients x model). 0 disables the "
        "advert AND eager folding (the stop-the-world barrier shape); "
        "default 4. Old clients interop either way (plain meta field)",
    )
    p.add_argument(
        "--dp-history-file",
        default=None,
        help="persist the DP resync window (the retained post-noise "
        "round deltas) to this npz file and reload it on startup, so a "
        "server RESTART between rounds no longer re-strands stale "
        "clients — they heal bit-exactly from the reloaded fp32 "
        "history. Post-noise deltas are DP outputs; persisting them "
        "costs no privacy",
    )
    p.add_argument(
        "--strategy-state-file",
        default=None,
        help="persist the server's last post-strategy global and the "
        "strategy's optimizer state (FedOpt/momentum memory) to this "
        "npz file after every round and reload it on startup — a "
        "restarted server resumes its optimizer trajectory (and keeps "
        "sparse-delta clients' base) instead of re-adopting the bare "
        "mean. Ignored when the persisted strategy differs from "
        "--strategy",
    )
    p.add_argument(
        "--strategy",
        default=None,
        help="server aggregation strategy applied to the folded mean at "
        "finalize, as NAME[:k=v,k=v] — fedavg (default, bit-identical "
        "to the plain fold), fedprox[:mu=0.01] (advertises the proximal "
        "weight to clients), fedopt[:opt=adam|yogi,lr=0.1], "
        "momentum[:lr=1.0,momentum=0.9], headboost[:gamma=1.5,"
        "match=classifier]. Streamed folding, crc replay and relay "
        "trees are unchanged underneath; non-fedavg strategies refuse "
        "--secure-agg and --dp-clip",
    )
    _add_flight_dir(p)
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "relay",
        help="intermediate aggregator: fold a subtree of clients into a "
        "partial weighted mean and forward one streamed upload upward "
        "(hierarchical fold tree for 64-256-client cohorts)",
        epilog="Clients point at the relay exactly as at a root server "
        "(same wire protocol, same FEDTPU_SECRET auth). Run the ROOT "
        "`fedtpu serve` with --num-clients = the relay count and "
        "--weighted, so subtree means recombine by their sample mass. "
        "Secure aggregation and central DP stay single-aggregator by "
        "design — run those fleets flat.",
    )
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument(
        "--port", type=int, default=12346,
        help="subtree-facing listen port (default 12346)",
    )
    p.add_argument(
        "--parent-host", default="127.0.0.1",
        help="root (or higher-tier relay) host (default 127.0.0.1)",
    )
    p.add_argument(
        "--parent-port", type=int, default=12345,
        help="root (or higher-tier relay) port (default 12345)",
    )
    p.add_argument(
        "--relay-id", type=int, required=True,
        help="this relay's client id on the PARENT tier — the fixed "
        "subtree order at the root (ascending relay id)",
    )
    p.add_argument(
        "--num-clients", type=int, default=2,
        help="subtree size: how many clients this relay terminates",
    )
    p.add_argument("--min-clients", type=int, default=None)
    p.add_argument("--rounds", type=int, default=1)
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument(
        "--compression",
        default="none",
        type=_reply_compression,
        help="wire encoding both ways at this hop: none|bf16|int8",
    )
    p.add_argument(
        "--stream-chunk-mb",
        type=float,
        default=None,
        help="chunk-streamed upload advert for the subtree (see `serve "
        "--stream-chunk-mb`); 0 = barrier shape below this relay",
    )
    p.add_argument(
        "--no-stream-upload",
        dest="stream_upload",
        action="store_false",
        default=True,
        help="send the upward partial as one dense frame (and skip the "
        "streamed-reply advert to the parent)",
    )
    p.add_argument(
        "--subtree-deadline-factor",
        type=float,
        default=0.5,
        help="per-subtree straggler deadline as a fraction of --timeout, "
        "strictly inside (0, 1): a slow subtree sheds its stragglers "
        "(set --min-clients below the subtree size) or fails its local "
        "quorum — so its clients can re-home — while the root is still "
        "inside ITS deadline, instead of stalling the whole tree "
        "(default 0.5)",
    )
    p.add_argument(
        "--trace-jsonl",
        help="append obs spans (round/agg/wire-reply/relay-forward) to "
        "this events-JSONL; merge with `fedtpu obs timeline --trace-dir`",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="Prometheus /metrics for this relay's round engine "
        "(0 = off, the default)",
    )
    p.add_argument(
        "--upward-topk",
        type=float,
        default=None,
        help="sparsify the UPWARD hop: after round 1, the relay uploads "
        "topk deltas of its subtree partial against the last root "
        "aggregate it fanned down (error feedback carries the dropped "
        "mass), even when its leaves upload dense — upward bytes drop "
        "superlinearly with tree depth. Needs the root on lossless "
        "reply compression (base agreement is crc-pinned); value is "
        "the kept fraction, e.g. 0.01",
    )
    p.add_argument(
        "--strategy",
        default="fedavg",
        help="strategy id this relay declares on every upward upload "
        "(strategies apply at the ROOT only; the root refuses a relay "
        "whose declared strategy differs from its own — the split-brain "
        "guard). Must name the root's --strategy (default fedavg)",
    )
    _add_flight_dir(p)
    p.set_defaults(fn=cmd_relay)

    p = sub.add_parser(
        "client",
        help="TCP federated client (demo-parity mode)",
        epilog="Set FEDTPU_SECRET (env var) to authenticate exchanges; must "
        "match the server's.",
    )
    _add_common(p)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=12345)
    p.add_argument(
        "--parent",
        action="append",
        metavar="HOST:PORT",
        default=None,
        help="parent aggregator as HOST:PORT; REPEATABLE — the first is "
        "the primary (overrides --host/--port), every further one a "
        "ranked fallback. When the primary's dial budget runs out, or "
        "its connection dies mid-exchange before the reply lands, the "
        "client re-homes to the next parent and re-uploads (dense, "
        "marked): the adoptive relay folds it as an EXTRA contributor. "
        "List sibling relays — client ids are globally unique across "
        "subtrees; relay ids at the root are a different namespace",
    )
    p.add_argument(
        "--rehome-dial-budget",
        type=float,
        default=8.0,
        help="seconds of seeded dial backoff per parent when fallback "
        "parents are configured (a dead parent costs this, not the "
        "whole --timeout; default 8)",
    )
    p.add_argument("--client-id", type=int, required=True)
    p.add_argument("--num-clients", type=int, default=None)  # None: config wins
    p.add_argument(
        "--data-parallel",
        type=int,
        help="shard the local training batch over this many of THIS "
        "host's devices (params replicated, gradient psum on-mesh); the "
        "trajectory stays threefry-identical to the single-device client "
        "and the wire exchange is unchanged",
    )
    p.add_argument(
        "--seq-parallel",
        type=int,
        help="sequence-parallel shards for the local phase (ring "
        "attention over a local 'seq' mesh axis via a C=1 fedseq trainer; "
        "model.max_len must divide by it)",
    )
    p.add_argument(
        "--fsdp",
        action="store_true",
        default=None,
        help="FSDP shard-at-rest with --data-parallel N: params AND "
        "optimizer state shard per-leaf over the N local devices "
        "(all-gather at use, backward re-gathers via remat, grads "
        "reduce-scatter) so per-chip static bytes scale ~1/N — big-model "
        "clients become compute-bound again. Trajectory matches the "
        "replicated mesh to fp32 reduction-order ulps; the wire "
        "exchange, secure-agg, and DP compose unchanged",
    )
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument(
        "--compression",
        default="none",
        type=_wire_compression,
        help="upload encoding: none|bf16|int8|topk[:frac]. topk switches "
        "the exchange to sparse round deltas with client-side error "
        "feedback (~50x smaller uploads at the default frac 0.01 after "
        "the first, dense round)",
    )
    p.add_argument(
        "--wire-dtype",
        choices=["fp32", "bf16", "int8"],
        default="fp32",
        help="quantize STREAMED upload chunks to this dtype when the "
        "server advertises support (negotiated via reply meta, like "
        "--stream-chunk-mb: round 1 goes fp32, later rounds upgrade). "
        "int8 carries a per-4096-element fp32 scale and cuts upload "
        "bytes ~3.98x; an old server keeps getting fp32. Refused "
        "alongside --secure-agg or --compression (the masked/sparse "
        "paths have their own encodings); composes with --dp — the "
        "server re-clips after dequantization",
    )
    p.add_argument(
        "--secure-agg",
        action="store_true",
        help="mask the upload with per-pair Diffie-Hellman secrets (fresh "
        "ephemeral keys each round, relayed through the server) so the "
        "server sees only the sum and no client can unmask another pair",
    )
    p.add_argument(
        "--min-participants",
        type=int,
        default=None,
        help="secure-agg quorum floor THIS client will mask over (default: "
        "the full fleet). Set to the server's --min-clients to opt into "
        "dropout-recovery quorums; a keys frame below the floor is "
        "refused without retry (anti-downgrade)",
    )
    p.add_argument(
        "--secure-protocol",
        choices=["double", "reveal"],
        default="double",
        help="secure-agg dropout recovery; must match the server's "
        "--secure-protocol (a mismatched advert is refused — downgrade "
        "protection)",
    )
    p.add_argument(
        "--secure-threshold",
        type=int,
        default=None,
        help="Shamir threshold for double-masking; must match the "
        "server's --secure-threshold (default: majority of the keyed "
        "participants)",
    )
    p.add_argument(
        "--dp",
        action="store_true",
        help="central DP (server runs with --dp-clip): upload the clipped "
        "round delta vs this round's starting params; the clip bound and "
        "noise multiplier come from the server's advert",
    )
    p.add_argument(
        "--checkpoint-dir",
        help="warm-start + save full state here (the reference's "
        "client{N}_model.pth re-launch pattern, client1.py:375-377,388,403)",
    )
    p.add_argument(
        "--rounds",
        type=int,
        default=1,
        help="train/exchange rounds in one process (server must serve >= "
        "this many); the reference achieves this by re-launching",
    )
    p.add_argument(
        "--no-stream-upload",
        dest="stream_upload",
        action="store_false",
        default=True,
        help="never chunk-stream uploads, even when the server "
        "advertises support (--stream-chunk-mb): every upload stays one "
        "dense frame — the old-peer wire shape, useful for interop "
        "testing and as the pipelining A/B arm",
    )
    p.add_argument(
        "--partition", help="sample|disjoint|dirichlet|quantity"
    )
    p.add_argument(
        "--dirichlet-alpha",
        type=float,
        help="skew concentration for --partition dirichlet/quantity "
        "(smaller = more non-IID; default 0.5). Same seeded partition "
        "as the mesh tier: client i holds identical rows on both tiers",
    )
    p.add_argument(
        "--persona",
        choices=["honest", "lazy", "slow", "intermittent", "stale",
                 "flaky-net"],
        default=None,
        help="run this client under a misbehavior persona "
        "(faults/personas.py): lazy trains fewer epochs; slow throttles "
        "its upload through a local fault proxy; intermittent dies "
        "mid-upload once per exchange and retries; stale sits out every "
        "second round; flaky-net randomly resets connections (seeded). "
        "Wire faults run through a deterministic in-process TCP proxy "
        "against the REAL server — start the server first",
    )
    p.add_argument(
        "--fault-seed",
        type=int,
        default=0,
        help="seed for the persona's deterministic wire-fault draws "
        "(same seed = same faults, byte-for-byte)",
    )
    p.add_argument(
        "--prox-mu",
        type=float,
        default=None,
        help="FedProx proximal weight for the LOCAL phase: each train "
        "step adds mu/2 * ||params - round-start aggregate||^2, pulling "
        "client drift back toward the global (pairs with the server's "
        "--strategy fedprox, whose reply meta advertises the fleet's "
        "mu). 0/unset = plain local SGD; composes with --data-parallel "
        "and --fsdp",
    )
    p.set_defaults(fn=cmd_client)

    p = sub.add_parser(
        "predict",
        help="batch inference: flow CSV -> per-row attack probability CSV",
    )
    _add_common(p)  # provides --csv (required here), --dataset, model flags
    p.add_argument(
        "--output", default="predictions.csv", help="predictions CSV path"
    )
    p.add_argument("--checkpoint-dir", help="local or federated training checkpoint")
    p.add_argument(
        "--threshold",
        type=float,
        default=0.5,
        help="P(attack) decision threshold (default 0.5)",
    )
    p.set_defaults(fn=cmd_predict)

    p = sub.add_parser(
        "infer-serve",
        help="online inference: dynamic-batching TCP scoring service with "
        "hot checkpoint reload",
        epilog="Requests are one frame each (serving/protocol.py): "
        '{"id": N, "text": "..."} or {"id": N, "features": {...}} with an '
        "optional per-request deadline_ms; replies carry P(attack) plus "
        "telemetry (model round, batch size, queue wait). A full queue or "
        "a blown deadline gets an explicit reject frame, never a hang.",
    )
    _add_common(p)  # model/tokenizer/dataset resolution flags
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=12380)
    p.add_argument(
        "--checkpoint-dir",
        help="serve (and hot-reload) from this local/federated training "
        "checkpoint; new rounds are picked up between batches",
    )
    p.add_argument(
        "--registry-dir",
        help="serve from the model registry's PROMOTED artifact instead "
        "of a raw checkpoint dir: the process follows the atomically-"
        "swapped serving pointer (fedtpu controller / registry promote), "
        "so unevaluated or gate-rejected rounds can never reach traffic "
        "and a rollback takes effect within one poll",
    )
    p.add_argument(
        "--auth",
        action="store_true",
        help="require the FL tier's HMAC challenge-response on every "
        "scoring connection (shared secret from FEDTPU_SECRET; the SDK "
        "passes auth_key). Default: open port, like the reference",
    )
    p.add_argument(
        "--buckets",
        default="1,8,32,128",
        help="micro-batch bucket shapes; XLA compiles one program per "
        "(bucket, seq) at startup and every request hits a warm path "
        "(default 1,8,32,128)",
    )
    p.add_argument(
        "--max-wait-ms",
        type=float,
        default=5.0,
        help="batch gather window: how long the scorer coalesces after "
        "the first queued request (latency floor a lone request pays; "
        "default 5)",
    )
    p.add_argument(
        "--max-queue",
        type=int,
        default=1024,
        help="admission-control queue bound; a submit beyond it is "
        "rejected immediately with a 503-style frame (default 1024)",
    )
    p.add_argument(
        "--default-deadline-ms",
        type=float,
        default=None,
        help="deadline applied to requests that name none (default: wait "
        "forever); expired requests get an explicit reject frame",
    )
    p.add_argument(
        "--reload-poll",
        type=float,
        default=2.0,
        help="seconds between checkpoint-directory polls on the scorer's "
        "idle tick (default 2)",
    )
    p.add_argument(
        "--threshold",
        type=float,
        default=0.5,
        help="P(attack) decision threshold in replies (default 0.5)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="expose live gauges/counters (queue depth, rejects by kind, "
        "scored total, queue-wait histogram) at http://HOST:PORT/metrics "
        "in Prometheus text format (0 = off, the default)",
    )
    p.add_argument(
        "--trace-sample",
        type=float,
        default=None,
        help="serve-batch span sampling rate in (0, 1]: with --trace-jsonl"
        " on a high-rate scorer, emit one span per ~1/RATE coalesced "
        "batches (deterministic batch-counter stride, not RNG; each span "
        "carries sampled_batches so the timeline can re-scale). Default "
        "1.0 = every batch, the pre-sampling behavior",
    )
    p.add_argument(
        "--scored-jsonl",
        help="append one {rid, prob, round} record per ANSWERED request "
        "here — the join key against the delayed ground-truth journal "
        "(fedtpu labels report --scored X). Off by default: the metrics "
        "stream keeps exporting binned histograms, never raw scores",
    )
    p.add_argument(
        "--data-parallel",
        type=int,
        default=None,
        help="with --fsdp: shard the serving params over this many local "
        "chips (N >= 2). Serves models bigger than one chip: per-chip "
        "static bytes scale ~1/N and each warm bucket program gathers "
        "the weights at use",
    )
    p.add_argument(
        "--fsdp",
        action="store_true",
        default=None,
        help="shard-at-rest serving (needs --data-parallel N): checkpoint "
        "restore scatters leaves straight onto shards, hot reloads swap "
        "without recompiling warm buckets, probs stay bit-identical to "
        "the replicated engine",
    )
    _add_flight_dir(p)
    p.set_defaults(fn=cmd_infer_serve)

    p = sub.add_parser(
        "route",
        help="serving router: load-balance the scoring protocol across N "
        "infer-serve replicas (least-in-flight pick, health probes, "
        "eject/readmit)",
        epilog="The router is model-free — it never tokenizes or scores; "
        "per-request cost is two id rewrites and two socket writes. "
        "Health rides the in-band stats() probe on each replica "
        "connection, so 'probe healthy' cannot diverge from 'requests "
        "flow'. With FEDTPU_SECRET + --auth the whole chain "
        "(client -> router -> replica) is HMAC-authenticated.",
    )
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=12390)
    p.add_argument(
        "--backend",
        action="append",
        metavar="HOST:PORT",
        help="an infer-serve replica to route across (repeatable, >= 1)",
    )
    p.add_argument(
        "--auth",
        action="store_true",
        help="HMAC challenge-response on the front port AND on every "
        "backend dial (shared secret from FEDTPU_SECRET)",
    )
    p.add_argument(
        "--probe-interval",
        type=float,
        default=1.0,
        help="seconds between per-replica stats() health probes (default 1)",
    )
    p.add_argument(
        "--probe-timeout",
        type=float,
        default=5.0,
        help="unanswered-probe age that ejects a replica (default 5)",
    )
    p.add_argument(
        "--max-inflight",
        type=int,
        default=1024,
        help="per-replica in-flight bound; a replica at the bound leaves "
        "the pick set until replies drain it (default 1024)",
    )
    p.add_argument(
        "--trace-jsonl",
        help="append obs spans (router-forward) to this events-JSONL",
    )
    p.add_argument(
        "--trace-sample",
        type=float,
        default=None,
        help="router-forward span sampling rate in (0, 1] (counter-strided"
        ", like infer-serve --trace-sample); default 1.0",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="Prometheus /metrics: per-replica in-flight gauges, eject and "
        "forward counters (0 = off, the default)",
    )
    _add_flight_dir(p)
    p.set_defaults(fn=cmd_route)

    p = sub.add_parser(
        "fleet",
        help="local replica fleet: N infer-serve replicas behind the "
        "router with registry-following ROLLING hot-reload (zero-drop "
        "promotions)",
        epilog="Serves the registry's PROMOTED artifact on every replica. "
        "On a promotion the fleet manager drains one replica at a time "
        "(router pick-set removal -> in-flight wait -> hot-swap -> "
        "readmit), so the serving pointer moves under load without "
        "dropping a request — the bench pins "
        "router_rolling_reload_dropped == 0.",
    )
    _add_common(p)  # model/tokenizer/dataset resolution flags
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=12390)
    p.add_argument(
        "--replicas",
        type=int,
        default=None,
        help="replica count (default: config router.replicas = 3)",
    )
    p.add_argument(
        "--registry-dir",
        required=True,
        help="model registry whose serving pointer the fleet follows",
    )
    p.add_argument(
        "--auth",
        action="store_true",
        help="HMAC auth end-to-end: front port, every replica port, and "
        "the router's backend dials (FEDTPU_SECRET)",
    )
    p.add_argument(
        "--buckets",
        default="1,8,32,128",
        help="per-replica micro-batch bucket shapes (default 1,8,32,128)",
    )
    p.add_argument(
        "--max-wait-ms",
        type=float,
        default=5.0,
        help="per-replica batch gather window (default 5)",
    )
    p.add_argument(
        "--max-queue",
        type=int,
        default=1024,
        help="per-replica admission-control queue bound (default 1024)",
    )
    p.add_argument(
        "--reload-poll",
        type=float,
        default=2.0,
        help="seconds between serving-pointer polls (default 2)",
    )
    p.add_argument(
        "--threshold",
        type=float,
        default=0.5,
        help="P(attack) decision threshold in replies (default 0.5)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="Prometheus /metrics for the router + replicas (0 = off)",
    )
    p.add_argument(
        "--shadow-sample",
        type=int,
        default=None,
        help="arm the shadow evaluation plane (shadow/): mirror one live "
        "request in N onto the registry's shadow-state artifact "
        "(deterministic counter stride, fire-and-forget — a full mirror "
        "queue drops the copy, never a live reply). The shadow replica "
        "is spun up by this fleet manager and NEVER joins the router's "
        "pick set. Default: config shadow.sample (0 = off)",
    )
    _add_flight_dir(p)
    p.set_defaults(fn=cmd_fleet)

    p = sub.add_parser(
        "controller",
        help="control plane: continuous eval-gated federated rounds "
        "(round -> gate -> promote -> serve -> drift-monitor loop)",
        epilog="Set FEDTPU_SECRET to authenticate the round endpoint "
        "(same contract as `serve`). Central DP is not supported here: a "
        "DP server never holds the absolute params an artifact needs.",
    )
    _add_common(p)  # dataset/model flags resolve the held-out gate split
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=12345)
    p.add_argument("--num-clients", type=int, default=None)
    p.add_argument("--min-clients", type=int, default=None)
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument(
        "--rounds",
        type=int,
        default=0,
        help="stop after this many controller cycles (0 = run until "
        "interrupted — the daemon shape)",
    )
    p.add_argument(
        "--registry-dir",
        required=True,
        help="model registry root: every finished round writes an "
        "immutable candidate artifact here; the serving pointer is the "
        "file infer-serve --registry-dir follows",
    )
    p.add_argument(
        "--state-jsonl",
        default=None,
        help="controller-state JSONL (default: "
        "<registry-dir>/controller_state.jsonl); a restarted controller "
        "replays it and resumes the campaign mid-way",
    )
    p.add_argument(
        "--secure-agg",
        action="store_true",
        help="accept pairwise-masked uploads (comm/secure.py); the gate "
        "evaluates the recovered mean as usual",
    )
    p.add_argument(
        "--gate-metric",
        default=None,
        help="held-out metric the promotion gate compares (default "
        "Accuracy; higher is better)",
    )
    p.add_argument(
        "--gate-min-delta",
        type=float,
        default=None,
        help="tolerated regression: candidate must score >= incumbent - "
        "delta (default 0 = never promote a worse model)",
    )
    p.add_argument(
        "--interval",
        type=float,
        default=None,
        help="minimum seconds between round starts (fixed cadence when no "
        "--drift-jsonl is given; default 0 = back-to-back)",
    )
    p.add_argument(
        "--max-interval",
        type=float,
        default=None,
        help="with --drift-jsonl: force a round after this many seconds "
        "even when no drift fired (default: none — purely drift-driven)",
    )
    p.add_argument(
        "--drift-jsonl",
        help="serving metrics-JSONL to tail (infer-serve --metrics-jsonl "
        "X): rounds trigger when the live score distribution shifts off "
        "the promoted artifact's eval histogram",
    )
    p.add_argument(
        "--drift-threshold",
        type=float,
        default=None,
        help="drift distance that triggers a round (default 0.25 — the "
        "classic PSI 'significant shift' bound)",
    )
    p.add_argument(
        "--drift-min-scores",
        type=int,
        default=None,
        help="minimum live scores before a drift verdict (default 256)",
    )
    p.add_argument(
        "--drift-method",
        choices=["psi", "ks"],
        default=None,
        help="distribution distance: psi (default) or ks",
    )
    p.add_argument(
        "--round-deadline",
        type=float,
        default=None,
        help="per-round straggler deadline in seconds handed to the round "
        "engine (default: the server --timeout)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="expose live counters (rounds, promotions, gate rejections, "
        "round-phase seconds) at http://HOST:PORT/metrics in Prometheus "
        "text format (0 = off, the default)",
    )
    p.add_argument(
        "--stream-chunk-mb",
        type=float,
        default=None,
        help="chunk-streamed upload advert for the embedded round engine "
        "(see `serve --stream-chunk-mb`); 0 = barrier shape",
    )
    p.add_argument(
        "--max-artifacts",
        type=int,
        default=None,
        help="registry GC after every promotion/rejection: prune oldest "
        "retired/rejected artifacts beyond this count (the serving "
        "artifact and its rollback chain are never pruned); default: "
        "keep everything",
    )
    p.add_argument(
        "--shadow-gate",
        action="store_true",
        help="hold every eval-passing candidate in the registry SHADOW "
        "state and promote only after the live mirror (fedtpu fleet "
        "--shadow-sample) accumulated >= --shadow-min-pairs pairs with "
        "disagreement under threshold; regression (or no evidence "
        "inside --shadow-timeout) fails closed to rejected with the "
        "verdict on the registry event",
    )
    p.add_argument(
        "--shadow-min-pairs",
        type=int,
        default=None,
        help="mirrored pairs required before the shadow gate rules "
        "(default: config shadow.min_pairs = 256)",
    )
    p.add_argument(
        "--shadow-timeout",
        type=float,
        default=None,
        help="seconds the shadow gate waits for its evidence before "
        "failing closed (default: config shadow.timeout_s = 600)",
    )
    p.add_argument(
        "--shadow-max-flip-rate",
        type=float,
        default=None,
        help="max tolerated prediction-flip fraction across mirrored "
        "pairs (default: config shadow.max_flip_rate = 0.02)",
    )
    p.add_argument(
        "--shadow-psi-threshold",
        type=float,
        default=None,
        help="max tolerated PSI between the paired serving/shadow score "
        "histograms (default: config shadow.psi_threshold = 0.25)",
    )
    p.add_argument(
        "--adaptive-cadence",
        action="store_true",
        help="scale the inter-round interval between --interval and "
        "--max-interval by each drift verdict's magnitude (barely over "
        "threshold -> relaxed max; >= 2x threshold -> urgent min); the "
        "chosen interval rides the drift-trigger span",
    )
    p.add_argument(
        "--label-gate",
        action="store_true",
        help="supervised promotion rung AFTER the shadow gate: join the "
        "candidate's mirror pairs against the delayed ground-truth "
        "journal (<registry>/labels/journal.jsonl, fedtpu labels "
        "ingest) and reject any candidate whose supervised error "
        "exceeds the incumbent's by more than --label-max-regression; "
        "too few joined labels or coverage under --label-coverage-floor "
        "fails closed",
    )
    p.add_argument(
        "--label-journal",
        help="ground-truth journal override (default: "
        "<registry>/labels/journal.jsonl)",
    )
    p.add_argument(
        "--label-min-joined",
        type=int,
        default=None,
        help="joined (labeled) flows required before the label gate "
        "rules (default: config labels.min_joined = 32)",
    )
    p.add_argument(
        "--label-coverage-floor",
        type=float,
        default=None,
        help="minimum joined/total coverage of the scored population "
        "(default: config labels.coverage_floor = 0.05)",
    )
    p.add_argument(
        "--label-max-regression",
        type=float,
        default=None,
        help="max tolerated candidate-over-serving supervised error "
        "excess (default: config labels.max_regression = 0)",
    )
    p.add_argument(
        "--error-drift",
        action="store_true",
        help="with --label-gate: also trigger rounds when the SERVING "
        "model's supervised error over joined ground truth rises "
        "labels.error_margin past its promoted reference (the "
        "regression score-histogram drift cannot see)",
    )
    p.add_argument(
        "--sentinel-jsonl",
        help="tail this sentinel verdicts-JSONL (fedtpu obs sentinel "
        "--verdicts-jsonl) and treat each new supervised-drift verdict "
        "as a corrective-round trigger — the cross-process twin of "
        "--error-drift (only verdicts appended AFTER startup count)",
    )
    p.add_argument(
        "--drift-cohort",
        action="store_true",
        help="scale the corrective round's quorum by each drift "
        "verdict's magnitude between --cohort-min-frac and "
        "--cohort-max-frac of --min-clients (one round, then the base "
        "quorum restores); the chosen quorum rides the drift-trigger "
        "record",
    )
    p.add_argument(
        "--cohort-min-frac",
        type=float,
        default=None,
        help="quorum fraction at barely-over-threshold drift (default: "
        "config control.cohort_min_frac = 0.5)",
    )
    p.add_argument(
        "--cohort-max-frac",
        type=float,
        default=None,
        help="quorum fraction at >= 2x-threshold drift (default: "
        "config control.cohort_max_frac = 1.0)",
    )
    p.add_argument(
        "--slo-alerts-jsonl",
        help="tail the health plane's alerts-JSONL (fedtpu obs "
        "health|watch --alerts-jsonl) and, while the round-duration "
        "burn alert FIRES, tighten the straggler deadline by "
        "--slo-deadline-factor until it clears",
    )
    p.add_argument(
        "--slo-deadline-factor",
        type=float,
        default=None,
        help="straggler-deadline multiplier applied while the "
        "round-duration SLO fires (default: config "
        "control.slo_deadline_factor = 0.5)",
    )
    _add_flight_dir(p)
    p.set_defaults(fn=cmd_controller)

    p = sub.add_parser(
        "scenario",
        help='the "federated in the wild" matrix: persona x partition '
        "cells of live loopback rounds with wire-level fault injection",
        epilog="Each cell runs a REAL AggregationServer + client fleet "
        "on loopback, with the row's persona driving faults through the "
        "deterministic TCP fault proxy (faults/). Outcomes come from "
        "the obs timeline (contributors, drop attribution, straggler "
        "wait); every successful round's aggregate is crc-pinned "
        "bit-exact against the clean barrier mean over the same "
        "survivor set. Exits 1 on any contract violation.",
    )
    p.add_argument(
        "--personas",
        default="lazy,slow,intermittent",
        help="comma list of matrix rows (honest|lazy|slow|intermittent|"
        "stale|flaky-net; default lazy,slow,intermittent)",
    )
    p.add_argument(
        "--partitions",
        default="iid,dirichlet",
        help="comma list of matrix columns (iid|dirichlet|quantity; "
        "default iid,dirichlet)",
    )
    p.add_argument(
        "--dirichlet-alpha",
        type=float,
        default=0.1,
        help="skew concentration for the dirichlet/quantity columns "
        "(default 0.1 — heavily non-IID)",
    )
    p.add_argument("--clients", type=int, default=3)
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument(
        "--payload-kb",
        type=int,
        default=64,
        help="synthetic per-client model payload size (default 64)",
    )
    p.add_argument(
        "--deadline",
        type=float,
        default=8.0,
        help="per-round straggler deadline seconds (default 8)",
    )
    p.add_argument("--fault-seed", type=int, default=0)
    p.add_argument(
        "--out-dir",
        default="outputs/scenario",
        help="grid.txt + scenario.jsonl + per-cell trace JSONLs land "
        "here (default outputs/scenario)",
    )
    p.add_argument(
        "--train",
        action="store_true",
        help="train a tiny real model per client on the partitioned "
        "shards (adds the per-cell accuracy column; slower)",
    )
    p.add_argument(
        "--no-auth-cell",
        action="store_true",
        help="skip the extra HMAC-authenticated cell",
    )
    p.add_argument(
        "--no-dead-relay-cell",
        action="store_true",
        help="skip the dead-relay cell (depth-2 fold tree with a seeded "
        "mid-round relay kill: the victim subtree's clients re-home to "
        "the surviving relay and the root completes a degraded round, "
        "crc-pinned against the actual-contributor replay)",
    )
    p.add_argument(
        "--no-stream",
        action="store_true",
        help="dense single-frame uploads in every cell (default: the "
        "server advertises chunk-streamed uploads, so round 2+ streams)",
    )
    p.add_argument(
        "--strategies",
        default=None,
        help="';'-separated server strategy specs (NAME[:k=v,...], see "
        "`serve --strategy`; plain ',' also works for bare names) to "
        "APPEND as extra matrix cells — each persona x partition pair "
        "re-runs under every listed non-fedavg strategy, with the base "
        "cells as the fedavg baseline (add --train for the accuracy "
        "comparator). fedprox specs thread their mu into the cell's "
        "client training automatically",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="print one JSON record per cell instead of the grid",
    )
    p.set_defaults(fn=cmd_scenario)

    p = sub.add_parser(
        "obs",
        help="observability: round timelines, Chrome export, live span "
        "tailing, fleet health (SLO burn alerts), postmortem bundles, "
        "device profiling",
        epilog="Every tier writes spans with --trace-jsonl; the server "
        "stamps one trace id per round into its replies, so the merged "
        "files agree on (trace, round). `timeline` attributes each "
        "round's wall-clock to per-client compute / straggler wait / "
        "wire / agg; `export` writes chrome://tracing JSON. `health` "
        "scrapes every --target daemon's /metrics.json, evaluates the "
        "SLO burn rates, and renders the one-screen fleet view (`watch` "
        "= the live-refresh loop); `postmortem` lists/inspects the "
        "flight recorder's failure bundles (--flight-dir). `profile` "
        "runs the device performance plane (obs/profile.py) end-to-end "
        "on real train steps: compile ledger by site, recompile flags, "
        "fenced host/dispatch/device step split, memory watermarks, "
        "the analytic-vs-XLA FLOPs cross-check, and the bucketed "
        "serving path's zero-recompile storm (--capture DIR wraps "
        "jax.profiler around the profiled steps).",
    )
    p.add_argument(
        "action",
        choices=[
            "timeline", "export", "tail", "health", "watch", "postmortem",
            "profile", "sentinel",
        ],
    )
    p.add_argument(
        "--trace-dir",
        help="directory of span JSONLs (every *.jsonl is merged; tail "
        "also picks up files that appear later)",
    )
    p.add_argument(
        "--trace",
        action="append",
        metavar="FILE",
        help="individual span JSONL (repeatable; composes with "
        "--trace-dir)",
    )
    p.add_argument(
        "--round",
        type=int,
        default=None,
        help="only this round (timeline/tail)",
    )
    p.add_argument(
        "--trace-id",
        default=None,
        help="tail: only spans carrying this trace id",
    )
    p.add_argument(
        "--from-start",
        action="store_true",
        help="tail: replay existing spans before following (default: "
        "start at each file's end, new spans only)",
    )
    p.add_argument(
        "--poll",
        type=float,
        default=0.5,
        help="tail: seconds between file polls (default 0.5)",
    )
    p.add_argument(
        "--max-seconds",
        type=float,
        default=None,
        help="tail: stop after this many seconds (default: follow until "
        "interrupted — the live-ops shape)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable JSON instead of the rendered output "
        "(timeline/health/postmortem)",
    )
    p.add_argument("--out", help="output path (export)")
    p.add_argument(
        "--target",
        action="append",
        metavar="TIER=HOST:PORT[,events=PATH]",
        help="health/watch: a daemon's /metrics.json endpoint to scrape "
        "(repeatable; TIER in serve|relay|controller|infer-serve|route|"
        "fleet names the lane; events=PATH additionally tails that "
        "process's span JSONL for drift/postmortem state)",
    )
    p.add_argument(
        "--slo",
        help="health/watch: JSON file of SLO objects (obs/slo.py SLO "
        "fields) replacing the built-in fleet objectives",
    )
    p.add_argument(
        "--alerts-jsonl",
        help="health/watch: append burn-alert fire/clear events here "
        "(one atomic JSON line each)",
    )
    p.add_argument(
        "--snapshot-jsonl",
        help="health/watch: append one merged fleet snapshot record "
        "per poll here, keyed by (tier, instance)",
    )
    p.add_argument(
        "--snapshot-max-mb",
        type=float,
        default=None,
        help="health/watch/sentinel: bound the snapshot JSONL — past "
        "this size the live file atomically rolls to <path>.1 and a "
        "fresh generation starts (at most ~2x the cap on disk; "
        "default: unbounded, the pre-existing behavior)",
    )
    p.add_argument(
        "--watch",
        action="store_true",
        help="health: live-refresh loop instead of one pass (same as "
        "the watch action)",
    )
    p.add_argument(
        "--interval",
        type=float,
        default=None,
        help="watch: seconds between scrape passes; health: spacing of "
        "the one-shot pass's two polls — burn rates and cadence are "
        "counter DELTAS, so one scrape has no baseline (default 2)",
    )
    p.add_argument(
        "--scrape-timeout",
        type=float,
        default=None,
        help="health/watch: per-target scrape timeout seconds "
        "(default 2); a slower daemon is marked DOWN, never blocks "
        "the screen",
    )
    p.add_argument(
        "--trace-jsonl",
        help="health/watch: append the hub's own slo-eval spans here",
    )
    p.add_argument(
        "--flight-dir",
        help="postmortem: the flight-recorder bundle directory the "
        "daemons were started with (--flight-dir on serve/relay/"
        "controller/infer-serve/route/fleet); health/watch: ALSO arm "
        "the hub's own recorder there, so a page-severity SLO fire "
        "dumps a postmortem bundle (the hub is the process that "
        "evaluates SLOs — daemon recorders never learn of a page)",
    )
    p.add_argument(
        "--bundle",
        help="postmortem: inspect this bundle (name from the list, or "
        "a path) instead of listing",
    )
    p.add_argument(
        "--alert-cmd",
        help="health/watch: run this shell command once per page-"
        "severity SLO fire, with the alert event JSON on stdin (the "
        "notification fan-out next to --alerts-jsonl); rate-limited to "
        "one spawn per --alert-interval, OSError-guarded — a broken "
        "pager never kills the poll loop",
    )
    p.add_argument(
        "--alert-interval",
        type=float,
        default=None,
        help="health/watch: minimum seconds between --alert-cmd spawns "
        "(default 30)",
    )
    p.add_argument(
        "--preset",
        default="tiny",
        help="profile: model preset to profile "
        "(tiny|distilbert|bert|bert-large; default tiny)",
    )
    p.add_argument(
        "--steps",
        type=int,
        default=12,
        help="profile: profiled train steps after warmup (default 12)",
    )
    p.add_argument(
        "--batch-size",
        type=int,
        default=8,
        help="profile: train batch size (default 8)",
    )
    p.add_argument(
        "--stride",
        type=int,
        default=1,
        help="profile: sample every Nth step (default 1 — every step "
        "fenced; production daemons use --profile-stride instead)",
    )
    p.add_argument(
        "--capture",
        metavar="DIR",
        help="profile: additionally wrap jax.profiler around the "
        "profiled steps and write the trace here (xprof/tensorboard)",
    )
    p.add_argument(
        "--canaries",
        help="sentinel: canary-flows JSONL fixture (fedtpu-canary-v1 "
        "lines: id, preset, label, text) scored through the live "
        "serving chain every tick",
    )
    p.add_argument(
        "--canary-preset",
        default=None,
        help="sentinel: only this preset's canaries from --canaries "
        "(default: all)",
    )
    p.add_argument(
        "--serve",
        metavar="HOST:PORT",
        help="sentinel: the scoring endpoint (router or replica) the "
        "canary probes dial",
    )
    p.add_argument(
        "--registry-dir",
        help="sentinel: model registry root — canary replies must match "
        "its promoted serving pointer (round + artifact identity)",
    )
    p.add_argument(
        "--scored-jsonl",
        help="sentinel: the serving tier's scored-request export "
        "(fedtpu-scored-v1) to tail for the supervised-drift join",
    )
    p.add_argument(
        "--labels-journal",
        help="sentinel: the ground-truth labels journal "
        "(fedtpu-label-v1) to tail against --scored-jsonl",
    )
    p.add_argument(
        "--reference-error",
        type=float,
        default=None,
        help="sentinel: the promoted model's reference error rate the "
        "continuous supervised monitor compares against (required with "
        "--scored-jsonl/--labels-journal)",
    )
    p.add_argument(
        "--error-margin",
        type=float,
        default=None,
        help="sentinel: supervised error margin over the reference "
        "before a drift verdict fires (default 0.05)",
    )
    p.add_argument(
        "--error-min-joined",
        type=int,
        default=None,
        help="sentinel: joined flows required before a supervised "
        "verdict may fire (default 64)",
    )
    p.add_argument(
        "--verdicts-jsonl",
        help="sentinel: append fired supervised-drift verdicts here — "
        "the file the controller's --sentinel-jsonl tails for its "
        "corrective-round poke",
    )
    p.add_argument(
        "--ring-jsonl",
        help="sentinel: the long-horizon retention ring's on-disk path "
        "(downsampled per-tick rows; survives sentinel restarts)",
    )
    p.add_argument(
        "--ring-records",
        type=int,
        default=None,
        help="sentinel: ring rows retained (default 512)",
    )
    p.add_argument(
        "--ring-stride",
        type=int,
        default=None,
        help="sentinel: retain every Nth tick in the ring (default 1)",
    )
    p.add_argument(
        "--baseline-n",
        type=int,
        default=None,
        help="sentinel: ring rows pinned as the regression baseline "
        "window (the first N retained; default 8)",
    )
    p.add_argument(
        "--window-n",
        type=int,
        default=None,
        help="sentinel: current-window rows a trend check averages "
        "(default 8)",
    )
    p.add_argument(
        "--regression-ratio",
        type=float,
        default=None,
        help="sentinel: fire when a watched field's current-window mean "
        "moves past baseline * ratio (default 1.5; round cadence fires "
        "on the inverse drop)",
    )
    p.add_argument(
        "--trend-field",
        action="append",
        default=None,
        metavar="NAME[:direction]",
        help="sentinel: ALSO run the retention-ring trend check on this "
        "per-deployment field (repeatable). The value is read from the "
        "scraped targets' metric snapshots (max across targets, like "
        "eject rate); direction up (default) fires on a rise past "
        "baseline * ratio, down on the inverse drop. --regression-ratio "
        "applies to these too",
    )
    p.set_defaults(fn=cmd_obs)

    p = sub.add_parser(
        "check",
        help="invariant-aware static analysis: wire-domain, determinism, "
        "concurrency, and obs-vocabulary passes over the tree",
        epilog="Findings are suppressed only by a reviewed per-line "
        "`# fedtpu: allow(<rule>): reason` pragma or an entry (with "
        "reason) in the repo-root ANALYSIS_BASELINE.json. Exit 0 = "
        "clean, 1 = non-baselined findings, 2 = usage/internal error.",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable result object instead of the finding list",
    )
    p.add_argument(
        "--baseline",
        help="baseline JSON path (default: ANALYSIS_BASELINE.json at the "
        "scanned root, when present)",
    )
    p.add_argument(
        "--root",
        help="tree to scan (default: this checkout's repo root) — the "
        "seeded-mutation self-tests point this at a temp copy",
    )
    p.add_argument(
        "--rules",
        help="comma-separated subset of rule names (default: all; see "
        "--list-rules)",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the registered rules and exit",
    )
    p.add_argument(
        "--prune-baseline",
        action="store_true",
        help="rewrite the baseline file minus STALE entries (findings "
        "that no longer fire) — the remediation path for the "
        "reported-not-failed stale list; live entries and the review "
        "comment survive untouched",
    )
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser(
        "registry",
        help="model registry operations: list | promote | rollback | gc",
    )
    p.add_argument("action", choices=["list", "promote", "rollback", "gc"])
    p.add_argument("--registry-dir", required=True)
    p.add_argument("--artifact", help="artifact id (promote)")
    p.add_argument(
        "--to",
        choices=["candidate", "shadow", "serving"],
        default=None,
        help="promotion target state (default: one rung up the "
        "candidate -> shadow -> serving ladder)",
    )
    p.add_argument(
        "--max-artifacts",
        type=int,
        default=None,
        help="gc: prune oldest retired/rejected artifacts until at most "
        "this many remain on disk; the serving artifact, its rollback "
        "chain, and live candidate/shadow artifacts are NEVER pruned "
        "(required for the gc action)",
    )
    p.set_defaults(fn=cmd_registry)

    p = sub.add_parser(
        "shadow",
        help="shadow evaluation plane: status | report — what is under "
        "live shadow evaluation and the paired disagreement evidence",
        epilog="Reads the registry directory only (the shadow pointer, "
        "the comparator's atomic status snapshot, and the paired-records "
        "JSONL under <registry>/shadow/) — works from any host that "
        "mounts it, like every other control-plane surface.",
    )
    p.add_argument("action", choices=["status", "report"])
    p.add_argument("--registry-dir", required=True)
    p.add_argument(
        "--artifact",
        help="report: this artifact's paired records (default: the "
        "artifact currently under shadow evaluation)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable output instead of the rendered summary",
    )
    p.set_defaults(fn=cmd_shadow)

    p = sub.add_parser(
        "labels",
        help="delayed ground-truth plane: ingest | status | report — "
        "append labeler verdicts to the journal and join them against "
        "what the models answered",
        epilog="Reads and appends under the registry directory only "
        "(<registry>/labels/journal.jsonl plus the shadow plane's "
        "paired records) — works from any host that mounts it, like "
        "every other control-plane surface.",
    )
    p.add_argument("action", choices=["ingest", "status", "report"])
    p.add_argument("--registry-dir", required=True)
    p.add_argument(
        "--journal",
        help="ground-truth journal override (default: "
        "<registry>/labels/journal.jsonl)",
    )
    p.add_argument(
        "--file",
        help='ingest: JSONL of {"rid", "label", "ts"} labeler records '
        "(missing ts falls back to --ts, then 0.0)",
    )
    p.add_argument("--rid", help="ingest: one request id")
    p.add_argument(
        "--label",
        type=int,
        default=None,
        help="ingest: the ground-truth class for --rid (0 = benign; "
        "any other class is an attack)",
    )
    p.add_argument(
        "--ts",
        type=float,
        default=None,
        help="ingest: labeler timestamp for records that carry none "
        "(last-writer-wins key; default 0.0)",
    )
    p.add_argument(
        "--watermark",
        type=float,
        default=None,
        help='ingest: advance the monotone "labels complete through T" '
        "watermark after applying the records",
    )
    p.add_argument(
        "--artifact",
        help="report: join this artifact's mirror pairs (default: the "
        "artifact currently under shadow evaluation)",
    )
    p.add_argument(
        "--scored",
        help="report: join a serving tier's scored-JSONL (infer-serve "
        "--scored-jsonl) instead of mirror pairs",
    )
    p.add_argument(
        "--threshold",
        type=float,
        default=0.5,
        help="decision threshold the join applies to each model's "
        "probability (default 0.5)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable output instead of the rendered summary",
    )
    p.set_defaults(fn=cmd_labels)

    p = sub.add_parser("distill", help="teacher -> student knowledge distillation")
    _add_common(p)
    p.add_argument("--teacher-layers", type=int, help="default: 2x student layers")
    p.add_argument(
        "--teacher-checkpoint",
        help="distill FROM this trained checkpoint (local or federated — "
        "e.g. a federated BERT fleet's aggregate) instead of training a "
        "fresh teacher; --pth + --hf-dir similarly supplies a "
        "reference-trained teacher",
    )
    p.add_argument(
        "--student-layers",
        type=int,
        help="student depth (default: the resolved model's) — e.g. distill "
        "a migrated 6-layer model into 3 layers",
    )
    p.add_argument("--distill-epochs", type=int, help="default: train epochs")
    p.add_argument("--temperature", type=float, help="KD softmax temperature")
    p.add_argument("--alpha", type=float, help="KD loss weight in [0,1]")
    p.add_argument(
        "--no-teacher-init",
        action="store_true",
        help="skip the every-other-layer student init",
    )
    p.add_argument("--checkpoint-dir")
    p.set_defaults(fn=cmd_distill)

    p = sub.add_parser(
        "export-hf",
        help="export a trained checkpoint to the HF DistilBERT layout "
        "(config.json + model.safetensors + vocab.txt)",
    )
    _add_common(p)
    # Not required: --pth + --hf-dir is the other valid weight source
    # (cmd_export_hf checks that exactly one is given at runtime).
    p.add_argument("--checkpoint-dir")
    p.add_argument("--out", required=True, help="output HF checkpoint dir")
    p.set_defaults(fn=cmd_export_hf)

    p = sub.add_parser("export-config", help="print the resolved config as JSON")
    _add_common(p)
    p.add_argument("--num-clients", type=int)
    p.add_argument("--rounds", type=int)
    p.set_defaults(fn=cmd_export_config)
    return ap


def main(argv: Sequence[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)

