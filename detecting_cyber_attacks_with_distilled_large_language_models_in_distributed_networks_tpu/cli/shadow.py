"""fedtpu shadow — the shadow evaluation plane's operator surface.

``status`` answers "what is under live shadow evaluation right now, and
how is it doing?" from the registry directory alone: the shadow pointer,
the comparator's latest atomic status snapshot, and the serving pointer
it is being measured against. ``report`` replays an artifact's paired-
records JSONL into the full disagreement picture (pairs, flips, score
movement, per-side histograms) — the evidence behind a gate verdict,
inspectable after the fact exactly like a registry event.
"""

from __future__ import annotations

import json

from ..utils.logging import get_logger

log = get_logger()


def _load_pairs(path: str) -> list[dict]:
    from ..shadow import PAIR_SCHEMA

    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail / foreign line
                if (
                    isinstance(rec, dict)
                    and rec.get("schema") == PAIR_SCHEMA
                ):
                    out.append(rec)
    except OSError:
        pass
    return out


def _resolve_artifact(args, registry) -> str | None:
    """--artifact wins; else the current shadow pointer's artifact."""
    aid = getattr(args, "artifact", None)
    if aid:
        return aid
    info = registry.shadow_info()
    return info.get("artifact") if info else None


def cmd_shadow(args) -> int:
    from ..registry import ModelRegistry, RegistryError
    from ..shadow import pairs_path, read_status

    registry = ModelRegistry(args.registry_dir)
    try:
        if args.action == "status":
            shadow = registry.shadow_info()
            serving = registry.serving_info()
            status = (
                read_status(args.registry_dir, shadow["artifact"])
                if shadow
                else None
            )
            if args.json:
                print(
                    json.dumps(
                        {
                            "shadow": shadow,
                            "serving": serving,
                            "status": status,
                        }
                    )
                )
                return 0
            if shadow is None:
                print("(nothing is under shadow evaluation)")
                if serving:
                    print(f"serving: {serving['artifact']}")
                return 0
            print(
                f"shadow artifact: {shadow['artifact']} "
                f"(round {shadow.get('round')})"
            )
            print(
                "serving incumbent: "
                + (serving["artifact"] if serving else "(none)")
            )
            if status is None:
                print("no comparator status yet (mirror not armed, or "
                      "no mirrored traffic)")
                return 0
            print(
                f"pairs {status.get('pairs', 0)}  flips "
                f"{status.get('flips', 0)}  flip_rate "
                f"{status.get('flip_rate', 0.0):.4f}  mean|dprob| "
                f"{status.get('mean_abs_dprob', 0.0):.4f}  psi "
                + (
                    f"{status['psi']:.4f}"
                    if status.get("psi") is not None
                    else "n/a"
                )
            )
            return 0
        if args.action == "report":
            aid = _resolve_artifact(args, registry)
            if aid is None:
                raise SystemExit(
                    "nothing under shadow evaluation and no --artifact "
                    "given — pass the artifact id whose paired records "
                    "to report"
                )
            pairs = _load_pairs(pairs_path(args.registry_dir, aid))
            status = read_status(args.registry_dir, aid)
            if args.json:
                print(
                    json.dumps(
                        {"artifact": aid, "status": status, "pairs": pairs}
                    )
                )
                return 0
            if not pairs and status is None:
                print(f"(no shadow evidence recorded for {aid})")
                return 1
            flips = sum(int(p.get("flip", 0)) for p in pairs)
            dsum = sum(
                abs(
                    float(p.get("serving_prob", 0.0))
                    - float(p.get("shadow_prob", 0.0))
                )
                for p in pairs
            )
            print(f"shadow report for {aid}:")
            print(
                f"  {len(pairs)} paired record(s), {flips} flip(s) "
                f"(rate {flips / len(pairs):.4f}), mean|dprob| "
                f"{dsum / len(pairs):.4f}"
                if pairs
                else "  (pairs JSONL empty; status snapshot only)"
            )
            if status is not None:
                print(
                    f"  status: pairs {status.get('pairs')}  psi "
                    + (
                        f"{status['psi']:.4f}"
                        if status.get("psi") is not None
                        else "n/a"
                    )
                    + f"  serving hist {status.get('hist_serving')}"
                    + f"  shadow hist {status.get('hist_shadow')}"
                )
            return 0
    except RegistryError as e:
        raise SystemExit(str(e)) from None
    raise SystemExit(f"unknown shadow action {args.action!r}")
