"""Timestamped phase logging.

The reference's entire observability story is ``print(f"... at
{datetime.now()}")`` begin/end brackets around every phase (e.g. reference
client1.py:85,92,97,115) — its golden terminal logs are the de-facto
benchmark record. This module keeps that phase-bracket shape (same
greppable begin/end lines) on top of structured ``logging``, and the phase
timer doubles as the profiling hook.
"""

from __future__ import annotations

import logging
import sys
import time
from contextlib import contextmanager
from datetime import datetime
from typing import Iterator

_FORMAT = "%(message)s"


def get_logger(name: str = "fedtpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def timestamp() -> str:
    return str(datetime.now())


@contextmanager
def phase(name: str, tag: str = "", logger: logging.Logger | None = None) -> Iterator[dict]:
    """Begin/end bracket with wall-clock duration, reference-log style::

        [CLIENT 0] Starting model training at 2026-07-29 ...
        [CLIENT 0] Finished model training at ... (12.3 s)

    Yields a dict; the measured duration lands in ``info['seconds']``.
    """
    log = logger or get_logger()
    prefix = f"[{tag}] " if tag else ""
    log.info(f"{prefix}Starting {name} at {timestamp()}")
    info: dict = {}
    t0 = time.perf_counter()
    try:
        yield info
    finally:
        info["seconds"] = time.perf_counter() - t0
        log.info(f"{prefix}Finished {name} at {timestamp()} ({info['seconds']:.2f} s)")
