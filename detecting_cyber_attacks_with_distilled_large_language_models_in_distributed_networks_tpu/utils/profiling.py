"""Profiling: analytic FLOPs, MFU accounting, and jax.profiler traces.

The reference's entire profiling story is timestamped ``print`` bracketing
plus tqdm rates (reference client1.py:85,92,97,115 and the golden terminal
logs, SURVEY.md §5) — there is no FLOPs or utilization accounting anywhere.
Here the model's step cost is computed analytically from the config, so any
timed step yields MFU against the local chip's peak (the BASELINE.json
north-star metric: ≥40% MFU on DistilBERT), and ``trace`` wraps
``jax.profiler`` for real TPU timelines (xprof/tensorboard).
"""

from __future__ import annotations

import contextlib
import re
from typing import Iterator

from ..config import ModelConfig

#: Peak dense bf16 matmul TFLOPs per CHIP by TPU generation (public specs;
#: the mental model follows jax-ml.github.io/scaling-book). Keys are matched
#: against ``jax.Device.device_kind`` strings like "TPU v4".
TPU_PEAK_TFLOPS: dict[str, float] = {
    "v2": 45.0,
    "v3": 123.0,
    "v4": 275.0,
    "v5e": 197.0,
    "v5 lite": 197.0,
    "v5litepod": 197.0,
    "v5p": 459.0,
    "v5": 459.0,
    "v6e": 918.0,
    "v6 lite": 918.0,
}


def forward_flops(
    cfg: ModelConfig, batch_size: int, seq_len: int | None = None
) -> float:
    """Analytic matmul FLOPs of one classifier forward pass.

    Counts every dense contraction (2·M·N·K per matmul): per transformer
    layer the Q/K/V/output projections (8·L·D²), the attention score and
    value contractions (4·L²·D), and the two FFN matmuls (4·L·D·F); plus the
    CLS head (2·D·C). Embedding gathers, layernorms, softmaxes, and biases
    are O(L·D) — negligible against the D² terms and excluded, which also
    matches how XLA's own cost model attributes transformer step cost.
    """
    L = seq_len if seq_len is not None else cfg.max_len
    D, F = cfg.dim, cfg.hidden_dim
    per_layer = 8 * L * D * D + 4 * L * L * D + 4 * L * D * F
    head = 2 * D * cfg.n_classes
    return float(batch_size) * (cfg.n_layers * per_layer + head)


def train_step_flops(
    cfg: ModelConfig, batch_size: int, seq_len: int | None = None
) -> float:
    """Forward + backward ≈ 3× forward (the backward pass contracts twice
    per forward matmul: grads w.r.t. activations and w.r.t. weights)."""
    return 3.0 * forward_flops(cfg, batch_size, seq_len)


def device_peak_flops(device=None) -> float | None:
    """Peak bf16 FLOPs/s of one device, or None when unknown (e.g. CPU).

    ``device`` defaults to ``jax.devices()[0]``.
    """
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or ""
    m = re.search(r"v\d+\s*(e|p|lite(pod)?)?", kind.lower())
    if not m:
        return None
    key = m.group(0).strip()
    tflops = TPU_PEAK_TFLOPS.get(key)
    if tflops is None:
        # "v5 litepod" etc. — retry with just the generation number.
        tflops = TPU_PEAK_TFLOPS.get(key.split()[0])
    return tflops * 1e12 if tflops is not None else None


def mfu(
    flops_per_step: float,
    step_time_s: float,
    n_devices: int = 1,
    peak_flops_per_device: float | None = None,
) -> float | None:
    """Model FLOPs utilization in [0, 1], or None when the peak is unknown."""
    if peak_flops_per_device is None:
        peak_flops_per_device = device_peak_flops()
    if peak_flops_per_device is None or step_time_s <= 0:
        return None
    return flops_per_step / (step_time_s * peak_flops_per_device * n_devices)


@contextlib.contextmanager
def trace(log_dir: str | None) -> Iterator[None]:
    """``jax.profiler.trace`` gated on ``log_dir`` — pass None for a no-op,
    so call sites need no branching (the CLI's --profile-dir plumbs here).
    View with xprof/tensorboard."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
