"""Shared loader for the repo's native C++ libraries (native/*.so).

One code path for every binding (comm/native.py wire byte-path,
data/native_tokenizer.py WordPiece encoder): lazily build via
native/build.py, load with ctypes, hand the CDLL to a configure callback
that declares argtypes/restypes, and cache the result — returning None
(pure-Python fallback) when no toolchain exists or anything fails.
"""

from __future__ import annotations

import ctypes
import os
from typing import Callable

_CACHE: dict[str, ctypes.CDLL | None] = {}


def repo_native_dir() -> str:
    # <repo>/<package>/utils/native.py -> <repo>/native
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), "native")


def load_native(
    src: str, soname: str, configure: Callable[[ctypes.CDLL], None]
) -> ctypes.CDLL | None:
    """Build (if stale) + load + configure ``native/<src>`` -> ``<soname>``.

    The first outcome — loaded library or None — is cached per soname;
    failures never raise (callers keep their pure-Python twin)."""
    if soname in _CACHE:
        return _CACHE[soname]
    lib: ctypes.CDLL | None = None
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            f"{soname}_build", os.path.join(repo_native_dir(), "build.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        so_path = mod.build_lib(src, soname)
        if so_path is not None:
            lib = ctypes.CDLL(so_path)
            configure(lib)
    except Exception:
        lib = None
    _CACHE[soname] = lib
    return lib
