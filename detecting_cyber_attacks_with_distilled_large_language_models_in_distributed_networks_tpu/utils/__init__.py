from .logging import get_logger, phase, timestamp  # noqa: F401
