"""fedtpu — TPU-native federated DDoS detection with distilled LLMs.

A brand-new JAX/XLA/pjit/Pallas framework with the capabilities of the reference
system ``Detecting_Cyber_Attacks_with_Distilled_Large_Language_Models_in_Distributed_Networks``
(three laptop processes shipping gzip-pickled PyTorch state dicts over hand-rolled
TCP — see reference client1.py / server.py):

* N federated clients fine-tune a DistilBERT binary DDoS classifier on per-client
  partitions of CICIDS2017 flow records rendered as English sentences.
* FedAvg weight aggregation between local-training phases is an XLA collective
  (mean over a ``clients`` mesh axis) — no server process, no serialization on
  the round path.
* Per-client local-vs-aggregated evaluation, metrics CSVs, plots,
  checkpoint/warm-start, fault-tolerant rounds, cross-host demo mode.

Import as::

    import detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu as fedtpu
"""

__version__ = "0.1.0"

from .config import (  # noqa: F401
    DataConfig,
    DistillConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
    ExperimentConfig,
)
