"""Command-line orchestration — the reference's three ``main()``s unified.

The reference's entry points are three scripts with hard-coded paths, ports,
seeds, and client count (reference client1.py:353-415, client2.py:332-392,
server.py:116-140); adding a client means copy-pasting a file. Here one CLI
covers every deployment shape, parameterized by client id / count:

  local       one client, train -> eval -> metrics CSV + plots
              (reference client1.py minus the sockets)
  federated   N clients on one TPU mesh: SPMD local epochs + pmean FedAvg,
              multi-round, checkpoint/resume (the TPU-native deployment)
  predict     batch inference: flow CSV -> per-row P(attack) CSV, from a
              local/federated checkpoint or a fine-tuned --hf-dir (the
              deployment step the reference never ships)
  distill     teacher -> student knowledge distillation (the recipe behind
              the reference's pre-distilled encoder)
  serve       TCP aggregation server (demo-parity mode, reference server.py)
  client      TCP client: train locally, exchange with a serve process,
              re-evaluate the aggregate (reference client1.py end-to-end)
  export-config   print the full default config as JSON (there is no config
                  file in the reference to copy from)

Config resolution: defaults <- --config JSON <- explicit flags.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Any, Sequence

import numpy as np

from .config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from .utils.logging import get_logger, phase

log = get_logger()


# ------------------------------------------------------------------ config
def _preset_model(preset: str, vocab_size: int) -> ModelConfig:
    if preset == "tiny":
        return ModelConfig.tiny(vocab_size=vocab_size)
    if preset == "distilbert":
        return ModelConfig(vocab_size=vocab_size)
    if preset == "bert":
        return ModelConfig.bert_base(vocab_size=vocab_size)
    if preset == "bert-large":
        return ModelConfig.bert_large(vocab_size=vocab_size)
    raise SystemExit(
        f"unknown --preset {preset!r} (tiny|distilbert|bert|bert-large)"
    )


def resolve_config(args: argparse.Namespace, *, vocab_size: int) -> ExperimentConfig:
    """defaults <- --config file <- flags."""
    if getattr(args, "config", None):
        with open(args.config) as f:
            cfg = ExperimentConfig.from_dict(json.load(f))
    else:
        preset = getattr(args, "preset", "tiny")
        model = _preset_model(preset, vocab_size)
        cfg = ExperimentConfig(
            model=model,
            data=DataConfig(max_len=model.max_len),
        )

    model_kw: dict[str, Any] = {}
    if getattr(args, "max_len", None):
        model_kw.update(max_len=args.max_len)
    if getattr(args, "gelu", None):
        model_kw.update(gelu=args.gelu)
    new_model = cfg.model.replace(**model_kw) if model_kw else cfg.model

    # model and data must change together: ExperimentConfig.__post_init__
    # checks data.max_len == model.max_len on every replace.
    data_kw: dict[str, Any] = {"max_len": new_model.max_len}
    if getattr(args, "dataset", None):
        data_kw.update(dataset=args.dataset)
    if getattr(args, "batch_size", None):
        data_kw.update(batch_size=args.batch_size, eval_batch_size=args.batch_size)
    if getattr(args, "data_fraction", None):
        data_kw.update(data_fraction=args.data_fraction)
    if getattr(args, "partition", None):
        data_kw.update(partition=args.partition)
    if getattr(args, "dirichlet_alpha", None) is not None:
        # Explicit 0 must reach DataConfig's own validation, not silently
        # fall back to the default.
        data_kw.update(dirichlet_alpha=args.dirichlet_alpha)
    cfg = dataclasses.replace(
        cfg, model=new_model, data=dataclasses.replace(cfg.data, **data_kw)
    )

    train_kw: dict[str, Any] = {}
    if getattr(args, "epochs", None):
        train_kw.update(epochs_per_round=args.epochs)
    if getattr(args, "learning_rate", None):
        train_kw.update(learning_rate=args.learning_rate)
    if getattr(args, "warmup_steps", None) is not None:
        train_kw.update(warmup_steps=args.warmup_steps)
    if getattr(args, "seed", None) is not None:
        train_kw.update(seed=args.seed)
    if train_kw:
        cfg = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, **train_kw))

    if hasattr(args, "num_clients"):
        n = args.num_clients or cfg.fed.num_clients
        participation = (
            cfg.fed.participation
            if getattr(args, "participation", None) is None
            else args.participation
        )
        # --participation implies the survivor floor can't exceed the
        # sampling rate; clamp ONLY the untouched default floor so an
        # explicitly configured floor still collides loudly in FedConfig
        # validation instead of being silently weakened.
        min_frac = cfg.fed.min_client_fraction
        if participation < min_frac and min_frac == FedConfig().min_client_fraction:
            min_frac = participation
        cfg = dataclasses.replace(
            cfg,
            fed=dataclasses.replace(
                cfg.fed,
                num_clients=n,
                rounds=getattr(args, "rounds", None) or cfg.fed.rounds,
                weighted=(
                    True
                    if getattr(args, "weighted", False)
                    else False
                    if getattr(args, "unweighted", False)
                    else cfg.fed.weighted
                ),
                prox_mu=(
                    cfg.fed.prox_mu
                    if getattr(args, "prox_mu", None) is None
                    else args.prox_mu
                ),
                participation=participation,
                min_client_fraction=min_frac,
                dp_clip=(
                    cfg.fed.dp_clip
                    if getattr(args, "dp_clip", None) is None
                    else args.dp_clip
                ),
                dp_noise_multiplier=(
                    cfg.fed.dp_noise_multiplier
                    if getattr(args, "dp_noise_multiplier", None) is None
                    else args.dp_noise_multiplier
                ),
                server_opt=getattr(args, "server_opt", None) or cfg.fed.server_opt,
                server_lr=(
                    cfg.fed.server_lr
                    if getattr(args, "server_lr", None) is None
                    else args.server_lr
                ),
                server_momentum=(
                    cfg.fed.server_momentum
                    if getattr(args, "server_momentum", None) is None
                    else args.server_momentum
                ),
            ),
            mesh=MeshConfig(
                clients=n, data=getattr(args, "data_parallel", None) or cfg.mesh.data
            ),
        )
    if getattr(args, "output_dir", None):
        cfg = dataclasses.replace(cfg, output_dir=args.output_dir)
    if getattr(args, "checkpoint_dir", None):
        cfg = dataclasses.replace(cfg, checkpoint_dir=args.checkpoint_dir)
    return cfg


# --------------------------------------------------------------- pretrained
def _resolve_with_pretrained(args, *, load_weights: bool = True):
    """(tokenizer, resolved config, initial params or None).

    ``load_weights=False`` skips the (full) HF/.pth weight load while still
    resolving tokenizer + architecture from ``--hf-dir`` — for callers
    whose weights come from elsewhere (e.g. distill --teacher-checkpoint).

    With ``--hf-dir`` (the reference's required ``./distilbert-base-uncased``
    directory, client1.py:357,360-361): vocab from its ``vocab.txt``,
    architecture from its ``config.json``, initial encoder weights from its
    checkpoint (fresh head, as at reference client1.py:58). Without it:
    the domain tokenizer and random init.
    """
    hf_dir = getattr(args, "hf_dir", None)
    if getattr(args, "pth", None) and not hf_dir:
        raise SystemExit(
            "--pth needs --hf-dir alongside it: the .pth holds only weights; "
            "the tokenizer and architecture come from the HF checkpoint dir "
            "(the reference requires the same directory, client1.py:357)"
        )
    if not hf_dir:
        from .data import default_tokenizer

        tok = default_tokenizer()
        return tok, resolve_config(args, vocab_size=len(tok.vocab)), None

    import copy

    from .data import WordPieceTokenizer
    from .models.hf_convert import config_from_hf_dir, load_hf_dir

    tok = WordPieceTokenizer.from_vocab_file(os.path.join(hf_dir, "vocab.txt"))
    # Resolve WITHOUT --max-len: the preset model this produces is discarded
    # below, and validating the flag against its (irrelevant) position table
    # would reject lengths the checkpoint actually supports.
    args_sans_len = copy.copy(args)
    args_sans_len.max_len = None
    cfg = resolve_config(args_sans_len, vocab_size=len(tok.vocab))
    # Architecture comes from the checkpoint; every non-architecture knob
    # (dtypes, dropouts, attention impl, head size) carries over from the
    # resolved config so --config files keep working under --hf-dir.
    # Sequence length defaults to min(128, the checkpoint's position table)
    # — the reference's 128 (client1.py:27) — unless --max-len says else.
    m = cfg.model
    overrides: dict[str, Any] = dict(
        dropout=m.dropout,
        attention_dropout=m.attention_dropout,
        head_dropout=m.head_dropout,
        n_classes=m.n_classes,
        compute_dtype=m.compute_dtype,
        param_dtype=m.param_dtype,
        attention_impl=m.attention_impl,
        ring_axis=m.ring_axis,
        remat=m.remat,
    )
    # Activation precedence: --gelu flag > --config file's model section >
    # the checkpoint's declared activation (config.json) > library default.
    # The config file only wins when it actually SAYS gelu — a file saved
    # before the field existed must not inject today's library default over
    # the checkpoint's declared activation (same legacy rule as
    # ExperimentConfig.from_checkpoint_dict).
    if getattr(args, "gelu", None):
        overrides["gelu"] = args.gelu
    elif getattr(args, "config", None):
        with open(args.config) as f:
            if "gelu" in json.load(f).get("model", {}):
                overrides["gelu"] = m.gelu
    if getattr(args, "max_len", None):
        overrides["max_len"] = args.max_len
    model_cfg = config_from_hf_dir(hf_dir, **overrides)
    if len(tok.vocab) != model_cfg.vocab_size:
        raise SystemExit(
            f"--hf-dir vocab.txt has {len(tok.vocab)} entries but config.json "
            f"says vocab_size={model_cfg.vocab_size}"
        )
    cfg = dataclasses.replace(
        cfg,
        model=model_cfg,
        data=dataclasses.replace(cfg.data, max_len=model_cfg.max_len),
    )
    if not load_weights:
        return tok, cfg, None
    if getattr(args, "pth", None):
        # The reference's own trained artifact: --hf-dir supplies the
        # tokenizer + architecture (exactly as the reference requires that
        # directory, client1.py:56,357), the .pth supplies the weights —
        # mirroring its DDoSClassifier(path) + load_state_dict flow
        # (client1.py:374-377).
        from .models.hf_convert import load_reference_pth

        with phase(f"loading reference .pth {args.pth}", tag="MODEL"):
            try:
                params = load_reference_pth(args.pth, model_cfg)
            except Exception as e:
                # KeyError = architecture mismatch vs --hf-dir's config.json,
                # FileNotFoundError = bad path, ValueError = headless dict —
                # all operator errors, none deserving a raw traceback.
                raise SystemExit(
                    f"--pth {args.pth}: {type(e).__name__}: {e} — expected "
                    "the reference's DDoSClassifier state dict matching "
                    "--hf-dir's architecture (client1.py:53-58,388)"
                ) from None
        return tok, cfg, params
    with phase(f"loading HF checkpoint {hf_dir}", tag="MODEL"):
        params, _ = load_hf_dir(
            hf_dir, cfg=model_cfg, head_rng=np.random.default_rng(cfg.train.seed)
        )
    return tok, cfg, params


# -------------------------------------------------------------------- data
def _load_client_splits(args, cfg: ExperimentConfig, num_clients: int):
    """CSV / mixed corpus / synthetic -> per-client text splits (host-side
    pandas/numpy only; tokenization is a separate phase so multi-host
    processes can tokenize just their own clients)."""
    from .data import (
        load_flow_csv,
        load_mixed_corpus,
        make_all_client_splits,
        make_all_client_splits_from_corpus,
        make_synthetic,
        parse_source_arg,
    )

    if getattr(args, "source", None):
        if getattr(args, "csv", None):
            raise SystemExit("--csv and --source are mutually exclusive")
        # --dataset pins the schema for unprefixed --source entries; entries
        # without either fall back to schema auto-detection.
        default_name = getattr(args, "dataset", None)
        entries = [
            (name or default_name, path)
            for name, path in map(parse_source_arg, args.source)
        ]
        with phase(f"loading {len(entries)}-source mixed corpus", tag="DATA"):
            corpus = load_mixed_corpus(entries)
        with phase("partition/split", tag="DATA"):
            return make_all_client_splits_from_corpus(corpus, num_clients, cfg.data)
    if getattr(args, "csv", None):
        with phase(f"loading {args.csv}", tag="DATA"):
            df = load_flow_csv(args.csv)
    else:
        n = getattr(args, "synthetic", None) or 2400
        with phase(f"generating {n} synthetic {cfg.data.dataset} flows", tag="DATA"):
            df = make_synthetic(cfg.data.dataset, n, seed=cfg.data.seed_base)
    with phase("partition/split", tag="DATA"):
        return make_all_client_splits(df, num_clients, cfg.data)


def _load_clients(args, cfg: ExperimentConfig, tok, num_clients: int):
    """Full path: text splits -> tokenized static-shape arrays, all clients."""
    from .data import tokenize_client

    if getattr(args, "stream", False):
        if not getattr(args, "csv", None):
            raise SystemExit("--stream needs --csv (chunked two-pass reader)")
        from .data import stream_client_tokens

        with phase(f"streaming {args.csv}", tag="DATA"):
            return stream_client_tokens(
                args.csv, cfg.data, num_clients, tok, max_len=cfg.model.max_len
            )
    splits = _load_client_splits(args, cfg, num_clients)
    with phase("tokenize", tag="DATA"):
        return [tokenize_client(s, tok, max_len=cfg.model.max_len) for s in splits]


# --------------------------------------------------------------- reporting
def _write_reports(
    client_id: int,
    local: dict,
    aggregated: dict | None,
    output_dir: str,
) -> None:
    """The reference's per-client artifact set: one-row metrics CSVs named
    ``client{N}_{local,aggregated}_metrics.csv`` (client1.py:386,401) and the
    plot set under ``client{N}_plots/`` (client1.py:153-225)."""
    from . import reporting

    os.makedirs(output_dir, exist_ok=True)
    reporting.save_metrics(
        local, os.path.join(output_dir, f"client{client_id}_local_metrics.csv")
    )
    if aggregated is not None:
        reporting.save_metrics(
            aggregated,
            os.path.join(output_dir, f"client{client_id}_aggregated_metrics.csv"),
        )
    written = reporting.plot_evaluation(
        local,
        aggregated,
        os.path.join(output_dir, f"client{client_id}_plots"),
        client_id=client_id,
    )
    log.info(
        f"[CLIENT {client_id}] wrote metrics CSVs and {len(written)} plots "
        f"under {output_dir}"
    )


# ---------------------------------------------------------------- commands
def cmd_local(args) -> int:
    from .train.engine import Trainer

    tok, cfg, pretrained = _resolve_with_pretrained(args)
    client = _load_clients(args, cfg, tok, max(args.client_id + 1, 1))[args.client_id]
    trainer = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
    state = trainer.init_state(params=pretrained)
    from .utils.profiling import trace

    with phase(f"client {args.client_id} local training", tag="TRAIN"), trace(
        getattr(args, "profile_dir", None)
    ):
        state, losses = trainer.fit(
            state,
            client.train,
            batch_size=cfg.data.batch_size,
            tag=f"[CLIENT {args.client_id}] ",
        )
    with phase("validation evaluation", tag="EVAL"):
        val = trainer.evaluate(state.params, client.val, batch_size=cfg.data.eval_batch_size)
    with phase("test evaluation", tag="EVAL"):
        test = trainer.evaluate(state.params, client.test, batch_size=cfg.data.eval_batch_size)
    log.info(
        f"[CLIENT {args.client_id}] val acc {val['Accuracy']:.4f} | "
        f"test acc {test['Accuracy']:.4f} f1 {test['F1-Score']:.4f}"
    )
    if getattr(args, "metrics_jsonl", None):
        from .reporting import append_metrics_jsonl

        for phase_name, m in (("val", val), ("test", test)):
            append_metrics_jsonl(
                args.metrics_jsonl,
                {"client": args.client_id, "phase": phase_name, **m},
            )
    _write_reports(args.client_id, test, None, cfg.output_dir)
    if cfg.checkpoint_dir:
        from .train.checkpoint import Checkpointer

        with Checkpointer(cfg.checkpoint_dir) as ckpt:
            ckpt.save(
                int(state.step),
                state,
                meta={
                    "client_id": args.client_id,
                    "kind": "local",
                    "config": cfg.to_dict(),
                },
            )
            ckpt.wait()
    return 0


def cmd_federated(args) -> int:
    import jax

    from .data import stack_clients_ragged, tokenize_client
    from .train.federated import FederatedTrainer

    # Multi-host bootstrap must precede the first backend touch
    # (jax.devices()/process_count()); config resolution and data loading
    # are backend-free so their order doesn't matter.
    mesh = None
    local_sl = None
    # multihost.initialize owns ALL the configuration logic (flag/env
    # resolution, single-process no-op, TPU-pod autodetect); the CLI only
    # converts its failures into actionable messages.
    from .parallel.multihost import initialize

    try:
        initialize(
            getattr(args, "coordinator", None),
            getattr(args, "num_processes", None),
            getattr(args, "process_id", None),
        )
    except Exception as e:
        raise SystemExit(
            f"multi-host bootstrap failed: {e}\n"
            "Pass --coordinator HOST:PORT --num-processes N --process-id I "
            "together (every process the same coordinator), or none of them "
            "on a platform where jax.distributed autodetects."
        )

    # Fail fast on an unfittable data axis — knowable from argv + device
    # count alone, before any (potentially large) HF checkpoint load.
    # Client-axis fitting itself lives in FederatedTrainer (replica
    # stacking), serving library callers too.
    if (
        jax.process_count() == 1
        and getattr(args, "data_parallel", None)
        and args.data_parallel > len(jax.devices())
    ):
        raise SystemExit(
            f"--data-parallel {args.data_parallel} exceeds the "
            f"{len(jax.devices())} available devices"
        )

    tok, cfg, pretrained = _resolve_with_pretrained(args)
    C = cfg.fed.num_clients
    if jax.process_count() > 1:
        from .parallel.multihost import local_client_slice, make_global_mesh

        if C != cfg.mesh.clients:
            raise SystemExit(
                f"multi-host runs need one mesh row per client "
                f"(num_clients={C}, mesh.clients={cfg.mesh.clients})"
            )
        mesh = make_global_mesh(
            cfg.mesh.clients, cfg.mesh.data, axis_names=cfg.mesh.axis_names
        )
        local_sl = local_client_slice(mesh)
        log.info(
            f"[FED] process {jax.process_index()}/{jax.process_count()} owns "
            f"clients [{local_sl.start}, {local_sl.stop})"
        )

    if getattr(args, "stream", False):
        if local_sl is not None:
            raise SystemExit(
                "--stream is single-host for now (multi-host feeds need "
                "per-host client slicing of the streamed plan)"
            )
        clients = _load_clients(args, cfg, tok, C)
        eval_rows_global = max(len(c.test) for c in clients)
        val_rows_global = max(len(c.val) for c in clients)
        train_sizes = [len(c.train) for c in clients]
    else:
        # Partitioning runs over the full fleet on every host (it must be
        # globally consistent); tokenization — the host-side hot loop — runs
        # only for this process's clients. Global row counts for the stacked
        # train/eval feeds come from the (cheap) split lengths, so every host
        # agrees on batch counts without seeing other hosts' token arrays.
        splits = _load_client_splits(args, cfg, C)
        local_ids = (
            range(C) if local_sl is None else range(local_sl.start, local_sl.stop)
        )
        with phase(f"tokenize clients {list(local_ids)}", tag="DATA"):
            clients = [
                tokenize_client(splits[c], tok, max_len=cfg.model.max_len)
                for c in local_ids
            ]
        eval_rows_global = max(len(s.test) for s in splits)
        val_rows_global = max(len(s.val) for s in splits)
        train_sizes = [len(s.train) for s in splits]
    # Ragged stack to the GLOBAL fleet-max row count: no client's rows are
    # truncated (the reference's N independent processes each train on all
    # their own samples), and every host agrees on the stacked shape.
    stacked_train = stack_clients_ragged(
        [c.train for c in clients],
        pad_id=tok.pad_id,
        target_rows=max(train_sizes),
    )
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id, mesh=mesh)

    ckpt = None
    start_round = 0
    state = trainer.init_state(params=pretrained)
    if cfg.checkpoint_dir:
        # Works multi-host too: every process participates in save/restore
        # (orbax coordinates through the jax.distributed runtime; the state
        # template carries the global shardings).
        from .train.checkpoint import Checkpointer, maybe_warm_start

        restored, step = maybe_warm_start(cfg.checkpoint_dir, state)
        if restored is not None:
            state, start_round = restored, int(step)
            log.info(f"[FED] resumed from round {start_round}")
            # Checkpoints are written BEFORE the per-round optimizer reset
            # (cmd loop below); apply the reset a continuous run would have
            # done so the resumed trajectory matches it exactly.
            if start_round < cfg.fed.rounds and cfg.fed.reset_optimizer_each_round:
                state = trainer.reset_optimizer(state)
        ckpt = Checkpointer(cfg.checkpoint_dir)

    # FedAvg weights are the GLOBAL per-client sample counts (known from the
    # cheap split phase on every host, reference semantics: weight by data).
    # weighted=None (the default) auto-weights; --unweighted forces the
    # reference's literal uniform mean.
    weights = (
        np.array(train_sizes, np.float64) if cfg.fed.resolve_weighted() else None
    )
    # Under a uniform mean (--unweighted, or DP's forced uniform), zero-row
    # clients would average their never-trained round-start params in with
    # full 1/C weight; mask them out as permanently dropped clients (same
    # rule as FederatedTrainer.run). train_sizes is global, so every host
    # builds the identical mask.
    base_mask = None
    if weights is None:
        empty = np.asarray(train_sizes) == 0
        if empty.any():
            base_mask = (~empty).astype(np.float64)
            log.warning(
                f"[FED] clients {np.flatnonzero(empty).tolist()} have zero "
                "train rows; excluding them from the uniform mean"
            )
    from .utils.profiling import trace

    prepared = trainer.prepare_eval(
        [c.test for c in clients], target_rows=eval_rows_global
    )
    # Validation metrics every phase, like the reference (it evaluates val
    # AND test at each of local/aggregated, client1.py:383-385,398-400).
    prepared_val = trainer.prepare_eval(
        [c.val for c in clients], target_rows=val_rows_global
    )
    history = []
    with trace(getattr(args, "profile_dir", None)):
        for r in range(start_round, cfg.fed.rounds):
            anchor = trainer.round_anchor(state)
            with phase(f"round {r + 1}/{cfg.fed.rounds}", tag="FED"):
                state, losses = trainer.fit_local(
                    state, stacked_train, epoch_offset=r * cfg.train.epochs_per_round
                )
                local_val = trainer.evaluate_clients(
                    state.params, prepared=prepared_val
                )
                local = trainer.evaluate_clients(state.params, prepared=prepared)
                mask = trainer.participation_mask(r)
                if base_mask is not None:
                    mask = base_mask if mask is None else mask * base_mask
                state = trainer.aggregate(
                    state,
                    weights=weights,
                    client_mask=mask,
                    anchor=anchor,
                    round_index=r,
                )
                aggregated_val = trainer.evaluate_clients(
                    state.params, prepared=prepared_val
                )
                aggregated = trainer.evaluate_clients(state.params, prepared=prepared)
            history.append((r, local, aggregated))
            for c in range(C):
                log.info(
                    f"[FED] round {r + 1} client {c}: local val/test acc "
                    f"{local_val[c]['Accuracy']:.4f}/{local[c]['Accuracy']:.4f}"
                    f" -> aggregated "
                    f"{aggregated_val[c]['Accuracy']:.4f}/"
                    f"{aggregated[c]['Accuracy']:.4f}"
                )
            if getattr(args, "metrics_jsonl", None) and jax.process_index() == 0:
                from .reporting import append_metrics_jsonl

                for c in range(C):
                    for phase_name, split_name, m in (
                        ("local", "val", local_val[c]),
                        ("local", "test", local[c]),
                        ("aggregated", "val", aggregated_val[c]),
                        ("aggregated", "test", aggregated[c]),
                    ):
                        append_metrics_jsonl(
                            args.metrics_jsonl,
                            {
                                "round": r + 1,
                                "client": c,
                                "phase": phase_name,
                                "split": split_name,
                                **m,
                            },
                        )
            if ckpt is not None:
                ckpt.save(
                    r + 1,
                    state,
                    meta={
                        "round": r + 1,
                        "kind": "federated",
                        "config": cfg.to_dict(),
                    },
                )
            if r + 1 < cfg.fed.rounds and cfg.fed.reset_optimizer_each_round:
                state = trainer.reset_optimizer(state)
    if ckpt is not None:
        ckpt.wait()
        ckpt.close()

    if cfg.fed.dp_clip > 0.0 and cfg.fed.dp_noise_multiplier > 0.0:
        from .parallel.dp import dp_epsilon

        # Only the rounds executed THIS launch are known to have run under
        # this DP config; a resumed checkpoint's earlier rounds may have
        # been trained without noise, so the guarantee must not cover them.
        dp_rounds = cfg.fed.rounds - start_round
        eps = dp_epsilon(dp_rounds, cfg.fed.dp_noise_multiplier, 1e-5)
        caveat = (
            ""
            if start_round == 0
            else (
                f" — covers rounds {start_round + 1}..{cfg.fed.rounds} only; "
                f"the {start_round} resumed round(s) carry whatever DP "
                "config they were run with"
            )
        )
        log.info(
            f"[DP] client-level guarantee for {dp_rounds} round(s): "
            f"({eps:.3g}, 1e-05)-DP "
            f"(clip {cfg.fed.dp_clip}, noise x{cfg.fed.dp_noise_multiplier})"
            f"{caveat}"
        )

    # Final reporting with probs for ROC/PR curves. Under multi-host the
    # per-example probs live on their owning hosts; the metric counts are
    # replicated everywhere, so process 0 writes prob-free reports for all.
    final_local = history[-1][1] if history else None
    multihost = jax.process_count() > 1
    final_agg = trainer.evaluate_clients(
        state.params, prepared=prepared, collect_probs=not multihost
    )
    if not multihost or jax.process_index() == 0:
        if final_local is None:
            # No round trained this launch (e.g. relaunching a completed
            # checkpointed run): there ARE no local-model metrics — write
            # aggregated artifacts only rather than mislabeling.
            from . import reporting

            log.info(
                "[FED] all rounds already complete; writing aggregated "
                "reports only"
            )
            os.makedirs(cfg.output_dir, exist_ok=True)
            for c in range(C):
                reporting.save_metrics(
                    final_agg[c],
                    os.path.join(
                        cfg.output_dir, f"client{c}_aggregated_metrics.csv"
                    ),
                )
        else:
            for c in range(C):
                _write_reports(c, final_local[c], final_agg[c], cfg.output_dir)
    return 0


def _auth_key() -> bytes | None:
    """Shared-secret HMAC key for the TCP demo-parity mode, from the
    FEDTPU_SECRET env var (never argv — process listings leak flags). The
    reference's protocol accepts weights from anyone who can connect
    (server.py:57-65); with a secret set, unauthenticated or tampered
    messages are rejected."""
    secret = os.environ.get("FEDTPU_SECRET")
    return secret.encode() if secret else None


def _mask_secret(enabled: bool) -> bytes | None:
    """Pairwise-mask secret for secure aggregation (comm/secure.py), from
    the FEDTPU_MASK_SECRET env var. Shared among CLIENTS ONLY — the server
    must not hold it, or it could unmask individual uploads."""
    if not enabled:
        return None
    secret = os.environ.get("FEDTPU_MASK_SECRET")
    if not secret:
        raise SystemExit(
            "--secure-agg needs FEDTPU_MASK_SECRET set (same value on every "
            "client; NOT on the server)"
        )
    return secret.encode()


def cmd_serve(args) -> int:
    from .comm import AggregationServer

    with AggregationServer(
        host=args.host,
        port=args.port,
        num_clients=args.num_clients,
        weighted=args.weighted,
        min_clients=args.min_clients,
        timeout=args.timeout,
        compression=args.compression,
        auth_key=_auth_key(),
        secure_agg=bool(getattr(args, "secure_agg", False)),
    ) as server:
        log.info(f"[SERVER] listening on {args.host}:{server.port}")
        server.serve(rounds=args.rounds or 1)
    return 0


def cmd_client(args) -> int:
    """The reference client1.py end-to-end: (warm start ->) train -> eval ->
    exchange over TCP -> load aggregate -> re-eval -> CSVs + plots; degrades
    to local-only reports when the exchange fails (client1.py:405-410).

    ``--checkpoint-dir`` is the reference's ``client{N}_model.pth`` pattern
    (save after local training and after applying the aggregate, auto-load
    on the next launch, client1.py:375-377,388,403 — its only multi-round
    mechanism), upgraded to full Orbax state. ``--rounds R`` runs the
    re-launch loop in-process instead (the server must be serving at least
    as many rounds)."""
    from .comm import FederatedClient, SecureAggError
    from .train.engine import Trainer

    tok, cfg, pretrained = _resolve_with_pretrained(args)
    client_data = _load_clients(args, cfg, tok, cfg.fed.num_clients)[args.client_id]
    trainer = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
    state = trainer.init_state(params=pretrained)
    ckpt = None
    if cfg.checkpoint_dir:
        from .train.checkpoint import Checkpointer, maybe_warm_start

        restored, step = maybe_warm_start(cfg.checkpoint_dir, state)
        if restored is not None:
            state = restored
            log.info(
                f"[CLIENT {args.client_id}] warm start from "
                f"{cfg.checkpoint_dir} (step {step})"
            )
        ckpt = Checkpointer(cfg.checkpoint_dir)

    import jax

    fed = FederatedClient(
        args.host, args.port, client_id=args.client_id,
        timeout=args.timeout, compression=args.compression,
        auth_key=_auth_key(),
        secure_secret=_mask_secret(getattr(args, "secure_agg", False)),
        num_clients=cfg.fed.num_clients,
    )
    import jax.numpy as jnp

    rounds = max(1, getattr(args, "rounds", None) or 1)
    local = agg_metrics = None
    E = cfg.train.epochs_per_round
    # Orbax step ids must be unique and increasing, and a duplicate save is
    # SILENTLY skipped — two saves per round (post-train, post-aggregate)
    # need their own sequence, seeded past the previous run's ids on warm
    # start (state.step alone can lag them).
    save_seq = int(state.step)
    if ckpt is not None:
        save_seq = max(save_seq, ckpt.latest_step() or 0)
    for r in range(rounds):
        with phase(f"client {args.client_id} round {r + 1}/{rounds} training", tag="TRAIN"):
            state, _ = trainer.fit(
                state, client_data.train, batch_size=cfg.data.batch_size,
                epoch_offset=r * E, tag=f"[CLIENT {args.client_id}] ",
            )
        local = trainer.evaluate(state.params, client_data.test)
        if ckpt is not None:
            # Post-train save — the reference's client1.py:388.
            save_seq += 1
            ckpt.save(
                save_seq,
                state,
                meta={
                    "client_id": args.client_id,
                    "kind": "local",
                    "config": cfg.to_dict(),
                },
            )
        host_params = jax.tree.map(np.asarray, state.params)
        try:
            with phase("federated exchange", tag="COMM"):
                aggregated = fed.exchange(
                    host_params, n_samples=len(client_data.train)
                )
            with phase("aggregated evaluation", tag="EVAL"):
                agg_metrics = trainer.evaluate(aggregated, client_data.test)
            log.info(
                f"[CLIENT {args.client_id}] round {r + 1}: local acc "
                f"{local['Accuracy']:.4f} -> aggregated acc "
                f"{agg_metrics['Accuracy']:.4f}"
            )
            if getattr(args, "metrics_jsonl", None):
                from .reporting import append_metrics_jsonl

                for phase_name, m in (("local", local), ("aggregated", agg_metrics)):
                    append_metrics_jsonl(
                        args.metrics_jsonl,
                        {
                            "round": r + 1,
                            "client": args.client_id,
                            "phase": phase_name,
                            **m,
                        },
                    )
            # Continue the next round FROM the aggregate with a fresh Adam
            # (every reference re-launch constructs a new optimizer,
            # client1.py:380) but a continuing step counter (LR warmup).
            trained_steps = int(state.step)
            state = trainer.init_state(params=aggregated)
            state = state._replace(step=jnp.asarray(trained_steps, jnp.int32))
            if ckpt is not None:
                # Post-aggregate save — the reference's client1.py:403.
                save_seq += 1
                ckpt.save(
                    save_seq,
                    state,
                    meta={
                        "client_id": args.client_id,
                        "kind": "local",
                        "config": cfg.to_dict(),
                        "aggregated": True,
                    },
                )
        except (ConnectionError, OSError, SecureAggError) as e:
            agg_metrics = None
            log.info(
                f"[CLIENT {args.client_id}] round {r + 1} exchange failed "
                f"({e}); local-only reports"
            )
            break
    if ckpt is not None:
        ckpt.wait()
        ckpt.close()
    _write_reports(args.client_id, local, agg_metrics, cfg.output_dir)
    return 0


def _restore_predict_params(cfg, tok, trainer, *, ckpt_dir=None):
    """Trained weights for inference from a checkpoint directory
    (``cfg.checkpoint_dir`` unless ``ckpt_dir`` overrides — distill's
    teacher restore points elsewhere).

    Understands both checkpoint flavors: a ``local``/``client`` TrainState
    (restored against this trainer's template, or the checkpoint's own
    recorded config when present) and a ``federated`` FedState (recognized
    by its metadata; restored on the mesh and collapsed to client 0's
    replica — post-aggregation all replicas are identical). Returns
    ``(model_cfg, params)``; raises instead of silently predicting from
    random weights."""
    from .train.checkpoint import Checkpointer

    ckpt_dir = cfg.checkpoint_dir if ckpt_dir is None else ckpt_dir
    if not os.path.isdir(ckpt_dir):
        # Read-only path: don't let the manager create a directory at a
        # mistyped location (it would later masquerade as a real run dir).
        raise SystemExit(f"checkpoint dir {ckpt_dir} does not exist")
    with Checkpointer(ckpt_dir) as ckpt:
        step = ckpt.latest_step()
        if step is None:
            raise SystemExit(f"no checkpoint found in {ckpt_dir}")
        meta = ckpt.restore_meta(step=step)
        import jax

        # "kind" discriminates local TrainState vs federated FedState
        # checkpoints; older federated checkpoints predate it but always
        # carried "round".
        is_fed = (
            meta.get("kind") == "federated" if "kind" in meta else "round" in meta
        )
        if is_fed:
            from .train.federated import FederatedTrainer

            fed_cfg = ExperimentConfig.from_checkpoint_dict(meta["config"])
            if fed_cfg.model.vocab_size != cfg.model.vocab_size:
                raise SystemExit(
                    f"checkpoint model vocab ({fed_cfg.model.vocab_size}) != "
                    f"tokenizer vocab ({cfg.model.vocab_size}); pass the "
                    "matching --hf-dir / vocab"
                )
            ftr = FederatedTrainer(fed_cfg, pad_id=tok.pad_id)
            # Abstract template + params-only restore: never materializes
            # the C-stacked Adam moments (3x C model copies for a fleet
            # checkpoint); only the [C, ...] params land, and replica 0 is
            # the global model (FedAvg replicates its output).
            template = jax.eval_shape(lambda: ftr.init_state(seed=0))
            stacked = ckpt.restore_params(template, step=step)
            params = jax.tree.map(lambda x: np.asarray(x)[0], stacked)
            log.info(
                f"[PREDICT] restored federated checkpoint (round "
                f"{meta.get('round', '?')}, {fed_cfg.fed.num_clients} clients)"
            )
            return fed_cfg.model, params
        # Without recorded config (legacy checkpoints) the caller's trainer
        # IS the architecture claim — return ITS config, not cfg.model
        # (distill passes a deeper-than-student teacher template here).
        model_cfg = trainer.model_cfg
        if "config" in meta:
            # Trust the checkpoint's recorded config over CLI presets —
            # e.g. its gelu variant does not change parameter shapes, so a
            # mismatched preset would restore fine and then run (or
            # export) the wrong activation.
            from .train.engine import Trainer

            ckpt_cfg = ExperimentConfig.from_checkpoint_dict(meta["config"])
            if ckpt_cfg.model.vocab_size != cfg.model.vocab_size:
                raise SystemExit(
                    f"checkpoint model vocab ({ckpt_cfg.model.vocab_size}) "
                    f"!= tokenizer vocab ({cfg.model.vocab_size}); pass the "
                    "matching --hf-dir / vocab"
                )
            model_cfg = ckpt_cfg.model
            if model_cfg != trainer.model_cfg:
                trainer = Trainer(model_cfg, cfg.train, pad_id=tok.pad_id)
        template = jax.eval_shape(lambda: trainer.init_state(seed=0))
        try:
            params = ckpt.restore_params(template, step=step)
        except Exception as e:
            raise SystemExit(
                f"checkpoint at {ckpt_dir} (step {step}) does not "
                f"match the resolved model ({type(e).__name__}: {e}) — pass "
                "the --preset/--config/--hf-dir the checkpoint was trained "
                "with"
            ) from None
        log.info(f"[PREDICT] restored local checkpoint (step {step})")
        return model_cfg, params


def cmd_predict(args) -> int:
    """Batch inference on new flows — the deployment step the reference
    never ships: it trains and evaluates (client1.py:379-400) but offers no
    way to RUN the detector on unlabeled traffic. Reads a flow CSV (label
    column optional), writes one row per flow: P(attack), the thresholded
    0/1 prediction, and its label name; logs metrics when labels exist."""
    import pandas as pd

    from .data import get_dataset, load_flow_csv
    from .data.pipeline import TokenizedSplit
    from .train.engine import Trainer

    if not getattr(args, "csv", None):
        raise SystemExit("predict needs --csv (the flows to classify)")
    for flag in ("stream", "source", "synthetic"):
        if getattr(args, flag, None):
            raise SystemExit(
                f"--{flag} is a training-data option; predict reads the "
                "flows to classify from --csv only"
            )
    if (
        not getattr(args, "checkpoint_dir", None)
        and getattr(args, "hf_dir", None)
        and not getattr(args, "pth", None)  # .pth supplies the trained head
    ):
        # Gate BEFORE the (expensive) weight conversion: a bare encoder's
        # head would be random noise, so predicting from it is meaningless.
        from .models.hf_convert import hf_dir_has_head

        if not hf_dir_has_head(args.hf_dir):
            raise SystemExit(
                f"--hf-dir {args.hf_dir} is a bare encoder (no classifier.* "
                "weights): its head would be random noise. Train it first "
                "(local/federated, then --checkpoint-dir), or point --hf-dir "
                "at a checkpoint fine-tuned with this head architecture"
            )
    tok, cfg, pretrained = _resolve_with_pretrained(args)
    if cfg.checkpoint_dir and getattr(args, "pth", None):
        # Checked on the RESOLVED config: checkpoint_dir may come from a
        # --config file, not just the flag.
        raise SystemExit(
            "--pth and a checkpoint_dir are both weight sources; pass one"
        )
    if not cfg.checkpoint_dir and pretrained is None:
        raise SystemExit(
            "predict needs trained weights: pass --checkpoint-dir (a local "
            "or federated training checkpoint) or --hf-dir (a fine-tuned "
            "classifier checkpoint)"
        )
    trainer = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
    if cfg.checkpoint_dir:
        model_cfg, params = _restore_predict_params(cfg, tok, trainer)
        if model_cfg != cfg.model:
            trainer = Trainer(model_cfg, cfg.train, pad_id=tok.pad_id)
    else:
        model_cfg, params = cfg.model, pretrained

    spec = get_dataset(cfg.data.dataset)
    with phase(f"loading {args.csv}", tag="DATA"):
        df = load_flow_csv(args.csv)
        texts = spec.render_texts(df)
        label_col = cfg.data.label_column if spec.label_kind == "positive" else spec.label_column
        labels = None
        if label_col in df.columns:
            from .data.cicids import _spec_labels

            labels = _spec_labels(df, cfg.data)
    if not texts:
        raise SystemExit(f"--csv {args.csv} has no data rows")
    with phase(f"tokenize {len(texts)} flows", tag="DATA"):
        enc = tok.batch_encode(texts, max_len=model_cfg.max_len)
    split = TokenizedSplit(
        enc["input_ids"],
        enc["attention_mask"],
        (labels if labels is not None else np.zeros(len(texts))).astype(np.int32),
    )
    bs = cfg.data.eval_batch_size
    with phase(f"predict ({len(texts)} flows, bs {bs})", tag="EVAL"):
        # Trainer.evaluate is the one eval pipeline (pad/slice/accumulate);
        # its metrics are ignored here (labels may be dummies) — predict
        # only consumes the per-row P(attack) probs.
        probs = trainer.evaluate(params, split, batch_size=bs)["probs"]
    preds = (probs >= args.threshold).astype(np.int32)
    positive = (
        cfg.data.positive_label if spec.label_kind == "positive" else "attack"
    )
    out = pd.DataFrame(
        {
            "prob_attack": probs,
            "prediction": preds,
            "label_name": np.where(preds == 1, positive, "BENIGN"),
        }
    )
    out.to_csv(args.output, index=False)
    log.info(
        f"[PREDICT] wrote {len(out)} predictions to {args.output} "
        f"({int(preds.sum())} flagged {positive})"
    )
    if labels is not None:
        # Metrics at the SAME threshold the predictions used (sklearn
        # average='binary' semantics, as the reference's evaluate_model).
        y = labels.astype(np.int32)
        tp = int(((preds == 1) & (y == 1)).sum())
        fp = int(((preds == 1) & (y == 0)).sum())
        fn = int(((preds == 0) & (y == 1)).sum())
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        log.info(
            f"[PREDICT] against the CSV's labels (threshold "
            f"{args.threshold}): acc {(preds == y).mean() * 100:.4f} "
            f"prec {prec:.4f} rec {rec:.4f} f1 {f1:.4f}"
        )
    return 0


def cmd_export_hf(args) -> int:
    """Export trained weights to the HF DistilBERT checkpoint layout
    (config.json + model.safetensors + vocab.txt) — the reference's own
    artifact format (its required ``./distilbert-base-uncased`` input dir
    and its ``.pth`` state dicts use the same key space, client1.py:56,388).
    A reference user can load this with ``DistilBertModel.from_pretrained``
    or hand it back to this framework via ``--hf-dir``."""
    import jax

    from .models.hf_convert import flax_to_hf
    from .train.engine import Trainer

    tok, cfg, pretrained = _resolve_with_pretrained(args)
    if getattr(args, "pth", None) and cfg.checkpoint_dir:
        # Resolved config: checkpoint_dir may come from a --config file.
        raise SystemExit(
            "--pth and a checkpoint_dir are both weight sources; pass one"
        )
    if cfg.checkpoint_dir:
        trainer = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
        model_cfg, params = _restore_predict_params(cfg, tok, trainer)
    elif getattr(args, "pth", None):
        # Convert a reference-trained .pth straight to the HF layout.
        model_cfg, params = cfg.model, pretrained
    else:
        raise SystemExit(
            "export-hf needs trained weights: --checkpoint-dir, or "
            "--pth + --hf-dir (a reference-trained model)"
        )
    if model_cfg.n_classes != 2 or not isinstance(params, dict) or "encoder" not in params:
        raise SystemExit("checkpoint does not hold a classifier params tree")
    sd = flax_to_hf(jax.tree.map(np.asarray, params), model_cfg)

    out = args.out
    os.makedirs(out, exist_ok=True)
    from safetensors.numpy import save_file

    save_file(sd, os.path.join(out, "model.safetensors"))
    hf_config = {
        "architectures": ["DistilBertModel"],
        "model_type": "distilbert",
        "vocab_size": model_cfg.vocab_size,
        "dim": model_cfg.dim,
        "n_layers": model_cfg.n_layers,
        "n_heads": model_cfg.n_heads,
        "hidden_dim": model_cfg.hidden_dim,
        "max_position_embeddings": model_cfg.max_position_embeddings,
        "dropout": model_cfg.dropout,
        "attention_dropout": model_cfg.attention_dropout,
        "pad_token_id": model_cfg.pad_token_id,
        "initializer_range": model_cfg.initializer_range,
        # Declare the activation the weights were actually trained under:
        # HF's "gelu" is the erf form, "gelu_new" the tanh form.
        "activation": "gelu" if model_cfg.gelu == "exact" else "gelu_new",
        "tie_weights_": True,
    }
    with open(os.path.join(out, "config.json"), "w") as f:
        json.dump(hf_config, f, indent=2)
    tok.save_vocab(os.path.join(out, "vocab.txt"))
    log.info(
        f"[EXPORT] wrote HF checkpoint ({len(sd)} tensors, "
        f"{sum(v.nbytes for v in sd.values()) / 1e6:.1f} MB) to {out}"
    )
    return 0


def cmd_distill(args) -> int:
    """Teacher -> student knowledge distillation — the recipe that produced
    the reference's pretrained DistilBERT (client1.py:56).

    Teacher sources, in precedence order: ``--teacher-checkpoint`` (a model
    trained here, e.g. a federated aggregate), ``--pth`` + ``--hf-dir``
    (a model the REFERENCE trained), or a fresh teacher trained in-run
    (2x student depth by default). ``--student-layers`` shrinks the student
    below the resolved model depth (e.g. distill a migrated 6-layer
    reference model into 3 layers)."""
    from . import reporting
    from .train.distill import DistillTrainer
    from .train.engine import Trainer

    if getattr(args, "teacher_checkpoint", None) and getattr(args, "pth", None):
        raise SystemExit(
            "--teacher-checkpoint and --pth are both teacher sources; pass one"
        )
    if getattr(args, "pth", None) and args.teacher_layers is not None:
        raise SystemExit(
            "--teacher-layers has no effect when --pth supplies the "
            "teacher (its depth comes from --hf-dir's config.json)"
        )
    if getattr(args, "student_layers", None) is not None and args.student_layers < 1:
        raise SystemExit(f"--student-layers {args.student_layers} must be >= 1")
    # --teacher-checkpoint supplies the weights; skip the (full) --hf-dir
    # weight load in that case — only tokenizer + architecture are needed.
    tok, cfg, pretrained = _resolve_with_pretrained(
        args, load_weights=not getattr(args, "teacher_checkpoint", None)
    )
    # Flags override the config only where given; invalid values (e.g.
    # --temperature 0) flow into DistillConfig validation rather than being
    # silently replaced, and --no-teacher-init can only turn the init OFF.
    d = cfg.distill
    cfg = dataclasses.replace(
        cfg,
        distill=dataclasses.replace(
            d,
            temperature=d.temperature if args.temperature is None else args.temperature,
            alpha=d.alpha if args.alpha is None else args.alpha,
            init_from_teacher=d.init_from_teacher and not args.no_teacher_init,
        ),
    )
    client = _load_clients(args, cfg, tok, 1)[0]

    from .utils.profiling import trace

    student_cfg = (
        cfg.model
        if getattr(args, "student_layers", None) is None
        else cfg.model.replace(n_layers=args.student_layers)
    )
    teacher_layers = (
        2 * student_cfg.n_layers
        if args.teacher_layers is None
        else args.teacher_layers
    )
    # ModelConfig validates n_layers >= 1; enforce deeper-than-student here so
    # a degenerate teacher fails before the training budget is spent.
    if teacher_layers < student_cfg.n_layers:
        raise SystemExit(
            f"--teacher-layers {teacher_layers} is shallower than the "
            f"{student_cfg.n_layers}-layer student"
        )
    teacher_cfg = cfg.model.replace(n_layers=teacher_layers)

    def _check_teacher(tc):
        if tc.n_layers < student_cfg.n_layers:
            raise SystemExit(
                f"teacher has {tc.n_layers} layers — shallower than the "
                f"{student_cfg.n_layers}-layer student"
            )
        if (tc.dim, tc.n_heads, tc.hidden_dim) != (
            student_cfg.dim, student_cfg.n_heads, student_cfg.hidden_dim,
        ):
            raise SystemExit(
                f"teacher width (dim {tc.dim}, heads {tc.n_heads}, ffn "
                f"{tc.hidden_dim}) != student (dim {student_cfg.dim}, heads "
                f"{student_cfg.n_heads}, ffn {student_cfg.hidden_dim}): "
                "depth-only distillation"
            )

    with trace(getattr(args, "profile_dir", None)):
        if getattr(args, "teacher_checkpoint", None):
            # Distill a model trained elsewhere — e.g. the aggregate of a
            # federated BERT-base fleet — into a small deployable student:
            # the end-to-end "distilled LLMs in distributed networks" story.
            teacher_cfg_hint = teacher_cfg
            t_trainer = Trainer(teacher_cfg_hint, cfg.train, pad_id=tok.pad_id)
            teacher_cfg, teacher_params = _restore_predict_params(
                cfg, tok, t_trainer, ckpt_dir=args.teacher_checkpoint
            )
            _check_teacher(teacher_cfg)
            if teacher_cfg != teacher_cfg_hint:
                t_trainer = Trainer(teacher_cfg, cfg.train, pad_id=tok.pad_id)
            log.info(
                f"[DISTILL] teacher from {args.teacher_checkpoint} "
                f"({teacher_cfg.n_layers} layers)"
            )
        elif getattr(args, "pth", None):
            # The migrated reference model IS the (already-trained) teacher.
            teacher_cfg, teacher_params = cfg.model, pretrained
            _check_teacher(teacher_cfg)
            t_trainer = Trainer(teacher_cfg, cfg.train, pad_id=tok.pad_id)
            log.info(
                f"[DISTILL] teacher from reference .pth {args.pth} "
                f"({teacher_cfg.n_layers} layers)"
            )
        else:
            t_trainer = Trainer(teacher_cfg, cfg.train, pad_id=tok.pad_id)
            # A bare --hf-dir encoder warm-starts the fresh teacher when the
            # depths line up (the reference's own pretrained-start pattern).
            warm = pretrained if teacher_cfg == cfg.model else None
            if pretrained is not None and warm is None:
                log.info(
                    f"[DISTILL] --hf-dir encoder ({cfg.model.n_layers} "
                    f"layers) cannot warm-start the {teacher_cfg.n_layers}-"
                    f"layer teacher; pass --teacher-layers "
                    f"{cfg.model.n_layers} to use it"
                )
            t_state = t_trainer.init_state(params=warm)
            with phase(
                f"teacher training ({teacher_cfg.n_layers} layers)", tag="DISTILL"
            ):
                t_state, _ = t_trainer.fit(
                    t_state, client.train, batch_size=cfg.data.batch_size,
                    tag="[TEACHER] ",
                )
            teacher_params = t_state.params
        teacher_metrics = t_trainer.evaluate(teacher_params, client.test)

        d_trainer = DistillTrainer(
            student_cfg, teacher_cfg, cfg.train, cfg.distill, pad_id=tok.pad_id
        )
        s_state = d_trainer.init_student_state(teacher_params)
        with phase(
            f"distilling into {student_cfg.n_layers}-layer student", tag="DISTILL"
        ):
            s_state, _ = d_trainer.distill(
                s_state,
                teacher_params,
                client.train,
                batch_size=cfg.data.batch_size,
                epochs=args.distill_epochs,
                tag="[STUDENT] ",
            )
        student_metrics = d_trainer.evaluate(s_state.params, client.test)

    log.info(
        f"[DISTILL] teacher acc {teacher_metrics['Accuracy']:.4f} -> "
        f"student acc {student_metrics['Accuracy']:.4f} "
        f"({teacher_cfg.n_layers} -> {student_cfg.n_layers} layers)"
    )
    os.makedirs(cfg.output_dir, exist_ok=True)
    reporting.save_metrics(
        teacher_metrics, os.path.join(cfg.output_dir, "teacher_metrics.csv")
    )
    reporting.save_metrics(
        student_metrics, os.path.join(cfg.output_dir, "student_metrics.csv")
    )
    reporting.plot_metrics_comparison(
        teacher_metrics,
        student_metrics,
        "Teacher vs Distilled Student (test)",
        os.path.join(cfg.output_dir, "distillation_comparison.png"),
        labels=("Teacher", "Student"),
    )
    if cfg.checkpoint_dir:
        from .train.checkpoint import Checkpointer

        with Checkpointer(cfg.checkpoint_dir) as ckpt:
            # Provenance records the STUDENT architecture (what the saved
            # params actually are), not the resolved teacher-sized model.
            student_experiment = dataclasses.replace(cfg, model=student_cfg)
            ckpt.save(
                int(s_state.step),
                s_state,
                meta={
                    "distilled": True,
                    "kind": "local",
                    "config": student_experiment.to_dict(),
                },
            )
            ckpt.wait()
    return 0


def cmd_export_config(args) -> int:
    from .data import default_tokenizer

    cfg = resolve_config(args, vocab_size=len(default_tokenizer().vocab))
    json.dump(cfg.to_dict(), sys.stdout, indent=2)
    sys.stdout.write("\n")
    return 0


# ------------------------------------------------------------------ parser
def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", help="JSON config file (ExperimentConfig.to_dict shape)")
    p.add_argument(
        "--preset", default="tiny", help="tiny|distilbert|bert|bert-large"
    )
    p.add_argument(
        "--gelu",
        choices=["exact", "tanh"],
        help="FFN activation: tanh (default, ~20%% faster on TPU, within a "
        "few bf16 ulps of erf) or exact (HF's erf form, fp32 parity)",
    )
    p.add_argument(
        "--hf-dir",
        help="HF DistilBERT checkpoint dir (config.json + vocab.txt + "
        "model.safetensors|pytorch_model.bin) — the reference's required "
        "./distilbert-base-uncased; pretrained encoder + fresh head",
    )
    p.add_argument(
        "--pth",
        help="a reference-run .pth state dict (its DDoSClassifier / "
        "aggregated model) as the weights, with --hf-dir supplying "
        "tokenizer + architecture — direct migration of a model the "
        "reference trained",
    )
    p.add_argument("--csv", help="flow CSV path (schema set by --dataset)")
    p.add_argument(
        "--dataset",
        help="registered dataset schema: cicids2017|cicddos2019|unswnb15",
    )
    p.add_argument(
        "--source",
        action="append",
        metavar="[DATASET=]PATH",
        help="mixed-corpus CSV source (repeatable); dataset auto-detected "
        "from the schema when omitted",
    )
    p.add_argument("--synthetic", type=int, metavar="N", help="use N synthetic flows")
    p.add_argument(
        "--stream",
        action="store_true",
        help="two-pass chunked CSV reader (corpora larger than RAM); "
        "index-based sampling semantics",
    )
    p.add_argument("--output-dir", default=None)
    p.add_argument("--batch-size", type=int)
    p.add_argument("--epochs", type=int, help="epochs per round")
    p.add_argument("--learning-rate", type=float)
    p.add_argument(
        "--warmup-steps",
        type=int,
        help="linear LR warmup steps (global step count; 0 = constant)",
    )
    p.add_argument("--max-len", type=int)
    p.add_argument("--data-fraction", type=float)
    p.add_argument("--seed", type=int)
    p.add_argument(
        "--profile-dir",
        help="write a jax.profiler trace of the training phase here "
        "(view with xprof/tensorboard)",
    )
    p.add_argument(
        "--metrics-jsonl",
        help="append one structured JSON record per (round, client, phase) "
        "here — machine-readable observability the reference's prints/CSVs "
        "lack (pd.read_json(..., lines=True))",
    )


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="fedtpu",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("local", help="single-client train/eval/report")
    _add_common(p)
    p.add_argument("--client-id", type=int, default=0)
    p.add_argument("--checkpoint-dir")
    p.set_defaults(fn=cmd_local)

    p = sub.add_parser("federated", help="N-client SPMD FedAvg on the TPU mesh")
    _add_common(p)
    p.add_argument("--num-clients", type=int, default=None)  # None: config wins
    p.add_argument("--rounds", type=int)
    p.add_argument("--data-parallel", type=int, help="per-client data-parallel shards")
    g = p.add_mutually_exclusive_group()
    g.add_argument(
        "--weighted",
        action="store_true",
        help="require sample-count FedAvg weights (the auto default already "
        "weights by sample count when counts are known and DP is off)",
    )
    g.add_argument(
        "--unweighted",
        action="store_true",
        help="force the uniform mean (the reference's server.py:73-76)",
    )
    p.add_argument("--partition", help="sample|disjoint|dirichlet")
    p.add_argument(
        "--dirichlet-alpha",
        type=float,
        help="label-skew concentration for --partition dirichlet "
        "(smaller = more non-IID; default 0.5)",
    )
    p.add_argument(
        "--prox-mu",
        type=float,
        help="FedProx proximal weight (0 = plain FedAvg); stabilizes "
        "non-IID partitions",
    )
    p.add_argument(
        "--participation",
        type=float,
        help="fraction of clients aggregated per round (sampled, seeded); "
        "1.0 = everyone (reference behavior)",
    )
    p.add_argument(
        "--dp-clip",
        type=float,
        help="DP-FedAvg: clip each client's round update to this L2 norm "
        "before aggregation (0 = off)",
    )
    p.add_argument(
        "--dp-noise-multiplier",
        type=float,
        help="DP-FedAvg: Gaussian noise multiplier on the clipped mean "
        "update (std = multiplier * clip / n_participants); requires "
        "--dp-clip",
    )
    p.add_argument(
        "--server-opt",
        choices=["none", "momentum", "adam"],
        help="FedOpt server optimizer over the round's mean update: "
        "momentum = FedAvgM, adam = FedAdam (default none = plain FedAvg)",
    )
    p.add_argument(
        "--server-lr", type=float, help="server optimizer learning rate (default 1.0)"
    )
    p.add_argument(
        "--server-momentum", type=float, help="FedAvgM momentum (default 0.9)"
    )
    p.add_argument("--checkpoint-dir")
    p.add_argument(
        "--coordinator",
        help="multi-host: coordinator HOST:PORT (every process passes the "
        "same address; also via JAX_COORDINATOR_ADDRESS)",
    )
    p.add_argument("--num-processes", type=int, help="multi-host: process count")
    p.add_argument("--process-id", type=int, help="multi-host: this process's id")
    p.set_defaults(fn=cmd_federated)

    p = sub.add_parser(
        "serve",
        help="TCP aggregation server (demo-parity mode)",
        epilog="Set FEDTPU_SECRET (env var, same value on server and every "
        "client) to require HMAC-SHA256-authenticated, replay-protected "
        "exchanges; unset = the reference's open protocol.",
    )
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=12345)
    p.add_argument("--num-clients", type=int, default=2)
    p.add_argument("--rounds", type=int, default=1)
    p.add_argument("--min-clients", type=int, default=None)
    p.add_argument("--weighted", action="store_true")
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--compression", default="none", choices=["none", "bf16", "int8"])
    p.add_argument(
        "--secure-agg",
        action="store_true",
        help="secure aggregation: accept pairwise-masked uploads and "
        "recover only their sum — individual client weights are never "
        "visible to the server",
    )
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "client",
        help="TCP federated client (demo-parity mode)",
        epilog="Set FEDTPU_SECRET (env var) to authenticate exchanges; must "
        "match the server's.",
    )
    _add_common(p)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=12345)
    p.add_argument("--client-id", type=int, required=True)
    p.add_argument("--num-clients", type=int, default=None)  # None: config wins
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--compression", default="none", choices=["none", "bf16", "int8"])
    p.add_argument(
        "--secure-agg",
        action="store_true",
        help="mask the upload with pairwise secrets (FEDTPU_MASK_SECRET, "
        "shared by clients only) so the server sees only the sum",
    )
    p.add_argument(
        "--checkpoint-dir",
        help="warm-start + save full state here (the reference's "
        "client{N}_model.pth re-launch pattern, client1.py:375-377,388,403)",
    )
    p.add_argument(
        "--rounds",
        type=int,
        default=1,
        help="train/exchange rounds in one process (server must serve >= "
        "this many); the reference achieves this by re-launching",
    )
    p.set_defaults(fn=cmd_client)

    p = sub.add_parser(
        "predict",
        help="batch inference: flow CSV -> per-row attack probability CSV",
    )
    _add_common(p)  # provides --csv (required here), --dataset, model flags
    p.add_argument(
        "--output", default="predictions.csv", help="predictions CSV path"
    )
    p.add_argument("--checkpoint-dir", help="local or federated training checkpoint")
    p.add_argument(
        "--threshold",
        type=float,
        default=0.5,
        help="P(attack) decision threshold (default 0.5)",
    )
    p.set_defaults(fn=cmd_predict)

    p = sub.add_parser("distill", help="teacher -> student knowledge distillation")
    _add_common(p)
    p.add_argument("--teacher-layers", type=int, help="default: 2x student layers")
    p.add_argument(
        "--teacher-checkpoint",
        help="distill FROM this trained checkpoint (local or federated — "
        "e.g. a federated BERT fleet's aggregate) instead of training a "
        "fresh teacher; --pth + --hf-dir similarly supplies a "
        "reference-trained teacher",
    )
    p.add_argument(
        "--student-layers",
        type=int,
        help="student depth (default: the resolved model's) — e.g. distill "
        "a migrated 6-layer model into 3 layers",
    )
    p.add_argument("--distill-epochs", type=int, help="default: train epochs")
    p.add_argument("--temperature", type=float, help="KD softmax temperature")
    p.add_argument("--alpha", type=float, help="KD loss weight in [0,1]")
    p.add_argument(
        "--no-teacher-init",
        action="store_true",
        help="skip the every-other-layer student init",
    )
    p.add_argument("--checkpoint-dir")
    p.set_defaults(fn=cmd_distill)

    p = sub.add_parser(
        "export-hf",
        help="export a trained checkpoint to the HF DistilBERT layout "
        "(config.json + model.safetensors + vocab.txt)",
    )
    _add_common(p)
    # Not required: --pth + --hf-dir is the other valid weight source
    # (cmd_export_hf checks that exactly one is given at runtime).
    p.add_argument("--checkpoint-dir")
    p.add_argument("--out", required=True, help="output HF checkpoint dir")
    p.set_defaults(fn=cmd_export_hf)

    p = sub.add_parser("export-config", help="print the resolved config as JSON")
    _add_common(p)
    p.add_argument("--num-clients", type=int)
    p.add_argument("--rounds", type=int)
    p.set_defaults(fn=cmd_export_config)
    return ap


def main(argv: Sequence[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
