"""Per-chunk int8 quantization for streamed uploads (``enc="int8c"``).

The wire module's row-quantized ``int8`` keys its fp32 scales to the
tensor's leading axis — fine for a matrix, degenerate for the 1-D and
scalar leaves a transformer tree is full of (one scale for a whole
embedding row block, or for an entire bias vector). This codec keys the
scales to FIXED element chunks of :data:`QUANT_CHUNK_ELEMS` instead, so
every leaf — any rank, any shape — quantizes with uniform local scale
resolution and the encoded size is computable from the element count
alone (what lets a stream header plan it before any leaf is gathered).

Payload layout for a tensor of ``n`` elements::

    [ceil(n / QUANT_CHUNK_ELEMS) x fp32 scale] + [n x int8]

Each chunk's scale is ``max|chunk| / 127``; values quantize as
``clip(rint(x / scale), -127, 127)``. Overhead is one fp32 per 4096
elements (~0.1%), so the wire cost is ~4x below fp32 — the
``--wire-dtype int8`` arm of the wire-efficiency bench.

Determinism contract (this module is in the ``fedtpu check``
determinism-pass SCOPE): both directions are pure elementwise numpy on
the input bytes — same payload in, same fp32 out, on every host and
every replay. Non-finite inputs map deterministically too: a chunk whose
max|x| is 0 or non-finite falls back to scale 1.0, NaN quantizes to 0,
±inf saturates to ±127. The server dequantizes BEFORE folding, so the
ascending-id fp32 fold order (and with it ``fleet_crc_exact`` and the
DP re-clip contract) extends to quantized rounds unchanged.
"""

from __future__ import annotations

import numpy as np

#: Elements per fp32 scale group. 4096 keeps the scale overhead at
#: ~0.1% while bounding each scale's blast radius (one outlier inflates
#: the quantization step of 4096 neighbors, not a whole tensor row).
QUANT_CHUNK_ELEMS = 4096


def int8c_nchunks(size: int) -> int:
    """Scale-group count for a tensor of ``size`` elements."""
    size = int(size)
    if size < 0:
        raise ValueError(f"negative tensor size {size}")
    return -(-size // QUANT_CHUNK_ELEMS)


def int8c_nbytes(size: int) -> int:
    """Exact encoded byte count for ``size`` elements — computable from
    shape alone, which is what makes the encoding streamable."""
    return 4 * int8c_nchunks(size) + int(size)


def quantize_int8c(arr: np.ndarray) -> bytes:
    """fp32 tensor -> ``[chunk scales fp32] + [int8 data]`` payload."""
    a = np.ascontiguousarray(arr, np.float32).reshape(-1)
    n = a.size
    if n == 0:
        return b""
    nchunks = int8c_nchunks(n)
    pad = nchunks * QUANT_CHUNK_ELEMS - n
    a2 = (np.pad(a, (0, pad)) if pad else a).reshape(
        nchunks, QUANT_CHUNK_ELEMS
    )
    with np.errstate(invalid="ignore"):
        amax = np.max(np.abs(a2), axis=1)
    scales = (amax / np.float32(127.0)).astype(np.float32)
    # A chunk of zeros/denormals (scale underflows to 0) or one holding
    # inf/NaN (scale non-finite) cannot set its own step; scale 1.0 keeps
    # both directions finite and deterministic.
    scales = np.where(
        np.isfinite(scales) & (scales > 0), scales, np.float32(1.0)
    ).astype(np.float32)
    with np.errstate(invalid="ignore", over="ignore"):
        ratio = a2 / scales[:, None]
    # NaN -> 0, +/-inf -> saturate: the deterministic non-finite mapping
    # (int8 cast of NaN is platform-defined — never let one reach it).
    ratio = np.nan_to_num(ratio, nan=0.0, posinf=127.0, neginf=-127.0)
    q = np.clip(np.rint(ratio), -127, 127).astype(np.int8)
    return scales.tobytes() + q.reshape(-1)[:n].tobytes()


def dequantize_int8c(raw, shape: tuple[int, ...]) -> np.ndarray:
    """Inverse of :func:`quantize_int8c` for a tensor of ``shape``.

    The payload is untrusted wire bytes: the length must match the shape
    exactly and every scale must be finite and positive (the encoder
    never emits anything else; a NaN scale would otherwise poison the
    round's running fold through one crafted upload)."""
    from . import wire

    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    nchunks = int8c_nchunks(size)
    want = 4 * nchunks + size
    if len(raw) != want:
        raise wire.WireError(
            f"int8c tensor payload is {len(raw)} bytes, expected {want}"
        )
    scales = np.frombuffer(raw, np.float32, count=nchunks)
    if nchunks and not bool(np.all(np.isfinite(scales) & (scales > 0))):
        raise wire.WireError(
            "int8c tensor carries a non-finite or non-positive scale"
        )
    q = np.frombuffer(raw, np.int8, count=size, offset=4 * nchunks)
    out = q.astype(np.float32) * np.repeat(scales, QUANT_CHUNK_ELEMS)[:size]
    return out.reshape(shape)
