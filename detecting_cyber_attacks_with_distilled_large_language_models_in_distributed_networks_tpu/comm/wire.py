"""Non-executable wire format for model weights: JSON manifest + flat arrays.

Replaces the reference's ``gzip(pickle(state_dict))`` (reference
client1.py:228-243, server.py:18-27). ``pickle.loads`` on unauthenticated
network bytes is remote code execution by design (SURVEY.md §5); this format
cannot encode code — a message is::

    MAGIC 'FTPW' | u32 version | u32 header_len | header JSON | payload bytes

where the header lists every tensor as ``{key, dtype, shape, enc, offset,
nbytes}`` plus a payload CRC-32 and a free-form JSON ``meta`` (client id,
round, sample count). Tensor keys are '/'-joined paths through the nested
params dict, so decode rebuilds the pytree with no embedded type tags.

One optional meta field is a cross-cutting contract rather than a caller
convention: ``meta["trace"]`` (obs/trace.py TRACE_META_KEY) carries the
server-minted round trace id in every aggregate reply, giving both ends
of a round the shared span identity the ``fedtpu obs`` timeline merges
on. It is plain meta — peers that omit or ignore it interop unchanged,
so tracing deploys one process at a time.

Optional ``compression="bf16"`` packs float32 tensors to bfloat16 via the
native fedwire library (comm/native.py) — a 2x cut that matches TPU compute
precision, instead of the reference's ~11 s/round byte-level gzip.
``compression="int8"`` goes further (4x vs fp32): symmetric per-row
quantization, each leading-axis row carrying its own fp32 scale
(``max|row| / 127``) prepended to the tensor's payload segment. Worst-case
per-weight error is half a quantization step (~0.4% of the row's max) —
lossier than bf16; an opt-in bandwidth/fidelity trade for slow links.

``compression="topk"`` / ``"topk:<frac>"`` keeps only the largest-magnitude
``frac`` of each fp32 tensor's entries (default 1%): per-tensor payload is
``u32 k | int32 indices[k] | fp32 values[k]`` — 8 bytes per kept entry, so
~50x smaller than fp32 at frac=0.01; decode scatters back to a dense
zero-filled tensor. On its own
this is extremely lossy — it exists for the *sparse round-delta* exchange
(comm/client.py ``FederatedClient`` with a topk compression: uploads become
top-k round deltas with client-held error feedback, so dropped mass is
carried to the next round, never lost).
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import json
import struct
from typing import Any, Mapping

import numpy as np

from . import native

MAGIC = b"FTPW"
VERSION = 1
#: HMAC-SHA256 tag appended after the payload when a shared key is used.
AUTH_TAG_LEN = 32
_AUTH_SCHEME = "hmac-sha256"
#: Challenge frame sent by an authenticated server on connect:
#: NONCE_MAGIC + NONCE_LEN random bytes, echoed in the client's header.
NONCE_MAGIC = b"NONC"
NONCE_LEN = 16
#: Round-advert frame sent by a secure-aggregation server on connect (after
#: the nonce challenge, if any): ROUND_MAGIC + u64 little-endian round
#: index + SESSION_LEN random session bytes (fresh per server run).
#: Clients derive their pairwise mask streams from (session, round), so all
#: participants of a round mask consistently and a mask stream is never
#: reused across rounds or server restarts (reuse would let an observer
#: difference two uploads and unmask a client's weight delta).
ROUND_MAGIC = b"RNDX"
SESSION_LEN = 16
#: DH key-exchange frames for per-pair secure-aggregation masks
#: (comm/secure.py): the client answers the round advert with
#: PUBKEY_MAGIC + u64 client_id + its 256-byte ephemeral public value
#: (+ an HMAC tag in auth mode); the server, once every participant's key
#: arrived, replies KEYS_MAGIC + num_clients x (u64 id + pubkey [+ tag]).
PUBKEY_MAGIC = b"DHPK"
KEYS_MAGIC = b"DHKS"
#: Central-DP handshake (after the nonce, if any; before the secure round
#: advert). The server speaks FIRST — DP_MAGIC + f64 clip + f64 noise
#: multiplier + f64 sampling rate q — so a mis-configured plain client
#: can diagnose the mode mismatch; the client identifies itself
#: (DPID_MAGIC + i64 client_id) and the server answers the per-round
#: Poisson cohort verdict (DPCOHORT_MAGIC + u8 sampled). A sampled
#: client proceeds with its clipped-round-delta upload; a non-sampled
#: one sits the round out but still receives the round's reply (its base
#: must track the fleet's). The DP reply is the noised mean delta over
#: the round's contributors — the server never holds absolute weights —
#: or a "noop" marker for an empty cohort.
DP_MAGIC = b"DPAD"
DPID_MAGIC = b"DPID"
DPCOHORT_MAGIC = b"DPCO"
#: Auth-mode sit-out acknowledgment: a non-sampled client proves key
#: knowledge — DPSKIP_MAGIC + HMAC(auth_key, domain + nonce + id) —
#: before the server registers it for the round's reply (without this an
#: unauthenticated connection could claim a sitting-out id, evict the
#: real client's registration, and collect the aggregate).
DPSKIP_MAGIC = b"DPSK"
DPSKIP_DOMAIN = b"fedtpu-dp-skip-v1"
#: Online scoring frames (serving/protocol.py), riding the same length-
#: framed transport in fire-and-forget mode (framing.send_frame
#: await_ack=False): SCORE_REQ carries one flow record (text or raw
#: features) + a per-request deadline; SCORE_REP answers with P(attack)
#: plus the serving telemetry (model round, batch size, queue wait);
#: SCORE_REJ is the explicit 503-style admission-control refusal — a
#: shed request is TOLD it was shed instead of hanging to its deadline.
SCORE_REQ_MAGIC = b"SCRQ"
SCORE_REP_MAGIC = b"SCRP"
SCORE_REJ_MAGIC = b"SCRJ"
#: Scoring-port authentication (serving/protocol.py): with ``--auth`` the
#: scoring server reuses the FL tier's challenge-response — it opens every
#: connection with the NONCE_MAGIC challenge above, and the client must
#: answer SCORE_AUTH_MAGIC + HMAC-SHA256(key, domain + nonce) before any
#: request is read. Connection-level (one proof per connection, not per
#: request): the scoring hot path stays HMAC-free, and a captured proof is
#: useless on any other connection (fresh nonce). Without a key the port
#: is the reference-style open protocol, as before.
SCORE_AUTH_MAGIC = b"SCAU"
SCORE_AUTH_DOMAIN = b"fedtpu-score-auth-v1"
_ALLOWED_DTYPES = {
    "float32", "float64", "float16", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
}


class WireError(ValueError):
    """Malformed, corrupt, or version-mismatched message."""


class ModeError(ValueError):
    """Client/server protocol-mode mismatch (e.g. --dp against a non-DP
    server). Deliberately NOT a WireError: retrying cannot help, so the
    client's retry loop must let it propagate immediately."""


# --------------------------------------------------- int8 row quantization
def _int8_rows(arr: np.ndarray) -> tuple[np.ndarray, int]:
    """View ``arr`` as [rows, cols] for per-row quantization (leading axis
    = rows; scalars/1-D become one row). Explicit cols so zero-size
    tensors reshape cleanly (reshape(-1) is ambiguous at size 0)."""
    rows = arr.shape[0] if arr.ndim >= 2 else 1
    cols = arr.size // rows if rows else 0
    return arr.reshape(rows, cols), rows


def quantize_int8(arr: np.ndarray) -> bytes:
    """fp32 tensor -> payload bytes: [rows x fp32 scale] + [int8 data]."""
    a, rows = _int8_rows(np.ascontiguousarray(arr, np.float32))
    amax = np.abs(a).max(axis=1) if a.size else np.zeros(rows, np.float32)
    scales = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(a / scales[:, None]), -127, 127).astype(np.int8)
    return scales.tobytes() + q.tobytes()


def dequantize_int8(raw, shape: tuple[int, ...]) -> np.ndarray:
    """Inverse of :func:`quantize_int8` for a tensor of ``shape``."""
    rows = shape[0] if len(shape) >= 2 else 1
    cols = int(np.prod(shape)) // rows if rows else 0
    want = 4 * rows + rows * cols
    if len(raw) != want:
        raise WireError(
            f"int8 tensor payload is {len(raw)} bytes, expected {want}"
        )
    scales = np.frombuffer(raw[: 4 * rows], np.float32)
    q = np.frombuffer(raw[4 * rows :], np.int8).reshape(rows, cols)
    return (q.astype(np.float32) * scales[:, None]).reshape(shape)


# ------------------------------------------------------ top-k sparsification
DEFAULT_TOPK_FRAC = 0.01
#: Densified-tensor allocation cap. For raw/bf16/int8 the payload itself
#: scales with the claimed shape (and is bounded by framing.MAX_FRAME =
#: 8 GiB), but a topk payload is ~8 bytes per kept entry regardless of the
#: claimed dense shape — a ~50-byte message claiming shape [1e12] would
#: otherwise trigger a multi-TB np.zeros on the receiver (memory-
#: amplification DoS on the default unauthenticated server). Mirror the
#: frame bound: no legitimate tensor can exceed what one frame can carry.
MAX_DENSE_TENSOR_BYTES = 8 << 30


def parse_compression(spec: str) -> tuple[str, float | None]:
    """``"topk:0.05"`` -> ``("topk", 0.05)``; plain modes -> ``(spec, None)``."""
    if spec.startswith("topk"):
        frac = DEFAULT_TOPK_FRAC
        if spec != "topk":
            if not spec.startswith("topk:"):
                raise WireError(f"unknown compression {spec!r}")
            try:
                frac = float(spec.split(":", 1)[1])
            except ValueError:
                raise WireError(f"bad topk fraction in {spec!r}") from None
        if not 0.0 < frac <= 1.0:
            raise WireError(f"topk fraction {frac} outside (0, 1]")
        return "topk", frac
    if spec not in ("none", "bf16", "int8"):
        raise WireError(f"unknown compression {spec!r}")
    return spec, None


def sparsify_topk(arr: np.ndarray, frac: float) -> bytes:
    """fp32 tensor -> ``u32 k | int32 idx[k] | fp32 vals[k]`` payload,
    keeping the ``k = max(1, round(frac * size))`` largest-|value| entries.
    Indices are sorted so decode's scatter is sequential."""
    a = np.ascontiguousarray(arr, np.float32).reshape(-1)
    if a.size == 0:
        return struct.pack("<I", 0)
    k = max(1, int(round(frac * a.size)))
    if k >= a.size:
        idx = np.arange(a.size, dtype=np.int32)
    else:
        idx = np.sort(np.argpartition(np.abs(a), -k)[-k:]).astype(np.int32)
    return struct.pack("<I", len(idx)) + idx.tobytes() + a[idx].tobytes()


def densify_topk(raw, shape: tuple[int, ...]) -> np.ndarray:
    """Inverse of :func:`sparsify_topk`: zeros everywhere but the kept
    entries. Bounds-checks everything — the payload is untrusted."""
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if size < 0 or 4 * size > MAX_DENSE_TENSOR_BYTES:
        # Checked BEFORE any allocation: the shape is attacker-controlled
        # and, unlike the dense encodings, unbacked by payload bytes.
        raise WireError(
            f"topk tensor claims dense size {size} "
            f"(> {MAX_DENSE_TENSOR_BYTES // 4} elements)"
        )
    if len(raw) < 4:
        raise WireError("topk tensor payload shorter than its count field")
    (k,) = struct.unpack("<I", bytes(raw[:4]))
    if k > size:
        raise WireError(f"topk count {k} exceeds dense tensor size {size}")
    if len(raw) != 4 + 8 * k:
        raise WireError(
            f"topk tensor payload is {len(raw)} bytes, expected {4 + 8 * k}"
        )
    idx = np.frombuffer(raw, np.int32, count=k, offset=4)
    vals = np.frombuffer(raw, np.float32, count=k, offset=4 + 4 * k)
    out = np.zeros(size, np.float32)
    if k:
        if idx.min() < 0 or idx.max() >= size:
            raise WireError("topk index out of tensor bounds")
        out[idx] = vals
    return out.reshape(shape)


class PreEncoded:
    """A tensor whose wire payload is already built (``enc``/``buf``/
    ``shape``/``dtype``): lets a caller that must inspect the encoded form
    anyway (the sparse-delta client mirrors the kept entries for its
    error-feedback residual) hand the bytes straight to :func:`encode`
    instead of paying the top-k selection twice."""

    __slots__ = ("enc", "buf", "shape", "dtype")

    def __init__(self, enc: str, buf: bytes, shape: tuple, dtype: str = "float32"):
        self.enc = enc
        self.buf = buf
        self.shape = tuple(int(s) for s in shape)
        self.dtype = dtype


def flat_l2_norm(flat: Mapping[str, Any]) -> float:
    """Global L2 norm across all tensors of a flat param/delta dict,
    accumulated in float64 — the single norm both the DP client's clip
    and the DP server's re-clip enforcement compute (their tolerance
    contract depends on both sides agreeing)."""
    return float(
        np.sqrt(
            sum(
                float(np.sum(np.asarray(v, np.float64) ** 2))
                for v in flat.values()
            )
        )
    )


def clip_flat(
    flat: Mapping[str, Any], clip: float
) -> tuple[dict[str, np.ndarray], float, float]:
    """Scale a flat delta dict to global L2 norm <= ``clip``; returns
    ``(clipped fp32 dict, original norm, applied scale)``."""
    norm = flat_l2_norm(flat)
    scale = min(1.0, clip / max(norm, 1e-12))
    return (
        {
            k: np.asarray(v, np.float32) * np.float32(scale)
            for k, v in flat.items()
        },
        norm,
        scale,
    )


def shapes_compatible(a: Mapping[str, Any], b: Mapping[str, Any]) -> bool:
    """True when two flat param dicts have identical key sets and per-key
    array shapes — i.e. delta/residual arithmetic between them is
    well-defined. Shared by the sparse-delta client (params vs base,
    residual vs params) and server (delta upload vs base)."""
    if set(a) != set(b):
        return False
    return all(np.asarray(a[k]).shape == np.asarray(b[k]).shape for k in a)


def flat_crc32(flat: Mapping[str, Any]) -> int:
    """Order-independent-of-construction checksum of a flat fp32 param
    dict: CRC-32 over the sorted-key concatenation of raw tensor bytes.
    The sparse-delta exchange uses it as the base-agreement contract —
    the server stamps its exact aggregate's crc into the reply, and a
    client only adopts the decoded reply as a delta base when its own
    crc matches (a lossy reply compression, e.g. int8, would silently
    bias every sparse round otherwise)."""
    crc = 0
    for key in sorted(flat):
        arr = np.ascontiguousarray(np.asarray(flat[key], np.float32))
        crc = native.crc32(np.frombuffer(arr.tobytes(), np.uint8), crc)
    return crc & 0xFFFFFFFF


# ------------------------------------------------------- pytree <-> flat
def flatten_params(tree: Any, *, sep: str = "/") -> dict[str, np.ndarray]:
    """Nested dict of arrays -> sorted flat ``{'a/b/c': ndarray}``."""
    out: dict[str, np.ndarray] = {}

    def _walk(node, prefix):
        if isinstance(node, Mapping):
            for key in node:
                if sep in str(key):
                    raise WireError(f"param key {key!r} contains separator {sep!r}")
                _walk(node[key], f"{prefix}{sep}{key}" if prefix else str(key))
        else:
            out[prefix] = np.asarray(node)

    _walk(tree, "")
    return dict(sorted(out.items()))


def unflatten_params(flat: Mapping[str, np.ndarray], *, sep: str = "/") -> dict:
    """Inverse of ``flatten_params``."""
    tree: dict = {}
    for path, value in flat.items():
        parts = path.split(sep)
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
            if not isinstance(node, dict):
                raise WireError(f"key path {path!r} collides with a tensor")
        node[parts[-1]] = value
    return tree


# ----------------------------------------------------------------- encode
def encode(
    params: Any,
    *,
    meta: Mapping[str, Any] | None = None,
    compression: str = "none",
    auth_key: bytes | None = None,
) -> bytes:
    """Params pytree (nested dict or flat dict of arrays) -> wire bytes.

    ``auth_key``: shared-secret HMAC-SHA256 over the entire message,
    appended as a 32-byte trailing tag. The reference's protocol has no
    authentication at all (any peer that can connect injects weights,
    server.py:57-65); a keyed decoder rejects unauthenticated or tampered
    messages."""
    compression, topk_frac = parse_compression(compression)
    flat = (
        dict(params)
        if isinstance(params, Mapping) and all(not isinstance(v, Mapping) for v in params.values())
        else flatten_params(params)
    )
    tensors = []
    chunks: list[bytes] = []
    offset = 0
    for key, arr in flat.items():
        if isinstance(arr, PreEncoded):
            tensors.append(
                {
                    "key": key,
                    "dtype": arr.dtype,
                    "shape": list(arr.shape),
                    "enc": arr.enc,
                    "offset": offset,
                    "nbytes": len(arr.buf),
                }
            )
            chunks.append(arr.buf)
            offset += len(arr.buf)
            continue
        arr = np.asarray(arr)
        dtype = str(arr.dtype)
        if dtype not in _ALLOWED_DTYPES:
            raise WireError(f"tensor {key!r} has unsupported dtype {dtype}")
        if compression == "bf16" and arr.dtype == np.float32:
            buf = np.ascontiguousarray(native.pack_bf16(arr)).tobytes()
            enc = "bf16"
        elif compression == "int8" and arr.dtype == np.float32:
            buf = quantize_int8(arr)
            enc = "int8"
        elif compression == "topk" and arr.dtype == np.float32:
            buf = sparsify_topk(arr, topk_frac)
            enc = "topk"
        else:
            buf = np.ascontiguousarray(arr).tobytes()
            enc = "raw"
        tensors.append(
            {
                "key": key,
                "dtype": dtype,
                "shape": list(arr.shape),
                "enc": enc,
                "offset": offset,
                "nbytes": len(buf),
            }
        )
        chunks.append(buf)
        offset += len(buf)
    payload = b"".join(chunks)
    header = {
        "tensors": tensors,
        "payload_nbytes": len(payload),
        "payload_crc32": native.crc32(payload),
        "meta": dict(meta or {}),
    }
    if auth_key is not None:
        header["auth"] = _AUTH_SCHEME
    hbytes = json.dumps(header, separators=(",", ":")).encode()
    msg = MAGIC + struct.pack("<II", VERSION, len(hbytes)) + hbytes + payload
    if auth_key is not None:
        msg += hmac_mod.new(auth_key, msg, hashlib.sha256).digest()
    return msg


# ----------------------------------------------------------------- decode
def decode(
    data: bytes | memoryview, *, auth_key: bytes | None = None
) -> tuple[dict, dict]:
    """Wire bytes -> ``(nested params dict, meta dict)``; verifies the CRC.

    With ``auth_key`` set, only messages carrying a valid HMAC-SHA256 tag
    are accepted — unauthenticated, tampered, or wrong-key messages raise
    :class:`WireError`. Without a key, a trailing tag (if any) is ignored
    (the peer authenticated; this side did not configure a key)."""
    view = memoryview(data)
    if len(view) < 12 or bytes(view[:4]) != MAGIC:
        raise WireError("bad magic: not a fedwire message")
    version, hlen = struct.unpack("<II", view[4:12])
    if version != VERSION:
        raise WireError(f"wire version {version} unsupported (expected {VERSION})")
    if len(view) < 12 + hlen:
        raise WireError("truncated header")
    try:
        header = json.loads(bytes(view[12 : 12 + hlen]).decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"malformed header: {e}") from None

    auth = header.get("auth")
    if auth not in (None, _AUTH_SCHEME):
        raise WireError(f"unknown auth scheme {auth!r}")
    if auth_key is not None and auth != _AUTH_SCHEME:
        raise WireError(
            f"unauthenticated message rejected (this side requires {_AUTH_SCHEME})"
        )
    if auth == _AUTH_SCHEME:
        # Tag boundary computed once for both verification and payload slice.
        if len(view) < 12 + hlen + AUTH_TAG_LEN:
            raise WireError("truncated auth tag")
        body_end = len(view) - AUTH_TAG_LEN
        if auth_key is not None:
            tag = bytes(view[body_end:])
            want = hmac_mod.new(auth_key, view[:body_end], hashlib.sha256).digest()
            if not hmac_mod.compare_digest(tag, want):
                raise WireError("HMAC verification failed (tampered or wrong key)")
        payload = view[12 + hlen : body_end]
    else:
        payload = view[12 + hlen :]
    if len(payload) != header.get("payload_nbytes"):
        raise WireError(
            f"payload length {len(payload)} != declared {header.get('payload_nbytes')}"
        )
    crc = native.crc32(np.frombuffer(payload, np.uint8))
    if crc != header.get("payload_crc32"):
        raise WireError(
            f"payload CRC mismatch (got {crc:#010x}, "
            f"header says {header.get('payload_crc32', 0):#010x})"
        )
    flat: dict[str, np.ndarray] = {}
    # Header fields are attacker-controlled; any inconsistency (missing keys,
    # shape/nbytes disagreement, bad offsets) must surface as WireError, not
    # leak as ValueError/KeyError and kill a server thread.
    try:
        tensors = header["tensors"]
        # Per-MESSAGE dense-size cap: the per-tensor cap alone still lets
        # one small frame list many near-cap topk tensors (claimed shapes
        # are unbacked by payload bytes), amplifying to hundreds of GiB of
        # np.zeros — and under Linux overcommit that is an OOM-kill of the
        # whole process later, not a catchable MemoryError now.
        claimed = sum(
            int(np.prod(t["shape"], dtype=np.int64)) * 4
            for t in tensors
            if t.get("enc") == "topk"
        )
        if claimed > MAX_DENSE_TENSOR_BYTES:
            raise WireError(
                f"message claims {claimed} dense bytes across topk tensors "
                f"(> {MAX_DENSE_TENSOR_BYTES})"
            )
        for t in tensors:
            key, dtype = t["key"], t["dtype"]
            if dtype not in _ALLOWED_DTYPES:
                raise WireError(f"tensor {key!r} has unsupported dtype {dtype}")
            offset, nbytes = int(t["offset"]), int(t["nbytes"])
            if offset < 0 or nbytes < 0 or offset + nbytes > len(payload):
                # Explicit bounds: a negative offset would slice from the
                # payload's tail and alias another tensor's bytes.
                raise WireError(f"tensor {key!r} has out-of-bounds extent")
            raw = payload[offset : offset + nbytes]
            if t["enc"] == "bf16":
                packed = np.frombuffer(raw, np.uint16)
                arr = native.unpack_bf16(packed, shape=tuple(t["shape"]))
            elif t["enc"] == "int8":
                arr = dequantize_int8(raw, tuple(t["shape"]))
            elif t["enc"] == "topk":
                arr = densify_topk(raw, tuple(t["shape"]))
            elif t["enc"] == "raw":
                arr = np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(t["shape"])
            else:
                raise WireError(f"unknown tensor encoding {t['enc']!r}")
            flat[key] = arr
        return unflatten_params(flat), dict(header.get("meta", {}))
    except WireError:
        raise
    except (KeyError, ValueError, TypeError, OverflowError, AttributeError) as e:
        # OverflowError: a claimed dim too large for int64 (np.prod cap
        # math); AttributeError: a tensor entry that isn't a dict. Both
        # reachable from attacker-controlled headers and must surface as
        # WireError, not kill a server thread.
        raise WireError(f"malformed tensor table: {e}") from None
