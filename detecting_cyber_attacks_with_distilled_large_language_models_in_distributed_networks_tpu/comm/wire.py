"""Non-executable wire format for model weights: JSON manifest + flat arrays.

Replaces the reference's ``gzip(pickle(state_dict))`` (reference
client1.py:228-243, server.py:18-27). ``pickle.loads`` on unauthenticated
network bytes is remote code execution by design (SURVEY.md §5); this format
cannot encode code — a message is::

    MAGIC 'FTPW' | u32 version | u32 header_len | header JSON | payload bytes

where the header lists every tensor as ``{key, dtype, shape, enc, offset,
nbytes}`` plus a payload CRC-32 and a free-form JSON ``meta`` (client id,
round, sample count). Tensor keys are '/'-joined paths through the nested
params dict, so decode rebuilds the pytree with no embedded type tags.

One optional meta field is a cross-cutting contract rather than a caller
convention: ``meta["trace"]`` (obs/trace.py TRACE_META_KEY) carries the
server-minted round trace id in every aggregate reply, giving both ends
of a round the shared span identity the ``fedtpu obs`` timeline merges
on. It is plain meta — peers that omit or ignore it interop unchanged,
so tracing deploys one process at a time.

Optional ``compression="bf16"`` packs float32 tensors to bfloat16 via the
native fedwire library (comm/native.py) — a 2x cut that matches TPU compute
precision, instead of the reference's ~11 s/round byte-level gzip.
``compression="int8"`` goes further (4x vs fp32): symmetric per-row
quantization, each leading-axis row carrying its own fp32 scale
(``max|row| / 127``) prepended to the tensor's payload segment. Worst-case
per-weight error is half a quantization step (~0.4% of the row's max) —
lossier than bf16; an opt-in bandwidth/fidelity trade for slow links.
``compression="int8c"`` is the same 4x with the fp32 scales keyed to
FIXED element chunks instead of tensor rows (comm/quant.py): uniform
scale resolution for every leaf shape and a size computable from the
element count alone, which is what the capability-negotiated
``--wire-dtype int8`` streamed uploads ride (WIRE_DTYPE_META_KEY below).

**Streamed uploads** (PR 5): a capability-negotiated alternative to the
single ``FTPW`` frame for model-sized uploads. The server advertises
``meta["stream"] = <chunk bytes>`` in its aggregate replies (plain meta,
exactly like the ``trace`` field — old peers ignore it and keep sending
single frames); a capable client then ships its NEXT upload as::

    STRH frame   stream header: magic + u32 version + u32 header_len +
                 header JSON (the same tensor table/meta as FTPW, plus
                 chunk_bytes) [+ 32-byte HMAC tag in auth mode]
    STRC frames  sequential payload chunks: magic + u64 seq + bytes
                 [+ tag]; sent fire-and-forget (framing await_ack=False)
                 so chunk k+1 is packed while k is on the wire
    STRT frame   trailer: magic + u64 chunk count [+ tag]; ACKed — the
                 upload-complete handshake

Tensor extents in a stream header must be CONTIGUOUS (offset 0, each
tensor abutting the previous): the receiver decodes leaf-by-leaf as
chunk bytes arrive and never materializes the whole payload. Integrity
is per-frame (framing CRC); in auth mode every frame carries its own
HMAC tag bound to the connection nonce and chunk sequence number, so the
receiver can fold a chunk into its running aggregate the moment it
arrives without trusting unauthenticated bytes.

**Streamed replies** (PR 7): the same three frames carry the aggregate
BACK to the client. A capable client advertises with
``meta[STREAM_REPLY_META_KEY] = 1`` in its upload meta (plain meta — an
old server ignores it and keeps sending one dense reply frame); the
server then ships that client's reply as STRH + STRC... + STRT instead
of one model-sized frame, and the client decodes (and can place) each
leaf as its bytes land. Every stream frame takes a ``direction``:
``"up"`` (upload) and ``"down"`` (reply) use DISJOINT HMAC domains, so
an on-path attacker cannot reflect a client's own authenticated upload
chunks back at it as the "aggregate" — the upload-domain tags verify
under no reply-domain check.

``compression="topk"`` / ``"topk:<frac>"`` keeps only the largest-magnitude
``frac`` of each fp32 tensor's entries (default 1%): per-tensor payload is
``u32 k | int32 indices[k] | fp32 values[k]`` — 8 bytes per kept entry, so
~50x smaller than fp32 at frac=0.01; decode scatters back to a dense
zero-filled tensor. On its own
this is extremely lossy — it exists for the *sparse round-delta* exchange
(comm/client.py ``FederatedClient`` with a topk compression: uploads become
top-k round deltas with client-held error feedback, so dropped mass is
carried to the next round, never lost).
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import json
import struct
from typing import Any, Mapping

import numpy as np

from . import native
from .quant import dequantize_int8c, int8c_nbytes, quantize_int8c

MAGIC = b"FTPW"
VERSION = 1
#: HMAC-SHA256 tag appended after the payload when a shared key is used.
AUTH_TAG_LEN = 32
_AUTH_SCHEME = "hmac-sha256"
#: Challenge frame sent by an authenticated server on connect:
#: NONCE_MAGIC + NONCE_LEN random bytes, echoed in the client's header.
NONCE_MAGIC = b"NONC"
NONCE_LEN = 16
#: Round-advert frame sent by a secure-aggregation server on connect (after
#: the nonce challenge, if any): ROUND_MAGIC + u64 little-endian round
#: index + SESSION_LEN random session bytes (fresh per server run).
#: Clients derive their pairwise mask streams from (session, round), so all
#: participants of a round mask consistently and a mask stream is never
#: reused across rounds or server restarts (reuse would let an observer
#: difference two uploads and unmask a client's weight delta).
ROUND_MAGIC = b"RNDX"
SESSION_LEN = 16
#: DH key-exchange frames for per-pair secure-aggregation masks
#: (comm/secure.py): the client answers the round advert with
#: PUBKEY_MAGIC + u64 client_id + its 256-byte ephemeral public value
#: (+ an HMAC tag in auth mode); the server, once every participant's key
#: arrived, replies KEYS_MAGIC + num_clients x (u64 id + pubkey [+ tag]).
PUBKEY_MAGIC = b"DHPK"
KEYS_MAGIC = b"DHKS"
#: Central-DP handshake (after the nonce, if any; before the secure round
#: advert). The server speaks FIRST — DP_MAGIC + f64 clip + f64 noise
#: multiplier + f64 sampling rate q — so a mis-configured plain client
#: can diagnose the mode mismatch; the client identifies itself
#: (DPID_MAGIC + i64 client_id) and the server answers the per-round
#: Poisson cohort verdict (DPCOHORT_MAGIC + u8 sampled). A sampled
#: client proceeds with its clipped-round-delta upload; a non-sampled
#: one sits the round out but still receives the round's reply (its base
#: must track the fleet's). The DP reply is the noised mean delta over
#: the round's contributors — the server never holds absolute weights —
#: or a "noop" marker for an empty cohort.
DP_MAGIC = b"DPAD"
DPID_MAGIC = b"DPID"
DPCOHORT_MAGIC = b"DPCO"
#: Auth-mode sit-out acknowledgment: a non-sampled client proves key
#: knowledge — DPSKIP_MAGIC + HMAC(auth_key, domain + nonce + id) —
#: before the server registers it for the round's reply (without this an
#: unauthenticated connection could claim a sitting-out id, evict the
#: real client's registration, and collect the aggregate).
DPSKIP_MAGIC = b"DPSK"
DPSKIP_DOMAIN = b"fedtpu-dp-skip-v1"
#: Online scoring frames (serving/protocol.py), riding the same length-
#: framed transport in fire-and-forget mode (framing.send_frame
#: await_ack=False): SCORE_REQ carries one flow record (text or raw
#: features) + a per-request deadline; SCORE_REP answers with P(attack)
#: plus the serving telemetry (model round, batch size, queue wait);
#: SCORE_REJ is the explicit 503-style admission-control refusal — a
#: shed request is TOLD it was shed instead of hanging to its deadline.
SCORE_REQ_MAGIC = b"SCRQ"
SCORE_REP_MAGIC = b"SCRP"
SCORE_REJ_MAGIC = b"SCRJ"
#: Scoring-port authentication (serving/protocol.py): with ``--auth`` the
#: scoring server reuses the FL tier's challenge-response — it opens every
#: connection with the NONCE_MAGIC challenge above, and the client must
#: answer SCORE_AUTH_MAGIC + HMAC-SHA256(key, domain + nonce) before any
#: request is read. Connection-level (one proof per connection, not per
#: request): the scoring hot path stays HMAC-free, and a captured proof is
#: useless on any other connection (fresh nonce). Without a key the port
#: is the reference-style open protocol, as before.
SCORE_AUTH_MAGIC = b"SCAU"
SCORE_AUTH_DOMAIN = b"fedtpu-score-auth-v1"
#: Scoring-fleet stats frames (serving/protocol.py): SCORE_STAT requests
#: a ``stats()`` snapshot over the scoring connection itself and
#: SCORE_STATR answers with it — the in-band health/telemetry probe the
#: router tier (router/) load-balances and ejects replicas on. In-band
#: on purpose: a probe exercises the same socket, auth handshake, and
#: reader thread a real request rides, so "probe healthy" cannot
#: diverge from "requests flow".
SCORE_STAT_MAGIC = b"SCST"
SCORE_STATR_MAGIC = b"SCSR"
#: Scoring-fleet reload choreography (serving/protocol.py): SCORE_RELOAD
#: asks a replica to drain-then-reload NOW — check its checkpoint/registry
#: watcher immediately (bypassing the poll interval) at the next batch
#: boundary — and SCORE_RELOADR answers once the adoption attempt
#: finished, carrying whether anything was adopted and the round now
#: serving. In-band like the stats probe, which is what lets a router/
#: fleet manager coordinate drain-first rolling reloads across
#: OUT-of-process replicas it cannot hot-swap directly: drain the pick
#: set, send SCORE_RELOAD on the same authenticated backend connection,
#: readmit on the reply.
SCORE_RELOAD_MAGIC = b"SCRL"
SCORE_RELOADR_MAGIC = b"SCRD"
#: Streamed-upload frames (module docstring "Streamed uploads"): header,
#: sequential payload chunk, trailer. The capability rides reply meta
#: under STREAM_META_KEY as the server's preferred chunk byte count.
STREAM_MAGIC = b"STRH"
STREAM_CHUNK_MAGIC = b"STRC"
STREAM_END_MAGIC = b"STRT"
STREAM_META_KEY = "stream"
#: Upload-meta advert for chunk-streamed REPLIES (module docstring
#: "Streamed replies"): a truthy value means this client decodes
#: STRH/STRC/STRT reply frames; old servers ignore it (plain meta).
STREAM_REPLY_META_KEY = "stream_reply"
#: Upload-meta re-home marker (comm/client.py fallback parents): a truthy
#: value means this upload comes from a client whose ranked parent list
#: moved it off a dead primary. The adoptive server folds it as an EXTRA
#: contributor — it never counts toward the subtree's own quorum — so a
#: re-homed cohort can complete a degraded round without masking a local
#: straggler miss. Plain meta: old servers treat the upload as any other.
REHOME_META_KEY = "rehomed"
#: Upload-meta contributor record on a relay's UPWARD upload
#: (comm/relay.py): the ascending client ids its subtree partial folded.
#: The root keeps the per-round (relay -> contributors) assignment from
#: these — the replay input for the crc contract over the round's ACTUAL
#: tree — and refuses a round where two subtrees claim one client (a
#: re-homed upload double-counted by a surviving old parent).
SUBTREE_IDS_META_KEY = "subtree_ids"
#: Strategy stamp (strategies/). On a round REPLY: the
#: ``{"name", "params"}`` describe() of the strategy that produced this
#: round's global, doubling as the round-START advert for the next round
#: (a fedprox advert carries the mu clients should train with). On a
#: relay's UPWARD upload: the strategy id the relay believes the fleet
#: runs — the root refuses the round when it mismatches the root's
#: active strategy (a split-brain fleet folding under two different
#: aggregation rules). Plain meta: old peers ignore it.
STRATEGY_META_KEY = "strategy"
#: Reply-meta capability advert for QUANTIZED streamed uploads (the
#: ``--wire-dtype`` negotiation): the list of lossy stream encodings this
#: server will dequantize before folding (e.g. ``["bf16", "int8c"]``).
#: Exactly the STREAM_META_KEY pattern — plain meta, one reply behind:
#: a client configured with ``--wire-dtype int8`` keeps uploading fp32
#: until a reply carries the advert (round 1, old servers, and every
#: dense retry stay bit-identical to today's wire), then upgrades its
#: streamed leaves to the negotiated encoding.
WIRE_DTYPE_META_KEY = "wire_dtypes"
#: ``--wire-dtype`` values -> the stream leaf encoding each negotiates.
#: ``fp32`` is the identity (no advert needed, nothing changes on the
#: wire); ``int8`` maps to the per-chunk-scale codec (comm/quant.py),
#: NOT the per-row ``int8`` — fixed element chunks give every leaf shape
#: uniform scale resolution and a plannable encoded size.
WIRE_DTYPE_ENCS = {"fp32": "raw", "bf16": "bf16", "int8": "int8c"}
#: Upload-meta capability advert for QUANTIZED streamed *replies* (the
#: server-side ``--reply-dtype`` negotiation — the mirror of
#: WIRE_DTYPE_META_KEY's upload leg): the list of lossy stream encodings
#: this client will dequantize when the server streams the round's
#: global back down (e.g. ``["bf16", "int8c"]``). Plain meta: an old
#: server ignores it and keeps replying fp32; a client that doesn't
#: advertise keeps receiving fp32 from a ``--reply-dtype int8`` server
#: (capability-negotiated per client, never assumed).
REPLY_DTYPE_META_KEY = "reply_dtypes"
DEFAULT_STREAM_CHUNK = 4 << 20  # 4 MiB: bounds receiver buffering
#: Worst-case STRC frame bytes beyond the chunk data itself (magic + u64
#: seq + auth tag). A configured/advertised chunk size must leave this
#: headroom under framing.MAX_FRAME, or the largest chunk would encode
#: into a frame the transport refuses to send.
STREAM_CHUNK_OVERHEAD = len(STREAM_CHUNK_MAGIC) + 8 + AUTH_TAG_LEN


def stream_chunk_bytes_from_mb(mb) -> int:
    """CLI ``--stream-chunk-mb`` value -> advertised chunk bytes
    (``None`` = the default advert). Shared by serve and controller so
    the two entrypoints can never diverge on the conversion rule."""
    if mb is None:
        return DEFAULT_STREAM_CHUNK
    return int(float(mb) * (1 << 20))
_STREAM_HDR_DOMAIN = b"fedtpu-stream-hdr-v1"
_STREAM_CHK_DOMAIN = b"fedtpu-stream-chk-v1"
_STREAM_END_DOMAIN = b"fedtpu-stream-end-v1"
#: Direction-bound HMAC domains for the stream frames: "up" = client
#: upload, "down" = server reply. Disjoint domains close the reflection
#: hole a shared domain would open — a client's own authenticated upload
#: chunks replayed back at it would otherwise carry valid tags for the
#: same (nonce, seq) and decode as the "aggregate".
_STREAM_DOMAINS = {
    "up": (_STREAM_HDR_DOMAIN, _STREAM_CHK_DOMAIN, _STREAM_END_DOMAIN),
    "down": (
        b"fedtpu-stream-rhdr-v1",
        b"fedtpu-stream-rchk-v1",
        b"fedtpu-stream-rend-v1",
    ),
}


def _stream_domains(direction: str) -> tuple[bytes, bytes, bytes]:
    try:
        return _STREAM_DOMAINS[direction]
    except KeyError:
        raise WireError(f"unknown stream direction {direction!r}") from None
#: Leaf encodings a stream may carry: the fixed-size ones whose encoded
#: byte count is computable from (dtype, shape) alone, so the header can
#: be built before any leaf is gathered off-device.
_STREAM_ENCS = ("raw", "bf16", "int8", "int8c")
_ALLOWED_DTYPES = {
    "float32", "float64", "float16", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
}


class WireError(ValueError):
    """Malformed, corrupt, or version-mismatched message."""


class ModeError(ValueError):
    """Client/server protocol-mode mismatch (e.g. --dp against a non-DP
    server). Deliberately NOT a WireError: retrying cannot help, so the
    client's retry loop must let it propagate immediately."""


# --------------------------------------------------- int8 row quantization
def _int8_rows(arr: np.ndarray) -> tuple[np.ndarray, int]:
    """View ``arr`` as [rows, cols] for per-row quantization (leading axis
    = rows; scalars/1-D become one row). Explicit cols so zero-size
    tensors reshape cleanly (reshape(-1) is ambiguous at size 0)."""
    rows = arr.shape[0] if arr.ndim >= 2 else 1
    cols = arr.size // rows if rows else 0
    return arr.reshape(rows, cols), rows


def quantize_int8(arr: np.ndarray) -> bytes:
    """fp32 tensor -> payload bytes: [rows x fp32 scale] + [int8 data]."""
    a, rows = _int8_rows(np.ascontiguousarray(arr, np.float32))
    amax = np.abs(a).max(axis=1) if a.size else np.zeros(rows, np.float32)
    scales = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(a / scales[:, None]), -127, 127).astype(np.int8)
    return scales.tobytes() + q.tobytes()


def dequantize_int8(raw, shape: tuple[int, ...]) -> np.ndarray:
    """Inverse of :func:`quantize_int8` for a tensor of ``shape``."""
    rows = shape[0] if len(shape) >= 2 else 1
    cols = int(np.prod(shape)) // rows if rows else 0
    want = 4 * rows + rows * cols
    if len(raw) != want:
        raise WireError(
            f"int8 tensor payload is {len(raw)} bytes, expected {want}"
        )
    scales = np.frombuffer(raw[: 4 * rows], np.float32)
    q = np.frombuffer(raw[4 * rows :], np.int8).reshape(rows, cols)
    return (q.astype(np.float32) * scales[:, None]).reshape(shape)


# ------------------------------------------------------ top-k sparsification
DEFAULT_TOPK_FRAC = 0.01
#: Densified-tensor allocation cap. For raw/bf16/int8 the payload itself
#: scales with the claimed shape (and is bounded by framing.MAX_FRAME =
#: 8 GiB), but a topk payload is ~8 bytes per kept entry regardless of the
#: claimed dense shape — a ~50-byte message claiming shape [1e12] would
#: otherwise trigger a multi-TB np.zeros on the receiver (memory-
#: amplification DoS on the default unauthenticated server). Mirror the
#: frame bound: no legitimate tensor can exceed what one frame can carry.
MAX_DENSE_TENSOR_BYTES = 8 << 30


def parse_compression(spec: str) -> tuple[str, float | None]:
    """``"topk:0.05"`` -> ``("topk", 0.05)``; plain modes -> ``(spec, None)``."""
    if spec.startswith("topk"):
        frac = DEFAULT_TOPK_FRAC
        if spec != "topk":
            if not spec.startswith("topk:"):
                raise WireError(f"unknown compression {spec!r}")
            try:
                frac = float(spec.split(":", 1)[1])
            except ValueError:
                raise WireError(f"bad topk fraction in {spec!r}") from None
        if not 0.0 < frac <= 1.0:
            raise WireError(f"topk fraction {frac} outside (0, 1]")
        return "topk", frac
    if spec not in ("none", "bf16", "int8", "int8c"):
        raise WireError(f"unknown compression {spec!r}")
    return spec, None


def sparsify_topk(arr: np.ndarray, frac: float) -> bytes:
    """fp32 tensor -> ``u32 k | int32 idx[k] | fp32 vals[k]`` payload,
    keeping the ``k = max(1, round(frac * size))`` largest-|value| entries.
    Indices are sorted so decode's scatter is sequential."""
    a = np.ascontiguousarray(arr, np.float32).reshape(-1)
    if a.size == 0:
        return struct.pack("<I", 0)
    k = max(1, int(round(frac * a.size)))
    if k >= a.size:
        idx = np.arange(a.size, dtype=np.int32)
    else:
        idx = np.sort(np.argpartition(np.abs(a), -k)[-k:]).astype(np.int32)
    return struct.pack("<I", len(idx)) + idx.tobytes() + a[idx].tobytes()


def densify_topk(raw, shape: tuple[int, ...]) -> np.ndarray:
    """Inverse of :func:`sparsify_topk`: zeros everywhere but the kept
    entries. Bounds-checks everything — the payload is untrusted."""
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if size < 0 or 4 * size > MAX_DENSE_TENSOR_BYTES:
        # Checked BEFORE any allocation: the shape is attacker-controlled
        # and, unlike the dense encodings, unbacked by payload bytes.
        raise WireError(
            f"topk tensor claims dense size {size} "
            f"(> {MAX_DENSE_TENSOR_BYTES // 4} elements)"
        )
    if len(raw) < 4:
        raise WireError("topk tensor payload shorter than its count field")
    (k,) = struct.unpack("<I", bytes(raw[:4]))
    if k > size:
        raise WireError(f"topk count {k} exceeds dense tensor size {size}")
    if len(raw) != 4 + 8 * k:
        raise WireError(
            f"topk tensor payload is {len(raw)} bytes, expected {4 + 8 * k}"
        )
    idx = np.frombuffer(raw, np.int32, count=k, offset=4)
    vals = np.frombuffer(raw, np.float32, count=k, offset=4 + 4 * k)
    out = np.zeros(size, np.float32)
    if k:
        if idx.min() < 0 or idx.max() >= size:
            raise WireError("topk index out of tensor bounds")
        out[idx] = vals
    return out.reshape(shape)


class PreEncoded:
    """A tensor whose wire payload is already built (``enc``/``buf``/
    ``shape``/``dtype``): lets a caller that must inspect the encoded form
    anyway (the sparse-delta client mirrors the kept entries for its
    error-feedback residual) hand the bytes straight to :func:`encode`
    instead of paying the top-k selection twice."""

    __slots__ = ("enc", "buf", "shape", "dtype")

    def __init__(self, enc: str, buf: bytes, shape: tuple, dtype: str = "float32"):
        self.enc = enc
        self.buf = buf
        self.shape = tuple(int(s) for s in shape)
        self.dtype = dtype


def flat_l2_norm(flat: Mapping[str, Any]) -> float:
    """Global L2 norm across all tensors of a flat param/delta dict,
    accumulated in float64 — the single norm both the DP client's clip
    and the DP server's re-clip enforcement compute (their tolerance
    contract depends on both sides agreeing)."""
    return float(
        np.sqrt(
            sum(
                float(np.sum(np.asarray(v, np.float64) ** 2))
                for v in flat.values()
            )
        )
    )


def clip_flat(
    flat: Mapping[str, Any], clip: float
) -> tuple[dict[str, np.ndarray], float, float]:
    """Scale a flat delta dict to global L2 norm <= ``clip``; returns
    ``(clipped fp32 dict, original norm, applied scale)``."""
    norm = flat_l2_norm(flat)
    scale = min(1.0, clip / max(norm, 1e-12))
    return (
        {
            k: np.asarray(v, np.float32) * np.float32(scale)
            for k, v in flat.items()
        },
        norm,
        scale,
    )


def shapes_compatible(a: Mapping[str, Any], b: Mapping[str, Any]) -> bool:
    """True when two flat param dicts have identical key sets and per-key
    array shapes — i.e. delta/residual arithmetic between them is
    well-defined. Shared by the sparse-delta client (params vs base,
    residual vs params) and server (delta upload vs base)."""
    if set(a) != set(b):
        return False
    return all(np.asarray(a[k]).shape == np.asarray(b[k]).shape for k in a)


def flat_crc32(flat: Mapping[str, Any]) -> int:
    """Order-independent-of-construction checksum of a flat fp32 param
    dict: CRC-32 over the sorted-key concatenation of raw tensor bytes.
    The sparse-delta exchange uses it as the base-agreement contract —
    the server stamps its exact aggregate's crc into the reply, and a
    client only adopts the decoded reply as a delta base when its own
    crc matches (a lossy reply compression, e.g. int8, would silently
    bias every sparse round otherwise)."""
    crc = 0
    for key in sorted(flat):
        arr = np.ascontiguousarray(np.asarray(flat[key], np.float32))
        crc = native.crc32(np.frombuffer(arr.tobytes(), np.uint8), crc)
    return crc & 0xFFFFFFFF


# ------------------------------------------------------- pytree <-> flat
def flatten_params(
    tree: Any, *, sep: str = "/", leaf_fn=np.asarray
) -> dict[str, np.ndarray]:
    """Nested dict of arrays -> sorted flat ``{'a/b/c': ndarray}``.
    ``leaf_fn`` is the leaf conversion — the ONE recursive walk (key
    validation included) serves both the eager wire path and
    :func:`flatten_lazy`'s deferred-gather variant."""
    out: dict[str, np.ndarray] = {}

    def _walk(node, prefix):
        if isinstance(node, Mapping):
            for key in node:
                if sep in str(key):
                    raise WireError(f"param key {key!r} contains separator {sep!r}")
                _walk(node[key], f"{prefix}{sep}{key}" if prefix else str(key))
        else:
            out[prefix] = leaf_fn(node)

    _walk(tree, "")
    return dict(sorted(out.items()))


def unflatten_params(flat: Mapping[str, np.ndarray], *, sep: str = "/") -> dict:
    """Inverse of ``flatten_params``."""
    tree: dict = {}
    for path, value in flat.items():
        parts = path.split(sep)
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
            if not isinstance(node, dict):
                raise WireError(f"key path {path!r} collides with a tensor")
        node[parts[-1]] = value
    return tree


# ----------------------------------------------------------------- encode
def encode(
    params: Any,
    *,
    meta: Mapping[str, Any] | None = None,
    compression: str = "none",
    auth_key: bytes | None = None,
) -> bytes:
    """Params pytree (nested dict or flat dict of arrays) -> wire bytes.

    ``auth_key``: shared-secret HMAC-SHA256 over the entire message,
    appended as a 32-byte trailing tag. The reference's protocol has no
    authentication at all (any peer that can connect injects weights,
    server.py:57-65); a keyed decoder rejects unauthenticated or tampered
    messages."""
    compression, topk_frac = parse_compression(compression)
    flat = (
        dict(params)
        if isinstance(params, Mapping) and all(not isinstance(v, Mapping) for v in params.values())
        else flatten_params(params)
    )
    tensors = []
    chunks: list[bytes] = []
    offset = 0
    for key, arr in flat.items():
        if isinstance(arr, PreEncoded):
            tensors.append(
                {
                    "key": key,
                    "dtype": arr.dtype,
                    "shape": list(arr.shape),
                    "enc": arr.enc,
                    "offset": offset,
                    "nbytes": len(arr.buf),
                }
            )
            chunks.append(arr.buf)
            offset += len(arr.buf)
            continue
        arr = np.asarray(arr)
        dtype = str(arr.dtype)
        if dtype not in _ALLOWED_DTYPES:
            raise WireError(f"tensor {key!r} has unsupported dtype {dtype}")
        if compression == "bf16" and arr.dtype == np.float32:
            buf = np.ascontiguousarray(native.pack_bf16(arr)).tobytes()
            enc = "bf16"
        elif compression == "int8" and arr.dtype == np.float32:
            buf = quantize_int8(arr)
            enc = "int8"
        elif compression == "int8c" and arr.dtype == np.float32:
            buf = quantize_int8c(arr)
            enc = "int8c"
        elif compression == "topk" and arr.dtype == np.float32:
            buf = sparsify_topk(arr, topk_frac)
            enc = "topk"
        else:
            buf = np.ascontiguousarray(arr).tobytes()
            enc = "raw"
        tensors.append(
            {
                "key": key,
                "dtype": dtype,
                "shape": list(arr.shape),
                "enc": enc,
                "offset": offset,
                "nbytes": len(buf),
            }
        )
        chunks.append(buf)
        offset += len(buf)
    payload = b"".join(chunks)
    header = {
        "tensors": tensors,
        "payload_nbytes": len(payload),
        "payload_crc32": native.crc32(payload),
        "meta": dict(meta or {}),
    }
    if auth_key is not None:
        header["auth"] = _AUTH_SCHEME
    hbytes = json.dumps(header, separators=(",", ":")).encode()
    msg = MAGIC + struct.pack("<II", VERSION, len(hbytes)) + hbytes + payload
    if auth_key is not None:
        msg += hmac_mod.new(auth_key, msg, hashlib.sha256).digest()
    return msg


def decode_tensor_entry(t: Mapping[str, Any], raw) -> np.ndarray:
    """One tensor-table entry's payload bytes -> ndarray. The shared
    per-leaf decoder of the single-frame path (:func:`decode`) and the
    streamed path (leaves decode as their bytes complete) — one
    implementation so the two can never disagree on decoded values."""
    dtype = t["dtype"]
    if dtype not in _ALLOWED_DTYPES:
        raise WireError(f"tensor {t.get('key')!r} has unsupported dtype {dtype}")
    if t["enc"] == "bf16":
        packed = np.frombuffer(raw, np.uint16)
        return native.unpack_bf16(packed, shape=tuple(t["shape"]))
    if t["enc"] == "int8":
        return dequantize_int8(raw, tuple(t["shape"]))
    if t["enc"] == "int8c":
        return dequantize_int8c(raw, tuple(t["shape"]))
    if t["enc"] == "topk":
        return densify_topk(raw, tuple(t["shape"]))
    if t["enc"] == "raw":
        return np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(t["shape"])
    raise WireError(f"unknown tensor encoding {t['enc']!r}")


# ----------------------------------------------------------------- decode
def decode(
    data: bytes | memoryview, *, auth_key: bytes | None = None
) -> tuple[dict, dict]:
    """Wire bytes -> ``(nested params dict, meta dict)``; verifies the CRC.

    With ``auth_key`` set, only messages carrying a valid HMAC-SHA256 tag
    are accepted — unauthenticated, tampered, or wrong-key messages raise
    :class:`WireError`. Without a key, a trailing tag (if any) is ignored
    (the peer authenticated; this side did not configure a key)."""
    view = memoryview(data)
    if len(view) < 12 or bytes(view[:4]) != MAGIC:
        raise WireError("bad magic: not a fedwire message")
    version, hlen = struct.unpack("<II", view[4:12])
    if version != VERSION:
        raise WireError(f"wire version {version} unsupported (expected {VERSION})")
    if len(view) < 12 + hlen:
        raise WireError("truncated header")
    try:
        header = json.loads(bytes(view[12 : 12 + hlen]).decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"malformed header: {e}") from None

    auth = header.get("auth")
    if auth not in (None, _AUTH_SCHEME):
        raise WireError(f"unknown auth scheme {auth!r}")
    if auth_key is not None and auth != _AUTH_SCHEME:
        raise WireError(
            f"unauthenticated message rejected (this side requires {_AUTH_SCHEME})"
        )
    if auth == _AUTH_SCHEME:
        # Tag boundary computed once for both verification and payload slice.
        if len(view) < 12 + hlen + AUTH_TAG_LEN:
            raise WireError("truncated auth tag")
        body_end = len(view) - AUTH_TAG_LEN
        if auth_key is not None:
            tag = bytes(view[body_end:])
            want = hmac_mod.new(auth_key, view[:body_end], hashlib.sha256).digest()
            if not hmac_mod.compare_digest(tag, want):
                raise WireError("HMAC verification failed (tampered or wrong key)")
        payload = view[12 + hlen : body_end]
    else:
        payload = view[12 + hlen :]
    if len(payload) != header.get("payload_nbytes"):
        raise WireError(
            f"payload length {len(payload)} != declared {header.get('payload_nbytes')}"
        )
    crc = native.crc32(np.frombuffer(payload, np.uint8))
    if crc != header.get("payload_crc32"):
        raise WireError(
            f"payload CRC mismatch (got {crc:#010x}, "
            f"header says {header.get('payload_crc32', 0):#010x})"
        )
    flat: dict[str, np.ndarray] = {}
    # Header fields are attacker-controlled; any inconsistency (missing keys,
    # shape/nbytes disagreement, bad offsets) must surface as WireError, not
    # leak as ValueError/KeyError and kill a server thread.
    try:
        tensors = header["tensors"]
        # Per-MESSAGE dense-size cap: the per-tensor cap alone still lets
        # one small frame list many near-cap topk tensors (claimed shapes
        # are unbacked by payload bytes), amplifying to hundreds of GiB of
        # np.zeros — and under Linux overcommit that is an OOM-kill of the
        # whole process later, not a catchable MemoryError now.
        claimed = sum(
            int(np.prod(t["shape"], dtype=np.int64)) * 4
            for t in tensors
            if t.get("enc") == "topk"
        )
        if claimed > MAX_DENSE_TENSOR_BYTES:
            raise WireError(
                f"message claims {claimed} dense bytes across topk tensors "
                f"(> {MAX_DENSE_TENSOR_BYTES})"
            )
        for t in tensors:
            key, dtype = t["key"], t["dtype"]
            if dtype not in _ALLOWED_DTYPES:
                raise WireError(f"tensor {key!r} has unsupported dtype {dtype}")
            offset, nbytes = int(t["offset"]), int(t["nbytes"])
            if offset < 0 or nbytes < 0 or offset + nbytes > len(payload):
                # Explicit bounds: a negative offset would slice from the
                # payload's tail and alias another tensor's bytes.
                raise WireError(f"tensor {key!r} has out-of-bounds extent")
            raw = payload[offset : offset + nbytes]
            flat[key] = decode_tensor_entry(t, raw)
        return unflatten_params(flat), dict(header.get("meta", {}))
    except WireError:
        raise
    except (KeyError, ValueError, TypeError, OverflowError, AttributeError) as e:
        # OverflowError: a claimed dim too large for int64 (np.prod cap
        # math); AttributeError: a tensor entry that isn't a dict. Both
        # reachable from attacker-controlled headers and must surface as
        # WireError, not kill a server thread.
        raise WireError(f"malformed tensor table: {e}") from None


# ------------------------------------------------------- streamed uploads
def flatten_lazy(tree: Any, *, sep: str = "/") -> dict[str, Any]:
    """Like :func:`flatten_params` but WITHOUT ``np.asarray`` on leaves:
    device-backed arrays (a meshed TCP client's replicated params) stay
    on device, so the streamed upload's packer can gather leaf k+1 to
    host while chunk k is already on the wire. Leaves only need
    ``.shape``/``.dtype`` for the plan; an already-flat dict passes
    through (sorted)."""
    def _leaf(node):
        # Shape/dtype metadata is all the plan needs; anything without it
        # (a python scalar) is converted now — it is tiny by definition.
        if isinstance(node, PreEncoded) or (
            hasattr(node, "dtype") and hasattr(node, "shape")
        ):
            return node
        return np.asarray(node)

    if isinstance(tree, Mapping) and tree and all(
        not isinstance(v, Mapping) for v in tree.values()
    ):
        return dict(sorted((str(k), _leaf(v)) for k, v in tree.items()))
    return flatten_params(tree, sep=sep, leaf_fn=_leaf)


def _leaf_plan(key: str, leaf: Any, compression: str) -> dict:
    """One tensor-table entry (enc + exact encoded byte count) computed
    from metadata alone — no host gather, no encode."""
    if isinstance(leaf, PreEncoded):
        return {
            "key": key,
            "dtype": leaf.dtype,
            "shape": list(leaf.shape),
            "enc": leaf.enc,
            "nbytes": len(leaf.buf),
        }
    dtype = str(np.dtype(leaf.dtype))
    if dtype not in _ALLOWED_DTYPES:
        raise WireError(f"tensor {key!r} has unsupported dtype {dtype}")
    shape = tuple(int(s) for s in leaf.shape)
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if compression == "bf16" and dtype == "float32":
        enc, nbytes = "bf16", 2 * size
    elif compression == "int8" and dtype == "float32":
        rows = shape[0] if len(shape) >= 2 else 1
        enc, nbytes = "int8", 4 * rows + size
    elif compression == "int8c" and dtype == "float32":
        enc, nbytes = "int8c", int8c_nbytes(size)
    else:
        enc, nbytes = "raw", size * np.dtype(dtype).itemsize
    return {"key": key, "dtype": dtype, "shape": list(shape), "enc": enc,
            "nbytes": nbytes}


def plan_stream(
    flat: Mapping[str, Any], compression: str = "none"
) -> tuple[list[dict], int]:
    """Flat (possibly lazy) param dict -> (contiguous tensor table,
    payload_nbytes). ``topk`` is not plannable (its encoded size depends
    on the values) — sparse-delta clients keep the single-frame path."""
    comp, _ = parse_compression(compression)
    if comp == "topk":
        raise WireError("topk uploads cannot be streamed (size is data-dependent)")
    tensors: list[dict] = []
    offset = 0
    for key, leaf in flat.items():
        t = _leaf_plan(key, leaf, comp)
        t["offset"] = offset
        offset += int(t["nbytes"])
        tensors.append(t)
    return tensors, offset


def encode_stream_leaf(leaf: Any, enc: str) -> bytes:
    """Materialize one planned leaf's payload bytes (the single host
    gather for a device-backed leaf happens here, at pack time)."""
    if isinstance(leaf, PreEncoded):
        return leaf.buf
    arr = np.asarray(leaf)
    if enc == "bf16":
        return np.ascontiguousarray(native.pack_bf16(arr)).tobytes()
    if enc == "int8":
        return quantize_int8(arr)
    if enc == "int8c":
        return quantize_int8c(arr)
    if enc == "raw":
        return np.ascontiguousarray(arr).tobytes()
    raise WireError(f"unknown stream leaf encoding {enc!r}")


def _stream_tag(domain: bytes, auth_key: bytes, nonce: bytes, body: bytes) -> bytes:
    return hmac_mod.new(auth_key, domain + nonce + body, hashlib.sha256).digest()


def encode_stream_header(
    tensors: list[dict],
    *,
    meta: Mapping[str, Any] | None = None,
    chunk_bytes: int,
    payload_nbytes: int,
    auth_key: bytes | None = None,
    direction: str = "up",
) -> bytes:
    """Build the STRH frame payload. In auth mode the tag covers the full
    prefix (magic + version + header JSON) under the direction's own
    domain; replay protection comes from the connection nonce the meta
    already carries (same contract as the single-frame upload's
    freshness check)."""
    hdr_domain, _, _ = _stream_domains(direction)
    header = {
        "tensors": tensors,
        "payload_nbytes": int(payload_nbytes),
        "chunk_bytes": int(chunk_bytes),
        "meta": dict(meta or {}),
    }
    if auth_key is not None:
        header["auth"] = _AUTH_SCHEME
    hbytes = json.dumps(header, separators=(",", ":")).encode()
    msg = STREAM_MAGIC + struct.pack("<II", VERSION, len(hbytes)) + hbytes
    if auth_key is not None:
        msg += _stream_tag(hdr_domain, auth_key, b"", msg)
    return msg


def decode_stream_header(
    data,
    *,
    auth_key: bytes | None = None,
    max_payload: int = 8 << 30,
    direction: str = "up",
) -> tuple[list[dict], dict, int, int]:
    """STRH frame -> (tensor table, meta, chunk_bytes, payload_nbytes).

    Validates everything the single-frame decoder validates — dtype
    allowlist, stream-safe encodings, extent bounds — plus the streamed
    path's extra invariant: tensor extents must be contiguous (offset 0,
    each abutting the previous, total == payload_nbytes), which is what
    lets the receiver decode leaves in one sequential pass."""
    hdr_domain, _, _ = _stream_domains(direction)
    view = memoryview(data)
    if len(view) < 12 or bytes(view[:4]) != STREAM_MAGIC:
        raise WireError("bad magic: not a stream header")
    version, hlen = struct.unpack("<II", view[4:12])
    if version != VERSION:
        raise WireError(f"stream version {version} unsupported (expected {VERSION})")
    if len(view) < 12 + hlen:
        raise WireError("truncated stream header")
    body_end = 12 + hlen
    try:
        header = json.loads(bytes(view[12:body_end]).decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"malformed stream header: {e}") from None
    auth = header.get("auth")
    if auth not in (None, _AUTH_SCHEME):
        raise WireError(f"unknown auth scheme {auth!r}")
    if auth_key is not None:
        if auth != _AUTH_SCHEME:
            raise WireError(
                f"unauthenticated stream rejected (this side requires {_AUTH_SCHEME})"
            )
        if len(view) != body_end + AUTH_TAG_LEN:
            raise WireError("stream header missing its auth tag")
        want = _stream_tag(
            hdr_domain, auth_key, b"", bytes(view[:body_end])
        )
        if not hmac_mod.compare_digest(bytes(view[body_end:]), want):
            raise WireError("stream header HMAC verification failed")
    try:
        tensors = list(header["tensors"])
        payload_nbytes = int(header["payload_nbytes"])
        chunk_bytes = int(header["chunk_bytes"])
        if not 0 < chunk_bytes <= max_payload:
            raise WireError(f"stream chunk_bytes {chunk_bytes} out of range")
        if not 0 <= payload_nbytes <= max_payload:
            raise WireError(f"stream payload {payload_nbytes} out of range")
        offset = 0
        for t in tensors:
            if t.get("enc") not in _STREAM_ENCS:
                raise WireError(
                    f"tensor {t.get('key')!r} has non-streamable encoding "
                    f"{t.get('enc')!r}"
                )
            if t["dtype"] not in _ALLOWED_DTYPES:
                raise WireError(
                    f"tensor {t.get('key')!r} has unsupported dtype {t['dtype']}"
                )
            if int(t["offset"]) != offset or int(t["nbytes"]) < 0:
                raise WireError(
                    f"tensor {t.get('key')!r} breaks the stream's contiguous "
                    "extent invariant"
                )
            offset += int(t["nbytes"])
        if offset != payload_nbytes:
            raise WireError(
                f"tensor extents sum to {offset}, header claims "
                f"{payload_nbytes} payload bytes"
            )
        keys = [t["key"] for t in tensors]
        if len(set(keys)) != len(keys):
            raise WireError("duplicate tensor key in stream header")
        return tensors, dict(header.get("meta", {})), chunk_bytes, payload_nbytes
    except WireError:
        raise
    except (KeyError, ValueError, TypeError, OverflowError, AttributeError) as e:
        raise WireError(f"malformed stream tensor table: {e}") from None


def encode_stream_chunk(
    seq: int,
    data: bytes,
    *,
    auth_key: bytes | None = None,
    nonce: bytes = b"",
    direction: str = "up",
) -> bytes:
    _, chk_domain, _ = _stream_domains(direction)
    body = STREAM_CHUNK_MAGIC + struct.pack("<Q", seq) + data
    if auth_key is not None:
        body += _stream_tag(chk_domain, auth_key, nonce, body)
    return body


def decode_stream_chunk(
    frame,
    *,
    expect_seq: int,
    auth_key: bytes | None = None,
    nonce: bytes = b"",
    direction: str = "up",
):
    """STRC frame -> chunk bytes (memoryview). Verifying the per-chunk
    tag BEFORE returning is what lets the server fold the chunk into its
    running aggregate immediately: every folded byte was authenticated,
    so a key-less attacker can't poison a round mid-stream."""
    _, chk_domain, _ = _stream_domains(direction)
    view = memoryview(frame)
    n_magic = len(STREAM_CHUNK_MAGIC)
    tag_len = AUTH_TAG_LEN if auth_key is not None else 0
    if len(view) < n_magic + 8 + tag_len or bytes(view[:n_magic]) != STREAM_CHUNK_MAGIC:
        raise WireError("bad stream chunk frame")
    (seq,) = struct.unpack("<Q", view[n_magic : n_magic + 8])
    if seq != expect_seq:
        raise WireError(f"stream chunk out of order (got {seq}, want {expect_seq})")
    body_end = len(view) - tag_len
    if auth_key is not None:
        want = _stream_tag(
            chk_domain, auth_key, nonce, bytes(view[:body_end])
        )
        if not hmac_mod.compare_digest(bytes(view[body_end:]), want):
            raise WireError(f"stream chunk {seq} HMAC verification failed")
    return view[n_magic + 8 : body_end]


def encode_stream_end(
    n_chunks: int,
    *,
    auth_key: bytes | None = None,
    nonce: bytes = b"",
    direction: str = "up",
) -> bytes:
    _, _, end_domain = _stream_domains(direction)
    body = STREAM_END_MAGIC + struct.pack("<Q", n_chunks)
    if auth_key is not None:
        body += _stream_tag(end_domain, auth_key, nonce, body)
    return body


def decode_stream_end(
    frame,
    *,
    expect_chunks: int,
    auth_key: bytes | None = None,
    nonce: bytes = b"",
    direction: str = "up",
) -> None:
    _, _, end_domain = _stream_domains(direction)
    view = memoryview(frame)
    n_magic = len(STREAM_END_MAGIC)
    tag_len = AUTH_TAG_LEN if auth_key is not None else 0
    if len(view) != n_magic + 8 + tag_len or bytes(view[:n_magic]) != STREAM_END_MAGIC:
        raise WireError("bad stream trailer frame")
    (n,) = struct.unpack("<Q", view[n_magic : n_magic + 8])
    if n != expect_chunks:
        raise WireError(
            f"stream trailer claims {n} chunks, received {expect_chunks}"
        )
    if auth_key is not None:
        body_end = len(view) - tag_len
        want = _stream_tag(
            end_domain, auth_key, nonce, bytes(view[:body_end])
        )
        if not hmac_mod.compare_digest(bytes(view[body_end:]), want):
            raise WireError("stream trailer HMAC verification failed")
