"""Streaming chunk aggregation for one round (comm/server.py PR 5).

The barrier aggregation path materializes every client's full state dict
and only then computes the weighted mean — O(N·model) peak memory, and
all of the aggregation compute exposed after the last upload finishes.
This module is the round's incremental alternative: uploads register an
*intent* (tensor key set + sample count, from the stream header or a
dense frame), leaves are handed over one at a time as their bytes
arrive, and the moment every fold-set member's copy of a leaf is present
the leaf is **folded** into the running mean and freed. Peak memory
drops toward O(model + in-flight leaves), and the fold work overlaps the
slower clients' remaining wire transfer.

Bit-exactness contract (pinned by tests): the folded result equals
``comm.server.aggregate_flat`` — the barrier mean — BIT-EXACTLY. That
holds because the fold replays the identical fp32 arithmetic in the
identical order: per key, ``acc = zeros; acc += float32(w_i) * leaf_i``
over clients in ascending-id order, with weights normalized in float64
exactly as the barrier does. fp32 addition is non-associative, so the
ascending-id order per leaf is not a style choice — it is what keeps the
base crc every DP/resync test pins unchanged.

Consequences of folding early (documented trade-offs):

* The fold set must be FROZEN before the first fold (weights are
  normalized over it). It freezes when every expected client's intent
  has arrived — milliseconds into a healthy round. If a client never
  shows up, nothing folds and ``finalize`` degrades to the barrier mean
  over the survivors at round close (quorum semantics unchanged, no
  overlap).
* A client that dies (or re-uploads) AFTER folds began poisons the
  round: its already-folded leaves cannot be subtracted back out. The
  round fails with a clear reason and clients retry; the next round's
  freeze simply never includes the dead client.
* A streamed DP upload that exceeds its declared clip can only be
  re-clipped server-side while none of its leaves have folded; once
  folds consumed unscaled leaves the round fails closed instead of
  widening the mechanism's sensitivity (the barrier path re-clips and
  proceeds; honest clients — which already clip client-side — never see
  the difference).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Mapping

import numpy as np

from . import wire
from ..ops import fold as fold_ops


class StreamAggPoisoned(RuntimeError):
    """The running aggregate can no longer reach a correct mean (a folded
    contributor died, re-uploaded, or violated its clip)."""


class StreamAgg:
    """One round's incremental weighted-mean state.

    Thread-safety: one internal lock serializes every mutation; folds run
    under it, which also serializes the fp32 accumulation (required for
    the bit-exactness contract — two concurrent folds of one key would
    race the accumulator).

    ``eager=False`` disables freezing/folding entirely: every upload is
    held and ``finalize`` computes the barrier mean at close. That is the
    non-pipelined A/B arm the bench compares against.
    """

    def __init__(
        self,
        *,
        eager: bool = True,
        base: Mapping[str, np.ndarray] | None = None,
    ):
        self._lock = threading.Lock()
        self.eager = bool(eager)
        #: Last aggregate (sparse-delta base): a dense delta upload folds
        #: as ``base[key] + delta`` exactly like the barrier's absolute
        #: reconstruction.
        self.base = base
        #: cid -> {"keys": tuple, "n_samples": float, "delta": bool,
        #:         "dp_crc": int | None}
        self.intents: dict[int, dict] = {}
        self._pending: dict[str, dict[int, np.ndarray]] = {}
        self._acc: dict[str, np.ndarray] = {}
        self._folded: set[str] = set()
        self.fold_ids: list[int] | None = None
        self._weights: dict[int, np.float64] | None = None
        self.poisoned: str | None = None
        self._wait_over = False
        #: cids whose upload fully arrived: a fold only counts as
        #: "overlapped" while some member's bytes are still in flight.
        self._complete: set[int] = set()
        #: Per-client fold stats handed to the round's aggregation
        #: strategy at finalize (strategies/core.py ``client_stats``):
        #: cid -> {"weight", "bytes", "scale"}. An entry lives exactly
        #: as long as the client's intent — ``drop_client`` purges it
        #: unconditionally (even on the poisoned path) so a dropped
        #: client can never leak into the strategy's view of the round.
        self._strategy_stats: dict[int, dict[str, float]] = {}
        # accounting (the obs layer's wire-overlap span + bench headline)
        self._cur_bytes = 0
        self.peak_bytes = 0
        self.early_bytes = 0
        self.late_bytes = 0
        self.early_s = 0.0
        self.late_s = 0.0
        self.first_fold_unix: float | None = None

    # ------------------------------------------------------------ intents
    def register(
        self,
        cid: int,
        *,
        keys: tuple,
        n_samples: float,
        delta: bool = False,
        dp_crc: int | None = None,
    ) -> None:
        with self._lock:
            self.intents[cid] = {
                "keys": tuple(keys),
                "n_samples": float(n_samples),
                "delta": bool(delta),
                "dp_crc": dp_crc,
            }
            self._strategy_stats[cid] = {
                "weight": float(n_samples),
                "bytes": 0.0,
                "scale": 1.0,
            }

    def admit(self, cid: int) -> bool:
        """Late-adopt a NEW contributor (a re-homed client, comm/server.py)
        into the round's fold. Before any fold ran, a frozen fold set is
        simply un-frozen — the next freeze re-normalizes the weights over
        the grown set, still the exact barrier mean. Once folds consumed
        the frozen weights no correct mean including ``cid`` exists any
        more: returns False and the caller refuses the adoption (the
        round's integrity beats the straggler's membership)."""
        with self._lock:
            if self.fold_ids is None or cid in self.fold_ids:
                return True
            if self._folded:
                return False
            self.fold_ids = None
            self._weights = None
            return True

    def drop_client(self, cid: int, *, poison: bool = True) -> bool:
        """Forget a client's unfolded state (mid-stream death, duplicate
        re-upload). Returns False when folds already consumed its leaves
        — poisoning the round when ``poison`` (a folded contributor DIED;
        no correct mean exists any more), or leaving it intact when not
        (a DUPLICATE upload is simply refused and the folded original
        stands). Before any fold, a frozen fold set containing ``cid`` is
        un-frozen again: nothing was consumed, so ``finalize`` can
        re-freeze over the survivors — the exact barrier semantics for a
        pre-aggregation death."""
        with self._lock:
            if self.fold_ids and cid in self.fold_ids:
                if self._folded:
                    if poison:
                        self.poisoned = (
                            f"client {cid} dropped its upload after "
                            f"{len(self._folded)} leaf folds already "
                            "consumed it"
                        )
                        # The round is dead either way, but the strategy
                        # view must not keep a ghost contributor: a
                        # poisoned-round retry reuses nothing, and the
                        # stats() invariant (strategy stats ⊆ intents)
                        # holds even on this failure path.
                        self.intents.pop(cid, None)
                        self._strategy_stats.pop(cid, None)
                        self._complete.discard(cid)
                    return False
                self.fold_ids = None
                self._weights = None
            self.intents.pop(cid, None)
            self._strategy_stats.pop(cid, None)
            self._complete.discard(cid)
            for leaves in self._pending.values():
                arr = leaves.pop(cid, None)
                if arr is not None:
                    self._cur_bytes -= arr.nbytes
            return True

    def mark_complete(self, cid: int) -> None:
        """The client's upload fully arrived (trailer verified / dense
        frame decoded): later folds no longer overlap ITS wire time."""
        with self._lock:
            self._complete.add(cid)

    def scale_client(self, cid: int, scale: float) -> bool:
        """Apply the DP re-clip scale to a client's pending leaves
        (``leaf * float32(scale)`` — byte-identical to the barrier's
        ``wire.clip_flat``). Returns False when folds already consumed
        unscaled leaves (caller fails the round)."""
        with self._lock:
            if self._folded and self.fold_ids and cid in self.fold_ids:
                self.poisoned = (
                    f"client {cid} exceeded its DP clip after folds "
                    "already consumed its unscaled leaves"
                )
                return False
            for leaves in self._pending.values():
                if cid in leaves:
                    leaves[cid] = np.asarray(
                        leaves[cid], np.float32
                    ) * np.float32(scale)
            if cid in self._strategy_stats:
                self._strategy_stats[cid]["scale"] *= float(scale)
            return True

    # ------------------------------------------------------------- leaves
    def add_leaf(self, cid: int, key: str, arr: np.ndarray) -> None:
        with self._lock:
            if key in self._folded:
                # A late leaf for an already-folded key can only belong
                # to a non-member (e.g. a stale DP client being drained);
                # a member's leaves were all present by definition.
                return
            prev = self._pending.setdefault(key, {}).get(cid)
            if prev is not None:
                # Re-supplied leaf (a dense retry completing a superseded
                # stream): replacement, not accumulation.
                self._cur_bytes -= prev.nbytes
            self._pending[key][cid] = arr
            self._cur_bytes += arr.nbytes
            if cid in self._strategy_stats:
                self._strategy_stats[cid]["bytes"] += float(arr.nbytes)
            self.peak_bytes = max(self.peak_bytes, self._cur_bytes)
            if self.fold_ids is not None:
                self._maybe_fold(key)

    def add_dense(self, cid: int, flat: Mapping[str, np.ndarray]) -> None:
        """A single-frame upload: all leaves at once (old-peer interop —
        dense and streamed clients mix freely in one fold)."""
        with self._lock:
            self._complete.add(cid)
            for key, arr in flat.items():
                if key in self._folded:
                    continue
                arr = np.asarray(arr)
                prev = self._pending.setdefault(key, {}).get(cid)
                if prev is not None:
                    self._cur_bytes -= prev.nbytes
                self._pending[key][cid] = arr
                self._cur_bytes += arr.nbytes
                if cid in self._strategy_stats:
                    self._strategy_stats[cid]["bytes"] += float(arr.nbytes)
            self.peak_bytes = max(self.peak_bytes, self._cur_bytes)
            if self.fold_ids is not None:
                for key in list(self._pending):
                    self._maybe_fold(key)

    # -------------------------------------------------------------- folds
    def freeze(self, ids: list[int], weights: list[float] | None) -> None:
        """Fix the fold set + normalized weights (weight math identical
        to ``aggregate_flat``), then fold every leaf already complete."""
        with self._lock:
            if self.poisoned:
                return
            ids = sorted(int(i) for i in ids)
            if self.fold_ids is not None:
                if ids == self.fold_ids:
                    return
                if self._folded:
                    # Folds already ran with the old set's weights; a
                    # different contributor set cannot reach a correct
                    # mean any more.
                    self.poisoned = (
                        f"fold set changed after {len(self._folded)} "
                        f"folds ({self.fold_ids} -> {ids})"
                    )
                    return
                # Frozen but nothing folded yet (a member died between
                # its intent and its first complete leaf): re-freeze
                # over the final set — still the exact barrier mean.
                self.fold_ids = None
                self._weights = None
            if weights is None:
                w = np.ones(len(ids), np.float64)
            else:
                w = np.asarray(weights, np.float64)
                if w.shape != (len(ids),) or w.sum() <= 0:
                    raise ValueError(f"bad weights {weights}")
            w = w / w.sum()
            self._weights = {cid: w[i] for i, cid in enumerate(ids)}
            self.fold_ids = ids
            for key in list(self._pending):
                self._maybe_fold(key)

    def _maybe_fold(self, key: str) -> None:
        """Caller holds the lock; folds ``key`` when every fold-set
        member's leaf is present."""
        if self.poisoned or key in self._folded:
            return
        leaves = self._pending.get(key)
        if leaves is None or any(c not in leaves for c in self.fold_ids):
            return
        # fedtpu: allow(determinism): first-fold wall-clock for the
        # wire-overlap span's t_start — observability only, the fold value
        # and order come from fold_ids
        t_unix = time.time()
        t0 = time.monotonic()
        try:
            # Batched fold: materialize the K leaves in ascending-id order
            # and hand them to the fold engine in ONE dispatch. Every
            # engine replays the identical per-element fp32 mul/add
            # sequence, so the result stays bit-exact with the barrier
            # mean regardless of which engine folded (pinned by the
            # shuffled-arrival property test).
            ordered: list[np.ndarray] = []
            for cid in self.fold_ids:
                arr = leaves[cid]
                if self.intents[cid].get("delta"):
                    # Barrier parity: absolute = base + float32(delta),
                    # validated against the base at upload time.
                    arr = self.base[key] + np.asarray(arr, np.float32)
                arr = np.asarray(arr, np.float32)
                if ordered and arr.shape != ordered[0].shape:
                    raise wire.WireError(f"shape mismatch for {key!r}")
                ordered.append(arr)
            acc = fold_ops.fold_ordered(
                ordered, [np.float32(self._weights[c]) for c in self.fold_ids]
            )
        except Exception as e:  # poison, don't kill the handler thread
            self.poisoned = f"fold of {key!r} failed: {e}"
            return
        self._acc[key] = acc
        freed = sum(a.nbytes for a in leaves.values())
        del self._pending[key]
        self._cur_bytes += acc.nbytes - freed
        self.peak_bytes = max(self.peak_bytes, self._cur_bytes)
        self._folded.add(key)
        dur = time.monotonic() - t0
        overlapped = not self._wait_over and any(
            c not in self._complete for c in self.fold_ids
        )
        if overlapped:
            if self.first_fold_unix is None:
                self.first_fold_unix = t_unix
            self.early_bytes += freed
            self.early_s += dur
        else:
            self.late_bytes += freed
            self.late_s += dur

    def mark_wait_end(self) -> None:
        """The round's wait phase is over: folds from here on are exposed
        aggregation time, not overlapped wire time."""
        with self._lock:
            self._wait_over = True

    # ----------------------------------------------------------- finalize
    def finalize(
        self, ids: list[int], weights: list[float] | None
    ) -> dict[str, np.ndarray]:
        """Fold whatever is left over the FINAL contributor set and
        return the mean. With no prior freeze (non-eager mode, or a
        straggler round that never completed its intents) this IS the
        barrier computation; with one, ``ids`` must match the frozen set
        — a divergence means folds used wrong weights, so fail loudly."""
        if self.poisoned:
            raise StreamAggPoisoned(self.poisoned)
        self.freeze(ids, weights)
        with self._lock:
            if self.poisoned:
                raise StreamAggPoisoned(self.poisoned)
            want = set(str(k) for i in self.fold_ids for k in self.intents[i]["keys"])
            for i in self.fold_ids:
                if set(self.intents[i]["keys"]) != want:
                    raise wire.WireError(
                        f"model {i} key set differs from the round's"
                    )
            missing = sorted(want - self._folded)
            for key in missing:
                leaves = self._pending.get(key, {})
                absent = [c for c in self.fold_ids if c not in leaves]
                if absent:
                    raise wire.WireError(
                        f"leaf {key!r} never arrived from clients {absent}"
                    )
                self._maybe_fold(key)
            if self.poisoned:
                raise StreamAggPoisoned(self.poisoned)
            return dict(sorted(self._acc.items()))

    # -------------------------------------------------------------- stats
    def client_stats(self) -> dict[int, dict[str, float]]:
        """Per-client fold stats for the round's aggregation strategy
        (snapshot copy: the strategy must see the round, not a live
        mutable view)."""
        with self._lock:
            return {
                cid: dict(self._strategy_stats[cid])
                for cid in sorted(self._strategy_stats)
            }

    def stats(self) -> dict[str, Any]:
        with self._lock:
            # Invariant (strategies/ PR): a dropped client's strategy
            # stats entry dies with its intent — a poisoned mid-round
            # drop must not leave a ghost contributor for the strategy.
            stale = sorted(set(self._strategy_stats) - set(self.intents))
            assert not stale, (
                f"strategy stats leak for dropped clients {stale}"
            )
            folded = self.early_bytes + self.late_bytes
            fold_s = self.early_s + self.late_s
            return {
                "peak_bytes": int(self.peak_bytes),
                "early_bytes": int(self.early_bytes),
                "late_bytes": int(self.late_bytes),
                "early_s": float(self.early_s),
                "late_s": float(self.late_s),
                "overlap_frac": (
                    self.early_bytes / folded if folded else 0.0
                ),
                "first_fold_unix": self.first_fold_unix,
                "fold_engine": fold_ops.engine_name(),
                "fold_s": float(fold_s),
                "fold_throughput_gbps": (
                    folded / fold_s / 1e9 if fold_s > 0 and folded else 0.0
                ),
            }
