"""Secure aggregation for the cross-host TCP mode: pairwise masking.

In the reference every client ships its raw state dict to the server, which
can read each client's exact weights (reference server.py:57-65) — the
aggregate is the only thing clients intend to reveal, but the server learns
far more. This module implements the canonical fix (the pairwise-mask
construction of Bonawitz et al., "Practical Secure Aggregation for
Privacy-Preserving Machine Learning", CCS 2017, in its simplest
all-parties-survive form):

* every client quantizes its weights to fixed point (``fp_bits`` fractional
  bits) in the ring Z_2^64,
* each pair of clients (i, j) derives the same mask stream from a shared
  mask secret (which the server does NOT hold): client min(i,j) adds the
  stream, client max(i,j) subtracts it, all mod 2^64,
* the server sums the masked uint64 uploads — the masks cancel exactly in
  modular arithmetic — and recovers the plain fixed-point sum, which it
  de-quantizes into the mean.

Properties: the server (and any wire observer) sees each upload as
uniformly random ring elements; the sum over ALL participants is exact
(bit-exact modular cancellation, no float cancellation error); the only
loss vs plain FedAvg is the fixed-point quantization, 2^-fp_bits per
weight. Mask streams are domain-separated by a per-server-run random
``session`` nonce plus the advertised round number, so a stream is never
reused across rounds or server restarts; a client instance additionally
refuses a (session, round) it has already masked different weights for.

Threat model: honest-but-curious server and passive wire observers (the
semi-honest setting of the Bonawitz paper). Pairwise streams derive from
PER-PAIR Diffie-Hellman secrets (fresh ephemeral keypairs every round,
public keys relayed through the server): client i holds only the secrets
of pairs it belongs to, so no PASSIVE party — a curious client reading
transcripts, or anyone holding a leaked client's key material — can
regenerate another pair's stream or unmask a third party's upload;
compromising one client reveals only that client's own masks. Precise
limits of the guarantee:

* ACTIVE in-group adversaries: with per-client keys provisioned
  (``AggregationServer(client_keys={id: key})`` +
  ``FederatedClient(client_key=...)``; CLI ``FEDTPU_CLIENT_SECRETS`` /
  ``FEDTPU_CLIENT_SECRET``) each DH hello is HMAC-bound by that client's
  OWN key, so a malicious member cannot impersonate another id in the
  key exchange — the forgery fails closed at the server. Reveal
  request/response frames likewise ride the per-client key when
  provisioned (request tagged under the recipient survivor's key,
  response under the sender's), so an in-group active adversary holding
  only the group key can neither forge a REVEAL_REQ naming a victim
  that actually uploaded (to harvest its pair secrets from survivors)
  nor spoof a survivor's response. The server re-tags verified keys
  under the group key for the relay (receivers hold the group key, not
  each other's). With only the group key, the HMAC proves membership,
  not identity, and the in-group impersonation race remains
  (first-registration-wins limits, not removes, it). A client-side
  ``min_participants`` floor (default: the full fleet) additionally
  stops a compromised server/MITM from shrinking a client's
  mask-partner set to a colluding singleton.
* A MALICIOUS (not just curious) server can substitute public keys in
  transit — it verifies and re-signs the relay, so per-client keys do
  not constrain it. This is the one remaining active adversary;
  removing it needs client-to-client signatures (full Bonawitz PKI).
* WITHOUT a group auth key (``FEDTPU_SECRET`` unset) the exchange has no
  integrity at all: an active on-path attacker can MITM the relay and
  unmask every upload. No-auth secure-agg protects against passive
  observers only; the client logs a warning.
* Client dropout recovery — two protocols, selected by
  ``secure_protocol`` and pinned by the client (a mismatched advert is
  refused, so a malicious server cannot downgrade):

  **"double" (default): full Bonawitz §6 double-masking.** Every upload
  additionally carries a self-mask stream from a per-round seed b_i, and
  each client Shamir-shares both b_i and its DH key seed among the keyed
  participants at threshold t (default: strict majority), the share
  blobs relayed through the server encrypted+MAC'd under the pair
  secrets. Recovery layers:

  - dropout BEFORE key distribution: the key set U1 finalizes at the
    quorum whose hellos arrived within the grace window (as before).
  - dropout AFTER keys but BEFORE share distribution: the
    share-complete set U2 finalizes at the dealers that delivered;
    nobody has masked against the missing yet, so the round proceeds.
  - dropout AFTER shares but before upload: the unmask round
    reconstructs the dead client's key seed from any t holders'
    shares (verified against its registered public key), regenerates
    its pair masks, and subtracts them from the ring sum.
  - dropout DURING the unmask round: tolerated while t holders keep
    answering — reconstruction needs any t shares, not everyone.

  The FALSE-DEATH attack of the reveal variant is closed: an honest
  holder reveals, per dealer, EITHER its b-share (dealer claimed alive)
  OR its key-seed share (claimed dead), never both — and the parse
  refuses overlapping claims. With the majority threshold, assembling t
  shares of both kinds for one dealer would need more answers than
  there are holders, so a server that received client j's upload yet
  declares j dead reconstructs j's pair masks but NOT j's self-mask:
  the upload stays hidden. (A malicious server sending DIFFERENT
  alive/dead partitions to different holders is bounded by the same
  counting argument; full resistance to arbitrary active servers still
  needs the consistency-check signatures of Bonawitz §7, out of scope
  with the rest of the active-server vector above.) Reconstructed
  self-mask seeds are verified against dealt commitments, so corrupted
  shares fail loudly rather than silently skewing the aggregate.

  **"reveal": the cheaper pre-r5 variant** (no shares, no self-masks;
  an unmask round only when someone died). Survivors disclose their
  per-pair DH secrets with the dead (``REVEAL_REQ``); per-round
  keypairs mean a revealed secret unlocks only that round's
  (survivor, dead) streams, and the dead contributed nothing to the
  sum. Known limits (why "double" is the default): the false-death
  unmask above, and a dropout DURING the reveal phase fails the round.
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import Mapping, Sequence

import numpy as np

#: Default fractional bits. 2^-24 ~ 6e-8 absolute quantization error per
#: weight — far below bf16 wire compression and Adam-step noise.
DEFAULT_FP_BITS = 24

_DOMAIN = b"fedtpu-secagg-v2"

# RFC 3526 group 14: 2048-bit MODP, generator 2 — finite-field DH from the
# stdlib alone (pow(g, x, P); no external crypto dependency in this image).
DH_PRIME = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16,
)
DH_GENERATOR = 2
DH_PUB_LEN = 256  # 2048-bit public values, fixed-width big-endian


def dh_keypair(entropy: bytes | None = None) -> tuple[int, bytes]:
    """Fresh ephemeral DH keypair: (private exponent, 256-byte public).

    256-bit private exponents — standard for a 2048-bit MODP group
    (~112-bit security either way). ``entropy`` pins the key for tests."""
    raw = os.urandom(32) if entropy is None else hashlib.sha256(entropy).digest()
    x = int.from_bytes(raw, "big") | (1 << 255)  # top bit set: full length
    y = pow(DH_GENERATOR, x, DH_PRIME)
    return x, y.to_bytes(DH_PUB_LEN, "big")


def check_dh_public(pub: bytes) -> int:
    """Parse + validate a peer public value; rejects the degenerate
    elements (0, 1, p-1, >= p) that would collapse the shared secret."""
    if len(pub) != DH_PUB_LEN:
        raise SecureAggError(f"DH public value is {len(pub)} bytes, want {DH_PUB_LEN}")
    y = int.from_bytes(pub, "big")
    if not 2 <= y <= DH_PRIME - 2:
        raise SecureAggError("degenerate DH public value")
    return y


def dh_pair_secret(private: int, peer_pub: bytes) -> bytes:
    """The (i, j) pair's shared mask secret: SHA-256 of the fixed-width
    DH shared value. Symmetric — both ends derive the same bytes; nobody
    without one of the two private exponents can."""
    shared = pow(check_dh_public(peer_pub), private, DH_PRIME)
    return hashlib.sha256(
        _DOMAIN + b"-dh" + shared.to_bytes(DH_PUB_LEN, "big")
    ).digest()


def pubkey_tag(
    auth_key: bytes, session: bytes, round_index: int, client_id: int, pub: bytes
) -> bytes:
    """HMAC binding a relayed public key to (session, round, client id):
    protects the DH exchange against tampering by anyone WITHOUT the group
    auth key (the server holds it, so server MITM stays out of scope —
    see the module threat model)."""
    import hmac

    return hmac.new(
        auth_key,
        _DOMAIN + b"-pk" + session + struct.pack("<Qq", round_index, client_id) + pub,
        hashlib.sha256,
    ).digest()


def verify_pubkey_tag(
    auth_key: bytes,
    session: bytes,
    round_index: int,
    client_id: int,
    pub: bytes,
    tag: bytes,
) -> None:
    """Constant-time check of :func:`pubkey_tag`; raises on mismatch.
    The single verification used by BOTH the server (on hellos) and the
    client (on the relayed keys frame), so the binding can never drift
    between the two ends."""
    import hmac

    if not hmac.compare_digest(
        tag, pubkey_tag(auth_key, session, round_index, client_id, pub)
    ):
        raise SecureAggError(
            f"DH public key for client {client_id} failed its authenticity "
            "check — possible tampering"
        )


class SecureAggError(ValueError):
    """Inconsistent secure-aggregation round (participants/format)."""


def quantize(flat: Mapping[str, np.ndarray], fp_bits: int = DEFAULT_FP_BITS) -> dict[str, np.ndarray]:
    """float32 params -> fixed-point ring elements (uint64, two's complement)."""
    scale = float(1 << fp_bits)
    out = {}
    for key in sorted(flat):
        arr = np.asarray(flat[key])
        if not np.issubdtype(arr.dtype, np.floating):
            raise SecureAggError(f"tensor {key!r} is {arr.dtype}, expected float")
        q = np.round(arr.astype(np.float64) * scale).astype(np.int64)
        out[key] = q.view(np.uint64)
    return out


def dequantize_sum(
    summed: Mapping[str, np.ndarray], n_clients: int, fp_bits: int = DEFAULT_FP_BITS
) -> dict[str, np.ndarray]:
    """Ring sum over clients -> float32 mean. The modular sum re-interpreted
    as int64 is the exact signed fixed-point sum as long as
    ``|sum| < 2^63 / 2^fp_bits`` per element (n_clients * max|w| < 2^39 at
    the default 24 bits — orders of magnitude of headroom)."""
    scale = float(1 << fp_bits)
    out = {}
    for key, arr in summed.items():
        if arr.dtype != np.uint64:
            raise SecureAggError(f"summed tensor {key!r} is {arr.dtype}, expected uint64")
        signed = arr.view(np.int64)
        out[key] = (signed / (scale * n_clients)).astype(np.float32)
    return out


def _pair_stream(
    pair_secret: bytes, session: bytes, round_index: int, lo: int, hi: int
) -> np.random.Generator:
    """The (lo, hi) client pair's shared mask PRG for one round. Both ends
    derive the identical stream from their DH pair secret; nobody without
    one of the pair's private keys can.

    ``session`` is the server run's random nonce (delivered in the round
    advert): it domain-separates mask streams across server restarts, so
    re-running the pipeline with the same pair secret and the same round
    numbers never reuses a stream."""
    if not 0 <= round_index < 2**63:
        raise SecureAggError(f"round_index {round_index} out of range [0, 2^63)")
    digest = hashlib.sha256(
        _DOMAIN + pair_secret + session + struct.pack("<Qqq", round_index, lo, hi)
    ).digest()
    return np.random.Generator(
        np.random.Philox(key=int.from_bytes(digest[:16], "little"))
    )


def _apply_pair_stream(
    out: dict[str, np.ndarray],
    pair_secret: bytes,
    session: bytes,
    round_index: int,
    lo: int,
    hi: int,
    *,
    add: bool,
) -> None:
    """Add (or subtract) the (lo, hi) pair's mask stream into ``out``
    in place, drawing per tensor in sorted-key order from one PRG. The
    SINGLE stream-expansion implementation shared by :func:`mask` and
    :func:`residual_mask_sum` — bit-exact cancellation (and reveal-round
    recovery) depends on both ends expanding identically."""
    rng = _pair_stream(pair_secret, session, round_index, lo, hi)
    for key in sorted(out):
        stream = rng.integers(
            0, 2**64, size=out[key].shape, dtype=np.uint64, endpoint=False
        )
        if add:
            out[key] += stream  # uint64 wraps mod 2^64
        else:
            out[key] -= stream


def mask(
    quantized: Mapping[str, np.ndarray],
    *,
    pair_secrets: Mapping[int, bytes],
    round_index: int,
    client_id: int,
    participants: Sequence[int],
    session: bytes = b"",
) -> dict[str, np.ndarray]:
    """Add this client's pairwise masks: +stream for partners above it,
    -stream for partners below (mod 2^64), per sorted tensor key. Summing
    every participant's masked upload cancels all masks bit-exactly.

    ``pair_secrets`` maps each partner id to THIS client's shared secret
    with that partner (:func:`dh_pair_secret`) — per-pair keys, so this
    client's key material never covers pairs it does not belong to."""
    ids = sorted(set(int(p) for p in participants))
    if int(client_id) not in ids:
        raise SecureAggError(f"client {client_id} not in participants {ids}")
    if len(ids) < 2:
        # A single participant has nobody to pair with; masking would be a
        # no-op that still leaks the raw update — refuse loudly.
        raise SecureAggError("secure aggregation needs >= 2 participants")
    missing = [p for p in ids if p != client_id and p not in pair_secrets]
    if missing:
        raise SecureAggError(
            f"client {client_id} lacks pair secrets for partners {missing}"
        )
    out = {k: np.array(quantized[k], dtype=np.uint64, copy=True) for k in sorted(quantized)}
    for other in ids:
        if other == client_id:
            continue
        lo, hi = min(client_id, other), max(client_id, other)
        _apply_pair_stream(
            out, pair_secrets[other], session, round_index, lo, hi,
            add=client_id == lo,
        )
    return out


def masked_upload(
    flat: Mapping[str, np.ndarray],
    *,
    pair_secrets: Mapping[int, bytes],
    round_index: int,
    client_id: int,
    participants: Sequence[int],
    fp_bits: int = DEFAULT_FP_BITS,
    session: bytes = b"",
) -> dict[str, np.ndarray]:
    """Client-side one-call path: quantize then mask."""
    return mask(
        quantize(flat, fp_bits),
        pair_secrets=pair_secrets,
        round_index=round_index,
        client_id=client_id,
        participants=participants,
        session=session,
    )


# ------------------------------------------------- dropout reveal round
#: Server -> survivor: "these keyed participants never uploaded; disclose
#: your pair secrets with them". REVEAL_MAGIC + u32 n + n x i64 dead ids
#: [+ HMAC tag]. The survivor answers REVEAL_RESP_MAGIC + n x (i64 id +
#: 32-byte pair secret) [+ tag].
REVEAL_MAGIC = b"RVLQ"
REVEAL_RESP_MAGIC = b"RVLA"
PAIR_SECRET_LEN = 32
_TAG_LEN = 32


def _reveal_tag(auth_key: bytes, kind: bytes, session: bytes,
                round_index: int, body: bytes) -> bytes:
    import hmac

    return hmac.new(
        auth_key,
        _DOMAIN + kind + session + struct.pack("<Q", round_index) + body,
        hashlib.sha256,
    ).digest()


def build_reveal_request(
    dead: Sequence[int], *, session: bytes, round_index: int,
    auth_key: bytes | None = None,
) -> bytes:
    ids = sorted(set(int(d) for d in dead))
    body = struct.pack("<I", len(ids)) + b"".join(
        struct.pack("<q", d) for d in ids
    )
    msg = REVEAL_MAGIC + body
    if auth_key is not None:
        msg += _reveal_tag(auth_key, b"-rq", session, round_index, body)
    return msg


def parse_reveal_request(
    frame: bytes, *, session: bytes, round_index: int,
    auth_key: bytes | None = None,
) -> list[int]:
    """Validate + parse a reveal request; raises :class:`SecureAggError`
    on malformed frames or (in auth mode) a bad tag."""
    import hmac

    if not frame.startswith(REVEAL_MAGIC):
        raise SecureAggError("not a reveal request")
    body_end = len(frame) - (_TAG_LEN if auth_key is not None else 0)
    body = frame[len(REVEAL_MAGIC) : body_end]
    if auth_key is not None and not hmac.compare_digest(
        frame[body_end:],
        _reveal_tag(auth_key, b"-rq", session, round_index, body),
    ):
        raise SecureAggError("reveal request failed its authenticity check")
    if len(body) < 4:
        raise SecureAggError("truncated reveal request")
    (n,) = struct.unpack("<I", body[:4])
    if len(body) != 4 + 8 * n or n == 0:
        raise SecureAggError("malformed reveal request body")
    ids = list(struct.unpack(f"<{n}q", body[4:]))
    if len(set(ids)) != n:
        raise SecureAggError("duplicate ids in reveal request")
    return ids


def build_reveal_response(
    secrets: Mapping[int, bytes], *, session: bytes, round_index: int,
    client_id: int, auth_key: bytes | None = None,
) -> bytes:
    body = b"".join(
        struct.pack("<q", d) + secrets[d] for d in sorted(secrets)
    )
    msg = REVEAL_RESP_MAGIC + body
    if auth_key is not None:
        msg += _reveal_tag(
            auth_key, b"-ra" + struct.pack("<q", client_id),
            session, round_index, body,
        )
    return msg


def parse_reveal_response(
    frame: bytes, *, session: bytes, round_index: int, client_id: int,
    expect_dead: Sequence[int], auth_key: bytes | None = None,
) -> dict[int, bytes]:
    import hmac

    if not frame.startswith(REVEAL_RESP_MAGIC):
        raise SecureAggError("not a reveal response")
    body_end = len(frame) - (_TAG_LEN if auth_key is not None else 0)
    body = frame[len(REVEAL_RESP_MAGIC) : body_end]
    if auth_key is not None and not hmac.compare_digest(
        frame[body_end:],
        _reveal_tag(
            auth_key, b"-ra" + struct.pack("<q", client_id),
            session, round_index, body,
        ),
    ):
        raise SecureAggError(
            f"reveal response from client {client_id} failed its "
            "authenticity check"
        )
    entry = 8 + PAIR_SECRET_LEN
    if len(body) % entry:
        raise SecureAggError("malformed reveal response body")
    out: dict[int, bytes] = {}
    for off in range(0, len(body), entry):
        (d,) = struct.unpack("<q", body[off : off + 8])
        out[d] = body[off + 8 : off + entry]
    if sorted(out) != sorted(set(int(x) for x in expect_dead)):
        raise SecureAggError(
            f"reveal response covers {sorted(out)}, expected "
            f"{sorted(expect_dead)}"
        )
    return out


def residual_mask_sum(
    template: Mapping[str, np.ndarray],
    revealed: Mapping[int, Mapping[int, bytes]],  # survivor -> dead -> secret
    *,
    session: bytes,
    round_index: int,
) -> dict[str, np.ndarray]:
    """The uncancelled mask residue a dropout leaves in the ring sum:
    ``sum over survivors i, dead j of sign(i,j) * stream(i,j)`` where
    ``sign`` is + when the survivor is the pair's low id (it ADDED the
    stream in :func:`mask`) and - otherwise. Streams are regenerated in
    the exact draw order ``mask`` used (one PRG per pair, tensors in
    sorted-key order), so subtracting this from the sum restores exact
    modular cancellation over the survivors."""
    out = {
        k: np.zeros_like(np.asarray(template[k], np.uint64))
        for k in sorted(template)
    }
    for survivor, secrets in sorted(revealed.items()):
        for dead_id, secret in sorted(secrets.items()):
            if len(secret) != PAIR_SECRET_LEN:
                raise SecureAggError(
                    f"pair secret for ({survivor}, {dead_id}) has length "
                    f"{len(secret)}"
                )
            lo, hi = min(survivor, dead_id), max(survivor, dead_id)
            _apply_pair_stream(
                out, secret, session, round_index, lo, hi,
                add=survivor == lo,
            )
    return out


def sum_masked(models: Sequence[Mapping[str, np.ndarray]]) -> dict[str, np.ndarray]:
    """Server-side ring sum of masked uploads (mod 2^64). With every
    participant present the pairwise masks cancel exactly."""
    if not models:
        raise SecureAggError("no masked models to sum")
    keys = set(models[0])
    for i, m in enumerate(models[1:], 1):
        if set(m) != keys:
            raise SecureAggError(f"masked model {i} key set differs from model 0")
    out = {}
    for key in keys:
        acc = np.zeros_like(np.asarray(models[0][key], np.uint64))
        for m in models:
            arr = np.asarray(m[key])
            if arr.dtype != np.uint64 or arr.shape != acc.shape:
                raise SecureAggError(
                    f"masked tensor {key!r}: dtype/shape mismatch "
                    f"({arr.dtype}, {arr.shape})"
                )
            acc += arr
        out[key] = acc
    return out


def aggregate_masked(
    models: Sequence[Mapping[str, np.ndarray]],
    fp_bits: int = DEFAULT_FP_BITS,
) -> dict[str, np.ndarray]:
    """Server-side: masked uploads (all participants!) -> float32 mean."""
    return dequantize_sum(sum_masked(models), len(models), fp_bits)


# ---------------------------------------------- double-masking (Bonawitz §6)
# The full construction: every upload additionally carries a SELF-mask
# stream from a per-round seed b_i, and each client Shamir-shares (at
# threshold t) both b_i and the seed of its per-round DH keypair among the
# participants. The unmask round asks survivors for the b-shares of ALIVE
# (contributing) clients and the key-seed shares of DEAD ones; the server
# reconstructs self-masks of contributors and pair masks of the dead, and
# subtracts both from the ring sum. Properties the reveal-round variant
# lacked (comm/secure.py module threat model):
#
# * FALSE-DEATH CLOSURE: an honest holder reveals, per dealer and round,
#   EITHER its b-share (dealer claimed alive) OR its key-seed share
#   (claimed dead), never both. With the default majority threshold
#   t = floor(n/2)+1, assembling t shares of BOTH kinds for one dealer
#   would need more answers than there are holders — so a server that
#   received client j's upload yet declares j dead can reconstruct j's
#   pair masks but NOT j's self-mask: the upload stays hidden.
# * UNMASK-PHASE DROPOUT: reconstruction needs any t holders, so clients
#   may keep dying during the unmask round as long as t survive.
#
# Share blobs travel dealer->server->holder encrypted and MAC'd under the
# (dealer, holder) pair secret — the server relays ciphertext it cannot
# read or undetectably alter.

SHARES_MAGIC = b"SHRS"
SHARESET_MAGIC = b"SHST"
UNMASK_MAGIC = b"UMRQ"
UNMASK_RESP_MAGIC = b"UMRS"
SEED_LEN = 32
SHARE_BLOB_LEN = 2 * SEED_LEN + 32  # enc(b_share || sk_share) + MAC
#: Protocol selector carried in the round advert: the reveal-round
#: variant (cheaper: no share distribution or unmask round when nobody
#: drops) vs full double-masking (the default).
PROTO_REVEAL = 0
PROTO_DOUBLE = 1


def majority_threshold(n: int) -> int:
    """The default Shamir threshold: a strict majority of the n
    participants. This is what makes the either/or reveal rule binding —
    t-of-both-kinds would need > n answers."""
    return n // 2 + 1


def share_x(client_id: int) -> int:
    """A client's fixed share x-coordinate (ids must stay < 255)."""
    cid = int(client_id)
    if not 0 <= cid < 255:
        raise SecureAggError(
            f"double-masking supports client ids 0..254, got {cid}"
        )
    return cid + 1


def apply_self_stream(
    out: dict[str, np.ndarray],
    seed: bytes,
    session: bytes,
    round_index: int,
    client_id: int,
    *,
    add: bool,
) -> None:
    """Add/subtract client ``client_id``'s self-mask stream (PRG keyed by
    its per-round seed b_i) into ``out`` in place — same sorted-tensor
    draw order as the pair streams, so server-side reconstruction expands
    identically."""
    if len(seed) != SEED_LEN:
        raise SecureAggError(f"self-mask seed has length {len(seed)}")
    digest = hashlib.sha256(
        _DOMAIN + b"-self" + seed + session
        + struct.pack("<Qq", round_index, int(client_id))
    ).digest()
    rng = np.random.Generator(
        np.random.Philox(key=int.from_bytes(digest[:16], "little"))
    )
    for key in sorted(out):
        stream = rng.integers(
            0, 2**64, size=out[key].shape, dtype=np.uint64, endpoint=False
        )
        if add:
            out[key] += stream
        else:
            out[key] -= stream


def self_mask_sum(
    template: Mapping[str, np.ndarray],
    seeds: Mapping[int, bytes],
    *,
    session: bytes,
    round_index: int,
) -> dict[str, np.ndarray]:
    """The summed self-mask streams of the given (client id -> b seed)
    set — what the server subtracts for the round's contributors."""
    out = {
        k: np.zeros_like(np.asarray(template[k], np.uint64))
        for k in sorted(template)
    }
    for cid, seed in sorted(seeds.items()):
        apply_self_stream(out, seed, session, round_index, cid, add=True)
    return out


def _share_keys(
    pair_secret: bytes, session: bytes, round_index: int,
    dealer: int, holder: int,
) -> tuple[bytes, bytes]:
    """(keystream, MAC key) for one share blob, domain-separated from the
    mask streams and bound to (session, round, dealer, holder)."""
    ctx = session + struct.pack("<Qqq", round_index, int(dealer), int(holder))
    stream = hashlib.shake_256(
        _DOMAIN + b"-shenc" + pair_secret + ctx
    ).digest(2 * SEED_LEN)
    mac_key = hashlib.sha256(
        _DOMAIN + b"-shmac" + pair_secret + ctx
    ).digest()
    return stream, mac_key


def encrypt_share_blob(
    pair_secret: bytes,
    session: bytes,
    round_index: int,
    dealer: int,
    holder: int,
    b_share: bytes,
    sk_share: bytes,
) -> bytes:
    """Encrypt-and-MAC one (b-share, key-seed-share) pair for its holder.
    The relaying server sees ciphertext only; tampering fails the MAC at
    the holder."""
    import hmac

    if len(b_share) != SEED_LEN or len(sk_share) != SEED_LEN:
        raise SecureAggError("share blobs carry two 32-byte shares")
    stream, mac_key = _share_keys(
        pair_secret, session, round_index, dealer, holder
    )
    pt = b_share + sk_share
    ct = bytes(a ^ b for a, b in zip(pt, stream))
    return ct + hmac.new(mac_key, ct, hashlib.sha256).digest()


def decrypt_share_blob(
    pair_secret: bytes,
    session: bytes,
    round_index: int,
    dealer: int,
    holder: int,
    blob: bytes,
) -> tuple[bytes, bytes]:
    """Verify + decrypt a relayed share blob -> (b_share, sk_share)."""
    import hmac

    if len(blob) != SHARE_BLOB_LEN:
        raise SecureAggError(f"share blob has length {len(blob)}")
    stream, mac_key = _share_keys(
        pair_secret, session, round_index, dealer, holder
    )
    ct, tag = blob[: 2 * SEED_LEN], blob[2 * SEED_LEN :]
    if not hmac.compare_digest(tag, hmac.new(mac_key, ct, hashlib.sha256).digest()):
        raise SecureAggError(
            f"share blob from dealer {dealer} failed its authenticity "
            "check — possible relay tampering"
        )
    pt = bytes(a ^ b for a, b in zip(ct, stream))
    return pt[:SEED_LEN], pt[SEED_LEN:]


def _unmask_tag(auth_key: bytes, kind: bytes, session: bytes,
                round_index: int, body: bytes) -> bytes:
    import hmac

    return hmac.new(
        auth_key,
        _DOMAIN + kind + session + struct.pack("<Q", round_index) + body,
        hashlib.sha256,
    ).digest()


def build_unmask_request(
    alive: Sequence[int], dead: Sequence[int], *, session: bytes,
    round_index: int, auth_key: bytes | None = None,
) -> bytes:
    """Server -> survivor: 'reveal b-shares for these alive (contributing)
    dealers and key-seed shares for these dead ones'."""
    a = sorted(set(int(x) for x in alive))
    d = sorted(set(int(x) for x in dead))
    body = (
        struct.pack("<I", len(a)) + b"".join(struct.pack("<q", i) for i in a)
        + struct.pack("<I", len(d)) + b"".join(struct.pack("<q", i) for i in d)
    )
    msg = UNMASK_MAGIC + body
    if auth_key is not None:
        msg += _unmask_tag(auth_key, b"-uq", session, round_index, body)
    return msg


def parse_unmask_request(
    frame: bytes, *, session: bytes, round_index: int,
    auth_key: bytes | None = None,
) -> tuple[list[int], list[int]]:
    """Validate + parse -> (alive ids, dead ids). Refuses overlap — an id
    claimed both alive and dead is exactly the both-kinds harvest the
    either/or rule exists to stop."""
    import hmac

    if not frame.startswith(UNMASK_MAGIC):
        raise SecureAggError("not an unmask request")
    body_end = len(frame) - (_TAG_LEN if auth_key is not None else 0)
    body = frame[len(UNMASK_MAGIC) : body_end]
    if auth_key is not None and not hmac.compare_digest(
        frame[body_end:],
        _unmask_tag(auth_key, b"-uq", session, round_index, body),
    ):
        raise SecureAggError("unmask request failed its authenticity check")
    if len(body) < 8:
        raise SecureAggError("truncated unmask request")
    (na,) = struct.unpack("<I", body[:4])
    off = 4 + 8 * na
    if len(body) < off + 4:
        raise SecureAggError("malformed unmask request body")
    alive = list(struct.unpack(f"<{na}q", body[4:off]))
    (nd,) = struct.unpack("<I", body[off : off + 4])
    if len(body) != off + 4 + 8 * nd:
        raise SecureAggError("malformed unmask request body")
    dead = list(struct.unpack(f"<{nd}q", body[off + 4 :]))
    if len(set(alive)) != na or len(set(dead)) != nd:
        raise SecureAggError("duplicate ids in unmask request")
    both = set(alive) & set(dead)
    if both:
        raise SecureAggError(
            f"unmask request claims clients {sorted(both)} both alive and "
            "dead — refusing (both-kinds share harvest)"
        )
    if not alive:
        raise SecureAggError("unmask request with no alive clients")
    return alive, dead


def build_unmask_response(
    b_shares: Mapping[int, bytes],
    sk_shares: Mapping[int, bytes],
    *,
    session: bytes,
    round_index: int,
    client_id: int,
    auth_key: bytes | None = None,
) -> bytes:
    """Survivor -> server: this holder's shares, kind-tagged (0 = b-share
    of an alive dealer, 1 = key-seed share of a dead dealer)."""
    entries = []
    for d in sorted(b_shares):
        entries.append(struct.pack("<qB", int(d), 0) + b_shares[d])
    for d in sorted(sk_shares):
        entries.append(struct.pack("<qB", int(d), 1) + sk_shares[d])
    body = struct.pack("<I", len(entries)) + b"".join(entries)
    msg = UNMASK_RESP_MAGIC + body
    if auth_key is not None:
        msg += _unmask_tag(
            auth_key, b"-ua" + struct.pack("<q", int(client_id)),
            session, round_index, body,
        )
    return msg


def parse_unmask_response(
    frame: bytes, *, session: bytes, round_index: int, client_id: int,
    expect_alive: Sequence[int], expect_dead: Sequence[int],
    auth_key: bytes | None = None,
) -> tuple[dict[int, bytes], dict[int, bytes]]:
    """Validate + parse -> ({alive dealer: b-share}, {dead dealer:
    sk-share}); the covered sets must match the request exactly."""
    import hmac

    if not frame.startswith(UNMASK_RESP_MAGIC):
        raise SecureAggError("not an unmask response")
    body_end = len(frame) - (_TAG_LEN if auth_key is not None else 0)
    body = frame[len(UNMASK_RESP_MAGIC) : body_end]
    if auth_key is not None and not hmac.compare_digest(
        frame[body_end:],
        _unmask_tag(
            auth_key, b"-ua" + struct.pack("<q", int(client_id)),
            session, round_index, body,
        ),
    ):
        raise SecureAggError(
            f"unmask response from client {client_id} failed its "
            "authenticity check"
        )
    if len(body) < 4:
        raise SecureAggError("truncated unmask response")
    (n,) = struct.unpack("<I", body[:4])
    entry = 8 + 1 + SEED_LEN
    if len(body) != 4 + n * entry:
        raise SecureAggError("malformed unmask response body")
    b_shares: dict[int, bytes] = {}
    sk_shares: dict[int, bytes] = {}
    for off in range(4, len(body), entry):
        d, kind = struct.unpack("<qB", body[off : off + 9])
        y = body[off + 9 : off + entry]
        if kind == 0:
            if d in b_shares:
                raise SecureAggError(f"duplicate b-share for dealer {d}")
            b_shares[d] = y
        elif kind == 1:
            if d in sk_shares:
                raise SecureAggError(f"duplicate sk-share for dealer {d}")
            sk_shares[d] = y
        else:
            raise SecureAggError(f"unknown share kind {kind}")
    if sorted(b_shares) != sorted(set(int(x) for x in expect_alive)):
        raise SecureAggError(
            f"unmask response b-shares cover {sorted(b_shares)}, expected "
            f"{sorted(expect_alive)}"
        )
    if sorted(sk_shares) != sorted(set(int(x) for x in expect_dead)):
        raise SecureAggError(
            f"unmask response sk-shares cover {sorted(sk_shares)}, "
            f"expected {sorted(expect_dead)}"
        )
    return b_shares, sk_shares


def b_seed_commitment(
    b_seed: bytes, session: bytes, round_index: int, dealer: int
) -> bytes:
    """Public commitment to a dealer's self-mask seed, carried in its
    SHARES frame: the server verifies the Shamir reconstruction against
    it, so corrupted/inconsistent shares fail loudly instead of silently
    skewing the aggregate. (The key seed needs no extra commitment — its
    reconstruction is verified against the dealer's registered DH public
    key.)"""
    return hashlib.sha256(
        _DOMAIN + b"-bcommit" + b_seed + session
        + struct.pack("<Qq", round_index, int(dealer))
    ).digest()


def build_shares_frame(
    dealer: int,
    commit: bytes,
    blobs: Mapping[int, bytes],
    *,
    threshold: int,
    session: bytes,
    round_index: int,
    auth_key: bytes | None = None,
) -> bytes:
    """Dealer -> server: encrypted (b-share, key-seed-share) blobs for
    every other participant, the b-seed commitment, and the Shamir
    threshold the shares were dealt at (the server validates it against
    its own effective threshold — a mismatch could never reconstruct)."""
    if not 2 <= int(threshold) <= 254:
        raise SecureAggError(f"threshold {threshold} out of range [2, 254]")
    body = struct.pack("<qB", int(dealer), int(threshold)) + commit + struct.pack(
        "<I", len(blobs)
    )
    for holder in sorted(blobs):
        blob = blobs[holder]
        if len(blob) != SHARE_BLOB_LEN:
            raise SecureAggError(f"share blob for holder {holder} malformed")
        body += struct.pack("<q", int(holder)) + blob
    msg = SHARES_MAGIC + body
    if auth_key is not None:
        msg += _unmask_tag(auth_key, b"-sh", session, round_index, body)
    return msg


def parse_shares_frame(
    frame: bytes,
    *,
    session: bytes,
    round_index: int,
    auth_key: bytes | None = None,
) -> tuple[int, int, bytes, dict[int, bytes]]:
    """-> (dealer id, threshold, b-seed commitment, {holder: blob}).
    ``auth_key`` is the DEALER's identity key when per-client keys are
    provisioned (the caller looks it up from the claimed dealer id before
    verifying)."""
    import hmac

    if not frame.startswith(SHARES_MAGIC):
        raise SecureAggError("not a shares frame")
    body_end = len(frame) - (_TAG_LEN if auth_key is not None else 0)
    body = frame[len(SHARES_MAGIC) : body_end]
    if auth_key is not None and not hmac.compare_digest(
        frame[body_end:],
        _unmask_tag(auth_key, b"-sh", session, round_index, body),
    ):
        raise SecureAggError("shares frame failed its authenticity check")
    if len(body) < 9 + 32 + 4:
        raise SecureAggError("truncated shares frame")
    dealer, threshold = struct.unpack("<qB", body[:9])
    commit = body[9:41]
    (n,) = struct.unpack("<I", body[41:45])
    entry = 8 + SHARE_BLOB_LEN
    if len(body) != 45 + n * entry:
        raise SecureAggError("malformed shares frame body")
    blobs: dict[int, bytes] = {}
    for off in range(45, len(body), entry):
        (holder,) = struct.unpack("<q", body[off : off + 8])
        if holder in blobs:
            raise SecureAggError(f"duplicate holder {holder} in shares frame")
        blobs[holder] = body[off + 8 : off + entry]
    return dealer, threshold, commit, blobs


def build_shareset_frame(
    share_set: Sequence[int],
    entries: Mapping[int, bytes],
    *,
    session: bytes,
    round_index: int,
    auth_key: bytes | None = None,
) -> bytes:
    """Server -> holder: the round's share-complete participant set U2
    (the set everyone masks over) plus this holder's encrypted share
    blobs from every other dealer in U2."""
    u2 = sorted(set(int(x) for x in share_set))
    body = struct.pack("<I", len(u2)) + b"".join(
        struct.pack("<q", i) for i in u2
    ) + struct.pack("<I", len(entries))
    for dealer in sorted(entries):
        body += struct.pack("<q", int(dealer)) + entries[dealer]
    msg = SHARESET_MAGIC + body
    if auth_key is not None:
        msg += _unmask_tag(auth_key, b"-ss", session, round_index, body)
    return msg


def parse_shareset_frame(
    frame: bytes,
    *,
    session: bytes,
    round_index: int,
    auth_key: bytes | None = None,
) -> tuple[list[int], dict[int, bytes]]:
    """-> (U2 ids, {dealer: blob for this holder})."""
    import hmac

    if not frame.startswith(SHARESET_MAGIC):
        raise SecureAggError("not a shareset frame")
    body_end = len(frame) - (_TAG_LEN if auth_key is not None else 0)
    body = frame[len(SHARESET_MAGIC) : body_end]
    if auth_key is not None and not hmac.compare_digest(
        frame[body_end:],
        _unmask_tag(auth_key, b"-ss", session, round_index, body),
    ):
        raise SecureAggError("shareset frame failed its authenticity check")
    if len(body) < 4:
        raise SecureAggError("truncated shareset frame")
    (nu,) = struct.unpack("<I", body[:4])
    off = 4 + 8 * nu
    if len(body) < off + 4:
        raise SecureAggError("malformed shareset frame body")
    u2 = list(struct.unpack(f"<{nu}q", body[4:off]))
    if len(set(u2)) != nu:
        raise SecureAggError("duplicate ids in shareset U2")
    (m,) = struct.unpack("<I", body[off : off + 4])
    entry = 8 + SHARE_BLOB_LEN
    if len(body) != off + 4 + m * entry:
        raise SecureAggError("malformed shareset frame body")
    entries: dict[int, bytes] = {}
    for e in range(off + 4, len(body), entry):
        (dealer,) = struct.unpack("<q", body[e : e + 8])
        if dealer in entries:
            raise SecureAggError(f"duplicate dealer {dealer} in shareset")
        entries[dealer] = body[e + 8 : e + entry]
    return u2, entries
