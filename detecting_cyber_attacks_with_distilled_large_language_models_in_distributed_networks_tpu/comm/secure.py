"""Secure aggregation for the cross-host TCP mode: pairwise masking.

In the reference every client ships its raw state dict to the server, which
can read each client's exact weights (reference server.py:57-65) — the
aggregate is the only thing clients intend to reveal, but the server learns
far more. This module implements the canonical fix (the pairwise-mask
construction of Bonawitz et al., "Practical Secure Aggregation for
Privacy-Preserving Machine Learning", CCS 2017, in its simplest
all-parties-survive form):

* every client quantizes its weights to fixed point (``fp_bits`` fractional
  bits) in the ring Z_2^64,
* each pair of clients (i, j) derives the same mask stream from a shared
  mask secret (which the server does NOT hold): client min(i,j) adds the
  stream, client max(i,j) subtracts it, all mod 2^64,
* the server sums the masked uint64 uploads — the masks cancel exactly in
  modular arithmetic — and recovers the plain fixed-point sum, which it
  de-quantizes into the mean.

Properties: the server (and any wire observer) sees each upload as
uniformly random ring elements; the sum over ALL participants is exact
(bit-exact modular cancellation, no float cancellation error); the only
loss vs plain FedAvg is the fixed-point quantization, 2^-fp_bits per
weight. Mask streams are domain-separated by a per-server-run random
``session`` nonce plus the advertised round number, so a stream is never
reused across rounds or server restarts; a client instance additionally
refuses a (session, round) it has already masked different weights for.

Threat model: honest-but-curious server and passive wire observers (the
semi-honest setting of the Bonawitz paper), with **mutually trusted
clients**: all pairwise streams derive from the ONE shared mask secret, so
any single client — or anyone who obtains that secret — can regenerate
every pair's stream and unmask every other client's upload from the wire.
Privacy here is against the server/wire only, not between clients; full
Bonawitz derives per-pair keys by Diffie-Hellman agreement so each client
can reconstruct only its own pairs. Also out of scope for this minimal
form: a fully malicious server actively replaying session nonces across
its own restarts (full Bonawitz adds signed key agreement), and client
dropout recovery — every advertised participant must upload; the server
enforces ``participants == all clients`` and fails the round otherwise,
which the caller sees as the reference-style failed-round path.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Mapping, Sequence

import numpy as np

#: Default fractional bits. 2^-24 ~ 6e-8 absolute quantization error per
#: weight — far below bf16 wire compression and Adam-step noise.
DEFAULT_FP_BITS = 24

_DOMAIN = b"fedtpu-secagg-v1"


class SecureAggError(ValueError):
    """Inconsistent secure-aggregation round (participants/format)."""


def quantize(flat: Mapping[str, np.ndarray], fp_bits: int = DEFAULT_FP_BITS) -> dict[str, np.ndarray]:
    """float32 params -> fixed-point ring elements (uint64, two's complement)."""
    scale = float(1 << fp_bits)
    out = {}
    for key in sorted(flat):
        arr = np.asarray(flat[key])
        if not np.issubdtype(arr.dtype, np.floating):
            raise SecureAggError(f"tensor {key!r} is {arr.dtype}, expected float")
        q = np.round(arr.astype(np.float64) * scale).astype(np.int64)
        out[key] = q.view(np.uint64)
    return out


def dequantize_sum(
    summed: Mapping[str, np.ndarray], n_clients: int, fp_bits: int = DEFAULT_FP_BITS
) -> dict[str, np.ndarray]:
    """Ring sum over clients -> float32 mean. The modular sum re-interpreted
    as int64 is the exact signed fixed-point sum as long as
    ``|sum| < 2^63 / 2^fp_bits`` per element (n_clients * max|w| < 2^39 at
    the default 24 bits — orders of magnitude of headroom)."""
    scale = float(1 << fp_bits)
    out = {}
    for key, arr in summed.items():
        if arr.dtype != np.uint64:
            raise SecureAggError(f"summed tensor {key!r} is {arr.dtype}, expected uint64")
        signed = arr.view(np.int64)
        out[key] = (signed / (scale * n_clients)).astype(np.float32)
    return out


def _pair_stream(
    mask_secret: bytes, session: bytes, round_index: int, lo: int, hi: int
) -> np.random.Generator:
    """The (lo, hi) client pair's shared mask PRG for one round. Both ends
    derive the identical stream; nobody without the mask secret can.

    ``session`` is the server run's random nonce (delivered in the round
    advert): it domain-separates mask streams across server restarts, so
    re-running the pipeline with the same secret and the same round
    numbers never reuses a stream."""
    if not 0 <= round_index < 2**63:
        raise SecureAggError(f"round_index {round_index} out of range [0, 2^63)")
    digest = hashlib.sha256(
        _DOMAIN + mask_secret + session + struct.pack("<Qqq", round_index, lo, hi)
    ).digest()
    return np.random.Generator(
        np.random.Philox(key=int.from_bytes(digest[:16], "little"))
    )


def mask(
    quantized: Mapping[str, np.ndarray],
    *,
    mask_secret: bytes,
    round_index: int,
    client_id: int,
    participants: Sequence[int],
    session: bytes = b"",
) -> dict[str, np.ndarray]:
    """Add this client's pairwise masks: +stream for partners above it,
    -stream for partners below (mod 2^64), per sorted tensor key. Summing
    every participant's masked upload cancels all masks bit-exactly."""
    ids = sorted(set(int(p) for p in participants))
    if int(client_id) not in ids:
        raise SecureAggError(f"client {client_id} not in participants {ids}")
    if len(ids) < 2:
        # A single participant has nobody to pair with; masking would be a
        # no-op that still leaks the raw update — refuse loudly.
        raise SecureAggError("secure aggregation needs >= 2 participants")
    out = {k: np.array(quantized[k], dtype=np.uint64, copy=True) for k in sorted(quantized)}
    for other in ids:
        if other == client_id:
            continue
        lo, hi = min(client_id, other), max(client_id, other)
        rng = _pair_stream(mask_secret, session, round_index, lo, hi)
        for key in sorted(out):
            stream = rng.integers(
                0, 2**64, size=out[key].shape, dtype=np.uint64, endpoint=False
            )
            if client_id == lo:
                out[key] += stream  # uint64 wraps mod 2^64
            else:
                out[key] -= stream
    return out


def masked_upload(
    flat: Mapping[str, np.ndarray],
    *,
    mask_secret: bytes,
    round_index: int,
    client_id: int,
    participants: Sequence[int],
    fp_bits: int = DEFAULT_FP_BITS,
    session: bytes = b"",
) -> dict[str, np.ndarray]:
    """Client-side one-call path: quantize then mask."""
    return mask(
        quantize(flat, fp_bits),
        mask_secret=mask_secret,
        round_index=round_index,
        client_id=client_id,
        participants=participants,
        session=session,
    )


def sum_masked(models: Sequence[Mapping[str, np.ndarray]]) -> dict[str, np.ndarray]:
    """Server-side ring sum of masked uploads (mod 2^64). With every
    participant present the pairwise masks cancel exactly."""
    if not models:
        raise SecureAggError("no masked models to sum")
    keys = set(models[0])
    for i, m in enumerate(models[1:], 1):
        if set(m) != keys:
            raise SecureAggError(f"masked model {i} key set differs from model 0")
    out = {}
    for key in keys:
        acc = np.zeros_like(np.asarray(models[0][key], np.uint64))
        for m in models:
            arr = np.asarray(m[key])
            if arr.dtype != np.uint64 or arr.shape != acc.shape:
                raise SecureAggError(
                    f"masked tensor {key!r}: dtype/shape mismatch "
                    f"({arr.dtype}, {arr.shape})"
                )
            acc += arr
        out[key] = acc
    return out


def aggregate_masked(
    models: Sequence[Mapping[str, np.ndarray]],
    fp_bits: int = DEFAULT_FP_BITS,
) -> dict[str, np.ndarray]:
    """Server-side: masked uploads (all participants!) -> float32 mean."""
    return dequantize_sum(sum_masked(models), len(models), fp_bits)
