"""Cross-host communication backend (demo-parity mode).

On-TPU federation never serializes weights (parallel/fedavg.py — the round
is a mesh collective). This package covers the reference's other deployment
shape — clients as separate processes on separate hosts over TCP (reference
client1.py:246-336, server.py:29-114) — with a non-executable wire format,
CRC'd chunked framing, and a native C++ byte-path (native/fedwire.cpp).
"""

from .client import FederatedClient, connect_with_retry  # noqa: F401
from .framing import PipelinedSender, recv_frame, send_frame  # noqa: F401
from .relay import RelayAggregator, aggregate_tree  # noqa: F401
from .secure import SecureAggError, aggregate_masked, masked_upload  # noqa: F401
from .server import AggregationServer, aggregate_flat  # noqa: F401
from .stream_agg import StreamAgg, StreamAggPoisoned  # noqa: F401
from .wire import (  # noqa: F401
    WireError,
    decode,
    encode,
    flatten_params,
    unflatten_params,
)
