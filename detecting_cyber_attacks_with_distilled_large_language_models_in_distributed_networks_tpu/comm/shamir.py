"""Shamir secret sharing over GF(256), byte-wise.

The primitive under the full-Bonawitz double-masking secure aggregation
(comm/secure.py): each client Shamir-shares its self-mask seed and its DH
key seed at threshold ``t`` so the unmask round tolerates dropouts — any
``t`` of the ``n`` holders reconstruct, fewer learn nothing (each byte is
a degree ``t-1`` polynomial; ``t-1`` points leave the constant term
uniform).

Classic SSS in the AES field (x^8 + x^4 + x^3 + x + 1, 0x11b), one
polynomial per secret byte, share x-coordinates in 1..255 (here: client
id + 1, so ids must stay < 255). Secrets are short (32-byte seeds), so
the pure-Python field arithmetic is microseconds per share; the reference
has no secret sharing (or any cryptography) at all — its server reads raw
weights off the wire (reference server.py:57-65).
"""

from __future__ import annotations

import os
from typing import Iterable, Mapping

# exp/log tables for GF(2^8) with the AES reduction polynomial; generator 3.
_EXP = [0] * 512
_LOG = [0] * 256
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    # multiply by the generator 0x03 = x + 1: x*3 = (x<<1) ^ x
    _x = (_x << 1) ^ _x
    if _x & 0x100:
        _x ^= 0x11B
for _i in range(255, 512):
    _EXP[_i] = _EXP[_i - 255]


def _mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return _EXP[_LOG[a] + _LOG[b]]


def _inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("no inverse of 0 in GF(256)")
    return _EXP[255 - _LOG[a]]


class ShamirError(ValueError):
    """Malformed shares or parameters."""


def split(
    secret: bytes,
    xs: Iterable[int],
    threshold: int,
    *,
    rng: "os.urandom.__class__ | None" = None,
) -> dict[int, bytes]:
    """Share ``secret`` to the holders at x-coordinates ``xs`` (distinct,
    in 1..255) so any ``threshold`` of them reconstruct it. ``rng``
    overrides the coefficient sampler (os.urandom) for tests ONLY —
    deterministic coefficients void the secrecy guarantee."""
    xs = [int(x) for x in xs]
    n = len(xs)
    if len(set(xs)) != n:
        raise ShamirError(f"duplicate share x-coordinates: {sorted(xs)}")
    if not all(1 <= x <= 255 for x in xs):
        raise ShamirError(f"share x-coordinates must be in 1..255: {sorted(xs)}")
    if not 1 <= threshold <= n:
        raise ShamirError(f"threshold {threshold} out of range [1, {n}]")
    draw = os.urandom if rng is None else rng
    shares = {x: bytearray(len(secret)) for x in xs}
    for bi, s in enumerate(secret):
        # f(0) = secret byte; higher coefficients uniform.
        coeffs = [s] + list(draw(threshold - 1))
        for x in xs:
            y = 0
            for c in reversed(coeffs):  # Horner in GF(256)
                y = _mul(y, x) ^ c
            shares[x][bi] = y
    return {x: bytes(v) for x, v in shares.items()}


def combine(shares: Mapping[int, bytes]) -> bytes:
    """Reconstruct the secret from ``>= threshold`` shares (Lagrange at 0).
    Passing more than ``threshold`` consistent shares is fine — they lie
    on the same polynomial; inconsistent or too-few shares reconstruct
    garbage, which callers must detect semantically (the double-masking
    server verifies reconstructed DH seeds against the dealt public
    keys, comm/secure.py)."""
    if not shares:
        raise ShamirError("no shares to combine")
    xs = [int(x) for x in shares]
    if not all(1 <= x <= 255 for x in xs):
        raise ShamirError(f"share x-coordinates must be in 1..255: {sorted(xs)}")
    lengths = {len(v) for v in shares.values()}
    if len(lengths) != 1:
        raise ShamirError(f"inconsistent share lengths: {sorted(lengths)}")
    (length,) = lengths
    # Lagrange basis at 0 depends only on the x set — compute once.
    lag = []
    for j, xj in enumerate(xs):
        num = den = 1
        for m, xm in enumerate(xs):
            if m != j:
                num = _mul(num, xm)
                den = _mul(den, xj ^ xm)
        lag.append(_mul(num, _inv(den)))
    out = bytearray(length)
    ys = [shares[x] for x in xs]
    for bi in range(length):
        acc = 0
        for lj, y in zip(lag, ys):
            acc ^= _mul(y[bi], lj)
        out[bi] = acc
    return bytes(out)
