"""ctypes bindings for the native fedwire byte-path, with numpy fallback.

``lib()`` lazily builds (native/build.py) and loads fedwire.so. Every entry
point has a pure-numpy twin so the wire format works identically without a
C++ toolchain; ``HAVE_NATIVE`` reports which path is active. zlib.crc32 and
the native crc32 implement the same IEEE polynomial — payloads checksummed
by one verify under the other.
"""

from __future__ import annotations

import ctypes
import zlib

import numpy as np

from ..utils.native import load_native


def _configure(cdll: ctypes.CDLL) -> None:
    cdll.fedwire_crc32.restype = ctypes.c_uint32
    cdll.fedwire_crc32.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_uint32,
    ]
    cdll.fedwire_pack_bf16.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_size_t,
    ]
    cdll.fedwire_unpack_bf16.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_size_t,
    ]
    cdll.fedwire_xor.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_size_t,
    ]


def lib() -> ctypes.CDLL | None:
    return load_native("fedwire.cpp", "fedwire.so", _configure)


def have_native() -> bool:
    return lib() is not None


# ------------------------------------------------------------------- crc32
def crc32(data: bytes | bytearray | memoryview | np.ndarray, seed: int = 0) -> int:
    """Zero-copy where possible — frames here are ~250 MB model payloads."""
    cdll = lib()
    if cdll is None:
        return zlib.crc32(data, seed)  # zlib takes any contiguous buffer
    if not isinstance(data, np.ndarray):
        data = np.frombuffer(data, np.uint8)  # view, not copy
    buf = np.ascontiguousarray(data)
    return int(
        cdll.fedwire_crc32(
            ctypes.c_char_p(buf.ctypes.data), buf.nbytes, seed
        )
    )


# --------------------------------------------------------------- bf16 pack
def pack_bf16(x: np.ndarray) -> np.ndarray:
    """fp32 array -> uint16 bf16 payload (round-to-nearest-even)."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    out = np.empty(x.shape, np.uint16)
    cdll = lib()
    if cdll is not None and x.size:
        cdll.fedwire_pack_bf16(
            x.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
            x.size,
        )
        return out
    import ml_dtypes  # JAX dependency; its cast is the TPU RNE semantics

    return x.astype(ml_dtypes.bfloat16).view(np.uint16)


def unpack_bf16(x: np.ndarray, shape=None) -> np.ndarray:
    """uint16 bf16 payload -> fp32 array."""
    x = np.ascontiguousarray(x, dtype=np.uint16)
    out = np.empty(x.shape, np.uint32)
    cdll = lib()
    if cdll is not None and x.size:
        cdll.fedwire_unpack_bf16(
            x.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
            x.size,
        )
    else:
        import ml_dtypes

        out[...] = (
            x.view(ml_dtypes.bfloat16).astype(np.float32).view(np.uint32)
        )
    f = out.view(np.float32)
    return f.reshape(shape) if shape is not None else f


# --------------------------------------------------------------- xor delta
def xor_bytes(src: np.ndarray, dst: np.ndarray) -> None:
    """dst ^= src in place (uint8 arrays of equal size); self-inverse."""
    if src.dtype != np.uint8 or dst.dtype != np.uint8 or src.size != dst.size:
        raise ValueError("xor_bytes wants equal-size uint8 arrays")
    cdll = lib()
    if cdll is not None and src.size:
        cdll.fedwire_xor(
            np.ascontiguousarray(src).ctypes.data_as(ctypes.c_void_p),
            dst.ctypes.data_as(ctypes.c_void_p),
            src.size,
        )
    else:
        np.bitwise_xor(dst, src, out=dst)
