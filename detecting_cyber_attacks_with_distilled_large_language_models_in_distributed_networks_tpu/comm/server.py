"""Aggregation server for the cross-host demo-parity mode.

On TPU, FedAvg is a ``pmean`` on the mesh and there is no server at all
(parallel/fedavg.py). This module exists for the reference's *other*
capability: genuinely separate client processes on separate hosts
(reference server.py end-to-end). Differences from the reference, by
design:

* one port, request/response on a single connection — the reference's
  second listening port plus 1 s client polling (client1.py:298-311,
  server.py:81-114) is a built-in race: probe connects are accepted by the
  send loop and kill it (WinError 10053 in the golden logs,
  server_terminal_output.txt:19,27). With request/response there is nothing
  to poll: the reply arrives on the connection the upload used.
* clients are identified by the ``client_id`` in the message meta, not by
  accept order (the reference can serve one client twice and starve
  another, SURVEY.md §5).
* weighted FedAvg by ``n_samples`` (optional) and a ``min_clients``
  quorum with a round deadline, instead of hanging forever when a client
  dies (reference server.py:69-71 + 124-132).
* wire format is non-executable (comm/wire.py) — no pickle RCE.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from .. import strategies
from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs.profile import note_memory
from ..obs.trace import new_trace_id
from ..utils.logging import get_logger
from . import framing, secure, wire
from .stream_agg import StreamAgg

log = get_logger()


def aggregate_flat(
    models: list[dict[str, np.ndarray]], weights: list[float] | None = None
) -> dict[str, np.ndarray]:
    """Weighted element-wise mean of flat param dicts (fp32 accumulation),
    the reference's ``aggregate_models`` (server.py:67-79) without the
    in-place mutation of client 0's weights."""
    if not models:
        raise ValueError("no models to aggregate")
    keys = set(models[0])
    for i, m in enumerate(models[1:], 1):
        if set(m) != keys:
            raise wire.WireError(f"model {i} key set differs from model 0")
    if weights is None:
        w = np.ones(len(models), np.float64)
    else:
        w = np.asarray(weights, np.float64)
        if w.shape != (len(models),) or w.sum() <= 0:
            raise ValueError(f"bad weights {weights}")
    w = w / w.sum()
    out: dict[str, np.ndarray] = {}
    for key in models[0]:
        acc = np.zeros_like(np.asarray(models[0][key], np.float32))
        for wi, m in zip(w, models):
            if m[key].shape != acc.shape:
                raise wire.WireError(f"shape mismatch for {key!r}")
            acc += np.float32(wi) * np.asarray(m[key], np.float32)
        out[key] = acc
    return out


@dataclass
class _Round:
    """One aggregation round's rendezvous state."""

    expected: int
    round_no: int = 0
    #: Round-scoped trace id (obs/trace.py), minted by serve_round and
    #: stamped into every reply's meta so clients adopt the same identity.
    trace: str = ""
    models: dict[int, dict] = field(default_factory=dict)  # client_id -> flat params
    # Sparse-delta uploads (topk clients): flat params holds the DENSIFIED
    # round delta; the absolute model is base + delta at aggregation time.
    deltas: dict[int, bool] = field(default_factory=dict)
    n_samples: dict[int, float] = field(default_factory=dict)
    conns: dict[int, socket.socket] = field(default_factory=dict)
    nonces: dict[int, str] = field(default_factory=dict)  # auth mode only
    # Secure mode: each participant's (pubkey, tag) hello, relayed to all
    # once everyone's arrived (keys_ready) — or, after the key grace
    # window, to the quorum subset that did arrive (key_set). The server
    # never holds any private key — it only forwards public values.
    pubkeys: dict[int, bytes] = field(default_factory=dict)
    key_set: list | None = None  # sorted ids the keys frame covered
    keys_ready: threading.Event = field(default_factory=threading.Event)
    # Double-masking (secure_protocol="double"): each dealer's encrypted
    # share blobs ({holder: blob}) + its b-seed commitment; U2 (share_set)
    # is the share-complete subset everyone masks over.
    share_blobs: dict[int, dict] = field(default_factory=dict)
    share_commits: dict[int, bytes] = field(default_factory=dict)
    share_set: list | None = None
    shares_ready: threading.Event = field(default_factory=threading.Event)
    # Central DP: each upload's declared round-base crc; the round only
    # aggregates when all are identical (a common anchor is what makes
    # the clipped-delta mean well-defined).
    dp_crcs: dict[int, int] = field(default_factory=dict)
    # Poisson cohort sampling (dp_participation < 1): the round's sampled
    # id set, drawn once per round from OS entropy; non-sampled clients
    # register here to receive the round's reply without contributing.
    cohort: set | None = None
    skip_conns: dict[int, socket.socket] = field(default_factory=dict)
    lock: threading.Lock = field(default_factory=threading.Lock)
    complete: threading.Event = field(default_factory=threading.Event)
    # Set (under lock) when serve_round snapshots the round; a handler that
    # finishes its recv after this must drop the connection, not register
    # into an abandoned round.
    closed: bool = False
    # True once any upload this round came from a sparse-delta-capable
    # client (meta ``delta`` or ``wants_delta``): gates the reply's
    # ``agg_crc`` stamp, a full fp32 pass + tobytes() copy over the whole
    # model that deployments with no topk client shouldn't pay every round.
    wants_delta: bool = False
    # Streaming chunk aggregation (comm/stream_agg.py): every plain/DP
    # upload — streamed or single-frame — registers here; leaves fold
    # into the running mean as they complete. None in secure-agg mode
    # (masked sums keep the barrier path).
    stream: Any = None
    # Clients whose upload meta advertised streamed-REPLY capability
    # (wire.STREAM_REPLY_META_KEY): their reply fan-out goes out as
    # STRH/STRC/STRT frames instead of one dense model-sized frame.
    stream_replies: set = field(default_factory=set)
    # Per-client quantized-reply capability (wire.REPLY_DTYPE_META_KEY):
    # the stream leaf encodings each stream-reply client said it can
    # dequantize. A --reply-dtype server only sends its lossy encoding
    # to clients whose advert includes it; everyone else gets the fp32
    # stream (capability-negotiated, like the upload leg's wire_dtypes).
    reply_dtype_encs: dict[int, tuple] = field(default_factory=dict)
    # Wire dtype each STREAMED upload actually arrived in ("fp32" /
    # "bf16" / "int8"), derived from its header's leaf encodings — the
    # wire-overlap span's wire_dtypes attr and the by-dtype /metrics
    # label. Dense single-frame uploads are not recorded here (their
    # encoding is the legacy compression knob, not a wire dtype).
    wire_dtypes: dict[int, str] = field(default_factory=dict)
    # Survivable fold trees (comm/relay.py): ids adopted into this round
    # via the re-home marker (wire.REHOME_META_KEY) — EXTRA contributors
    # from a dead sibling subtree. They fold with everyone else
    # (ascending id) but never count toward ``expected``, so adoption
    # cannot mask a local quorum miss; completion additionally waits for
    # every adopted upload to finish (they widen the fold set).
    adopted: set = field(default_factory=set)
    # Per-upload contributor record (wire.SUBTREE_IDS_META_KEY, stamped
    # by relays on their upward upload): uploader id -> the ascending
    # client ids its partial folded. The round's ACTUAL (relay ->
    # contributors) assignment — the crc contract's replay input — and
    # the double-count tripwire (one client in two subtrees' lists
    # fails the round loudly).
    subtree_ids: dict[int, list] = field(default_factory=dict)


class AggregationServer:
    """Receive ``num_clients`` models, FedAvg, reply on the same connections.

    ``serve_round()`` runs one round; ``serve(rounds=N)`` loops. A round
    deadline plus ``min_clients`` lets the mean proceed over survivors
    (masked mean) instead of hanging on a dead client.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        num_clients: int = 2,
        weighted: bool = False,
        min_clients: int | None = None,
        timeout: float = 300.0,  # the reference's TIMEOUT (server.py:10)
        compression: str = "none",
        auth_key: bytes | None = None,
        secure_agg: bool = False,
        fp_bits: int = secure.DEFAULT_FP_BITS,
        key_grace: float | None = None,
        dp_clip: float = 0.0,
        dp_noise_multiplier: float = 0.0,
        client_keys: dict[int, bytes] | None = None,
        secure_protocol: str = "double",
        secure_threshold: int | None = None,
        dp_participation: float = 1.0,
        dp_resync_rounds: int = 8,
        dp_history_path: str | None = None,
        tracer=None,
        stream_chunk_bytes: int = wire.DEFAULT_STREAM_CHUNK,
        strategy: str | None = None,
        strategy_state_path: str | None = None,
        reply_dtype: str = "fp32",
    ):
        if client_keys is not None and auth_key is None:
            raise ValueError(
                "client_keys (per-client DH identity binding) requires "
                "auth_key: the wire messages and the relayed keys frame "
                "are authenticated under the group key"
            )
        if dp_noise_multiplier > 0.0 and dp_clip <= 0.0:
            raise ValueError("dp_noise_multiplier needs dp_clip > 0")
        if dp_clip > 0.0 and weighted:
            raise ValueError(
                "central DP is a uniform mean over clipped updates; "
                "weighted=True is incompatible"
            )
        if secure_agg and weighted:
            raise ValueError(
                "secure aggregation is an unweighted ring sum; "
                "weighted=True is incompatible"
            )
        if secure_agg and min_clients is not None and min_clients < 2:
            raise ValueError(
                "secure aggregation needs min_clients >= 2: a lone "
                "survivor's 'sum' is its raw update"
            )
        if secure_protocol not in ("reveal", "double"):
            raise ValueError(
                f"secure_protocol {secure_protocol!r} must be reveal|double"
            )
        if secure_agg and secure_protocol == "double" and num_clients > 254:
            raise ValueError(
                "double-masking Shamir x-coordinates support <= 254 clients"
            )
        if secure_threshold is not None and secure_threshold < 2:
            raise ValueError(
                "secure_threshold < 2 would let the server reconstruct "
                "secrets from a single holder"
            )
        if not 0.0 < dp_participation <= 1.0:
            raise ValueError(
                f"dp_participation={dp_participation} must be in (0, 1]"
            )
        if dp_participation < 1.0 and dp_clip <= 0.0:
            raise ValueError(
                "dp_participation < 1 is the DP cohort sampler; it needs "
                "dp_clip > 0 (the sampling exists for the accountant's "
                "privacy amplification)"
            )
        if compression.startswith("topk"):
            raise ValueError(
                "topk is an upload-side (sparse round-delta) compression; "
                "the reply is an absolute aggregate — use none/bf16/int8"
            )
        # Quantized streamed replies (--reply-dtype): the downward mirror
        # of the upload leg's --wire-dtype. Only the STREAMED reply leg
        # quantizes — dense replies (old clients, non-advertisers, resync
        # payloads) stay exactly self.compression — so the knob composes
        # per client via capability negotiation, never by assumption.
        if reply_dtype not in wire.WIRE_DTYPE_ENCS:
            raise ValueError(
                f"reply_dtype {reply_dtype!r} must be one of "
                f"{sorted(wire.WIRE_DTYPE_ENCS)}"
            )
        if reply_dtype != "fp32":
            if secure_agg:
                # Mirror of the upload rule: the unmask protocol releases
                # the exact masked sum; a lossy re-encode of that release
                # would hand clients a DIFFERENT value than the protocol
                # authorized (and break the bit-exact base agreement the
                # masked rounds depend on).
                raise ValueError(
                    "lossy reply_dtype is refused under secure aggregation:"
                    " the unmask release is bit-exact by contract"
                )
            if compression != "none":
                raise ValueError(
                    "reply_dtype and a reply compression are two encoders "
                    "for the same leg; pass one (compression "
                    f"{compression!r} already re-encodes the reply)"
                )
        self.reply_dtype = reply_dtype
        # Server aggregation strategy (strategies/): a pure transform of
        # (previous global, folded mean) applied at finalize — the fold
        # itself is untouched, so "fedavg" is bit-identical to the
        # historical round. Validated here so a typo fails at construction,
        # not mid-round.
        self._strategy = strategies.make_strategy(strategy)
        if self._strategy.name != "fedavg":
            if secure_agg:
                raise ValueError(
                    f"strategy {self._strategy.name!r} is incompatible "
                    "with secure aggregation: the unmask protocol "
                    "releases exactly the masked SUM; a server-side "
                    "post-transform would operate on (and leak through) "
                    "a different release"
                )
            if dp_clip > 0.0:
                raise ValueError(
                    f"strategy {self._strategy.name!r} is incompatible "
                    "with central DP: the DP release is the noised mean "
                    "DELTA with a calibrated sensitivity; an optimizer "
                    "transform on top would change what is released "
                    "without re-deriving the bound"
                )
        self.num_clients = num_clients
        self.weighted = weighted
        self.min_clients = num_clients if min_clients is None else min_clients
        self.timeout = timeout
        self.compression = compression
        self.auth_key = auth_key
        self.secure_agg = secure_agg
        # "double" (default): full Bonawitz double-masking — self-mask +
        # Shamir-shared seeds, unmask round every round; closes the
        # false-death unmask and survives dropouts during unmasking.
        # "reveal": the cheaper reveal-round variant (no share
        # distribution; a dropout during its reveal fails the round).
        self.secure_protocol = secure_protocol
        # Shamir threshold; None = strict majority of the round's U2 (the
        # default that makes the either/or share-reveal rule binding).
        self.secure_threshold = secure_threshold
        self.fp_bits = fp_bits
        # Central DP (dp_clip > 0): uploads must be clipped round deltas
        # (the client flag --dp; the advert carries clip+noise); the
        # aggregate is mean(clipped deltas) + Gaussian(noise*clip/n), and
        # the reply is that noised mean DELTA — this server never holds
        # absolute model weights in DP mode. Base agreement is enforced by
        # requiring every upload's dp_base_crc to be identical.
        self.dp_clip = float(dp_clip)
        self.dp_noise_multiplier = float(dp_noise_multiplier)
        # Poisson cohort sampling rate: each registered client is drawn
        # independently with probability q every round — the sampler the
        # subsampled-Gaussian accountant assumes, so the TCP tier's
        # epsilon is exact under q < 1 (privacy amplification), mirroring
        # the mesh tier's participation_mode="poisson".
        self.dp_participation = float(dp_participation)
        # Stranded-client resync (plain DP only): the server retains the
        # last ``dp_resync_rounds`` released round deltas together with
        # the base crc their round's uploads agreed on. A client that
        # missed a reply declares a base crc matching one of those
        # retained rounds; instead of failing the whole round, its (stale)
        # upload is excluded from the mean and it is answered with the
        # catch-up SEQUENCE of retained deltas (every one from its base
        # forward, including this round's), which it replays in round
        # order — the same fp32 additions every current client performed,
        # so the resynced base matches the fleet's bit-exactly and the
        # next round's crc agreement holds. Privacy cost: zero — each
        # retained delta is a post-noise DP OUTPUT, and re-releasing
        # released values is post-processing. Memory cost:
        # dp_resync_rounds model-sized fp32 trees. Not available under
        # secure-agg DP (a masked upload cannot be excluded from the sum
        # — the masks only cancel over the full set), under lossy reply
        # compression (the fleet's bases are the DECODED deltas, which
        # the fp32 retention cannot reproduce), or across server
        # restarts (history is in-memory);
        # a client staler than the window still fails the round's crc
        # agreement exactly as before.
        self.dp_resync_rounds = int(dp_resync_rounds)
        self._dp_history: list[tuple[int, dict]] = []
        # Resync-history persistence (ROADMAP's last resync residual):
        # with a path set, the retained post-noise deltas are written
        # after every round and RELOADED on construction, so a server
        # restart between rounds no longer re-strands stale clients —
        # they heal bit-exactly from the reloaded fp32 history (npz is
        # lossless). Post-noise deltas are DP outputs: persisting and
        # re-releasing them is free post-processing, same argument as
        # the in-memory retention.
        self.dp_history_path = dp_history_path
        # Single background writer with a latest-snapshot slot: the
        # window is up to dp_resync_rounds model-sized fp32 trees, and
        # re-serializing it synchronously inside serve_round would put
        # GB-scale disk I/O on the aggregation critical path every
        # round. Entries are immutable once appended, so a snapshot
        # list is safe to write off-thread; close() drains the writer
        # so a clean shutdown always leaves the newest window on disk.
        self._dp_persist_lock = threading.Lock()
        self._dp_persist_pending: list | None = None
        self._dp_persist_thread: threading.Thread | None = None
        if dp_history_path:
            self._load_dp_history()
        # Noise generator: Philox (counter-based, 128-bit crypto-derived
        # keying) keyed from OS entropy, never seeded deterministically —
        # the draw sequence is not predictable from any run artifact.
        # Residual caveat (stated in the serve banner): the samples are
        # float32 Gaussians, which the Mironov (2012) floating-point
        # precision attack applies to; a fully attack-hardened mechanism
        # would use a discrete Gaussian over the integers.
        self._dp_rng = np.random.Generator(
            np.random.Philox(key=int.from_bytes(os.urandom(16), "little"))
        )
        # Per-client DH identity keys (secure.py threat model): a hello
        # claiming id i must carry a tag under client i's OWN key, so no
        # group member can impersonate another in the key exchange.
        self.client_keys = dict(client_keys) if client_keys else None
        # Dropout-before-keys window: once a connected participant has
        # waited this long without the full fleet's DH hellos, the key set
        # closes at the min_clients quorum and the round proceeds without
        # the missing clients (secure.py "dropout recovery"). This is the
        # liveness/straggler trade-off knob: a client arriving after the
        # cut is ejected for the ROUND (its retries fail fast; it rejoins
        # next round), so the default is half the round budget — generous
        # to compute/shard skew, while a genuinely dead client still costs
        # at most half the deadline instead of failing the round outright.
        self.key_grace = timeout / 2.0 if key_grace is None else key_grace
        # Monotonic round counter plus a per-run random session nonce,
        # advertised to secure clients on connect: mask streams are keyed
        # by (session, round), so they are fresh across rounds AND across
        # server restarts (a restarted counter alone would reuse streams,
        # letting an observer difference two runs' uploads).
        self._round_counter = 0
        self._session = os.urandom(16)
        # Last completed aggregate (flat fp32) + its round index: the base
        # that sparse-delta (topk) uploads difference against. Advertised
        # to clients via the reply's ``agg_round`` meta; a restarted server
        # has no base and rejects delta uploads, which makes clients fall
        # back to a dense resend.
        self._last_agg: dict | None = None
        self._last_agg_round = -1
        # Server-state persistence (``strategy_state_path``): the last
        # post-strategy global, its round index, and the strategy's
        # optimizer-state leaves, written atomically after every plain
        # finalized round (dp_history_path's background-writer pattern)
        # and RELOADED on construction. Closes the PR 16 residual where
        # a restarted FedOpt/momentum root lost its optimizer memory and
        # re-adopted the bare mean on its first round — and, since
        # ``_last_agg``/``_last_agg_round`` come back too, sparse-delta
        # clients keep their base across the restart instead of paying a
        # dense resend. A reloaded state whose strategy describe() does
        # not match the configured strategy is ignored (operator swapped
        # strategies between runs: fresh optimizer memory is correct).
        self.strategy_state_path = strategy_state_path
        self._strategy_persist_lock = threading.Lock()
        self._strategy_persist_pending: tuple | None = None
        self._strategy_persist_thread: threading.Thread | None = None
        if strategy_state_path:
            self._load_strategy_state()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        # Backlog sized for fleet cohorts: 256 clients dialing one round
        # start simultaneously overflow the old num_clients*2 backlog on
        # small fleets' defaults (refused dials burn client retries);
        # the kernel clamps to SOMAXCONN, so asking high is free.
        self._sock.listen(max(128, num_clients * 2))
        self._sock.settimeout(timeout)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        # Bounded upload-handler pool (one task per accepted connection).
        # Unbounded thread-per-dial let a retry storm (or a port scan)
        # spawn without limit; the bound must still exceed the fleet —
        # secure-mode handlers all block concurrently on the DH/key
        # rendezvous, and duplicate retry dials legitimately coexist with
        # the originals — hence 2x the fleet plus slack, queueing the
        # excess instead of spawning it.
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(
            max_workers=2 * num_clients + 8,
            thread_name_prefix="fedtpu-upload",
        )
        # Every connection a handler is CURRENTLY serving (registered or
        # not — a mid-upload child is not in rnd.conns yet): close()
        # must be able to shed them all promptly.
        self._conn_lock = threading.Lock()
        self._open_conns: set[socket.socket] = set()
        # Observability (obs/): optional span tracer + always-on cheap
        # phase accounting. phase_seconds accumulates where each round's
        # wall went — wait (accept + straggler + upload wire), agg
        # (aggregation compute), reply (fan-out) — the measured comm/
        # compute breakdown bench.py's comm_phase_* headline fields and
        # the /metrics endpoint report. last_trace is the most recent
        # round's (trace id, round index) for callers (the controller)
        # that stamp their own follow-on spans with the round's identity.
        # Streamed uploads + streaming chunk aggregation (PR 5): the
        # preferred chunk size advertised in every reply's meta (wire.py
        # STREAM_META_KEY — plain meta, old clients interop unchanged).
        # 0 disables BOTH the advert and eager folding: every round then
        # runs the stop-the-world barrier shape (the bench's A/B arm).
        # Secure-agg rounds never advertise: a masked upload's unmask
        # protocol needs the full contributor set resolved before any
        # aggregate exists, so those stay single-frame by design.
        stream_chunk_cap = framing.MAX_FRAME - wire.STREAM_CHUNK_OVERHEAD
        if not 0 <= int(stream_chunk_bytes) <= stream_chunk_cap:
            # The cap leaves room for the STRC envelope (magic + seq +
            # auth tag): a full chunk must still encode into a frame the
            # transport accepts, or every streamed attempt would fail
            # and silently pay a dense retry.
            raise ValueError(
                f"stream_chunk_bytes={stream_chunk_bytes} must be in "
                f"[0, {stream_chunk_cap}] (0 = streaming off)"
            )
        self.stream_chunk_bytes = int(stream_chunk_bytes)
        # Cross-round streaming totals: bytes folded during the wait
        # phase (overlapped with the wire) vs after it, and the peak
        # aggregation-state footprint — the comm_overlap_frac /
        # server_peak_agg_bytes bench headline fields.
        # One lock for every stream_totals mutation: upload handlers on
        # the pool increment fallback/upload counters while serve_round
        # folds reply/peak stats — per-key dict ops are GIL-atomic, but
        # the discipline "all writers hold _totals_lock" is what the
        # static concurrency pass can actually verify, and it makes the
        # read side (comm_overlap_frac's two-key ratio) consistent
        # instead of torn-across-keys.
        self._totals_lock = threading.Lock()
        self.stream_totals = {
            "early_bytes": 0,
            "late_bytes": 0,
            "early_s": 0.0,
            "late_s": 0.0,
            "peak_agg_bytes": 0,
            "last_round_peak_bytes": 0,
            "stream_uploads": 0,
            # Reply-side streaming + fallback accounting (PR 7): replies
            # shipped as chunk streams, and dense uploads accepted while
            # the streaming advert was active (topk/secure-agg/old-peer/
            # retry fallbacks — the client logs its one-line reason).
            "stream_replies": 0,
            "stream_fallbacks": 0,
            # Compiled-fold telemetry (ops/fold.py), refreshed per round.
            "fold_engine": "",
            "last_fold_throughput_gbps": 0.0,
        }
        # Hierarchical fold tree hook (comm/relay.py): when set, the
        # plain round's aggregate is handed to this callable BETWEEN
        # aggregation and the reply fan-out — the relay forwards the
        # subtree partial to its parent and returns the ROOT aggregate,
        # which is what this subtree's clients then receive (and what
        # next round's sparse-delta base tracks). Incompatible with DP
        # (the partial would be an un-noised release) and secure-agg
        # (the unmask protocol needs the single-aggregator shape).
        self.reply_via = None
        self.tracer = tracer
        self.phase_seconds = {"wait": 0.0, "agg": 0.0, "reply": 0.0}
        self.last_trace: tuple[str, int] | None = None
        m = obs_metrics.default_registry()
        self._m_stream_uploads = m.counter(
            "fedtpu_server_stream_uploads_total",
            help="chunk-streamed client uploads accepted into a round",
        )
        self._m_stream_replies = m.counter(
            "fedtpu_server_stream_replies_total",
            help="aggregate replies fanned out as chunk streams",
        )
        self._m_stream_fallbacks = m.counter(
            "fedtpu_server_stream_fallbacks_total",
            help="dense single-frame uploads accepted while streaming "
            "was advertised (topk/secure-agg/old-peer/retry fallbacks)",
        )
        self._g_inflight_streams = m.gauge(
            "fedtpu_server_stream_inflight",
            help="chunk-streamed uploads currently mid-transfer",
        )
        # Wire efficiency (quantized uploads + compiled fold): uploads
        # by the wire dtype they actually arrived in, and the last
        # round's fold throughput. Label families are created per value
        # at record time (the registry memoizes on (name, labels)).
        self._m_uploads_by_dtype = lambda wd: m.counter(
            "fedtpu_server_stream_uploads_by_wire_dtype_total",
            help="chunk-streamed uploads accepted, by negotiated wire "
            "dtype (fp32|bf16|int8)",
            labels={"wire_dtype": wd},
        )
        self._g_fold_throughput = m.gauge(
            "fedtpu_server_fold_throughput_gbps",
            help="last round's fold throughput (bytes folded / fold "
            "seconds), by the active fold engine",
        )
        self._g_peak_agg = m.gauge(
            "fedtpu_server_peak_agg_bytes",
            help="peak aggregation-state bytes of the last round "
            "(accumulator + pending leaves)",
        )
        self._m_rounds = m.counter(
            "fedtpu_server_rounds_total",
            help="aggregation rounds started",
        )
        # Strategy plane (strategies/): rounds finalized per strategy —
        # the /metrics label postmortems join against the round trace's
        # strategy attr and the reply meta stamp. Created per label value
        # at finalize (set_strategy can swap mid-run); the registry
        # memoizes on (name, labels) so this is the single family owner.
        self._m_strategy_rounds = lambda name: m.counter(
            "fedtpu_strategy_rounds_total",
            help="aggregation rounds finalized, by server strategy",
            labels={"strategy": name},
        )
        self._m_round_failures = m.counter(
            "fedtpu_server_round_failures_total",
            help="rounds that raised (quorum miss, deadline, bad uploads)",
        )
        self._m_uploads = m.counter(
            "fedtpu_server_uploads_total",
            help="client model uploads accepted into a round",
        )
        self._m_bytes_in = m.counter(
            "fedtpu_server_wire_bytes_received_total",
            help="model upload payload bytes received",
        )
        self._m_bytes_out = m.counter(
            "fedtpu_server_wire_bytes_sent_total",
            help="aggregate reply payload bytes sent",
        )
        self._m_phase = {
            p: m.counter(
                "fedtpu_server_round_phase_seconds_total",
                help="round wall-clock by phase (wait|agg|reply)",
                labels={"phase": p},
            )
            for p in ("wait", "agg", "reply")
        }
        self._m_subtree_failures = m.counter(
            "fedtpu_relay_subtree_failures_total",
            help="expected fold-tree children missing from a completed "
            "round at a parent of relays (the subtree was dropped from "
            "the fold; the mean renormalized over survivors)",
        )
        self._m_stragglers_shed = m.counter(
            "fedtpu_relay_stragglers_shed_total",
            help="expected leaf clients missing from a completed quorum "
            "round (shed locally at this aggregator's deadline instead "
            "of stalling its parent)",
        )
        # Plain attribute twins for harnesses that hold the server object
        # (bench chaos arm, tests): mutated under _totals_lock like
        # stream_totals.
        self.tree_totals = {
            "subtree_failures": 0,
            "stragglers_shed": 0,
            "degraded_rounds": 0,
        }
        # The last completed round's ACTUAL aggregation assignment:
        # {"round": n, "groups": [...]} where each group is either a
        # bare uploader id (a leaf client / relay with no contributor
        # record) or the list of client ids a relay's partial folded —
        # exactly aggregate_tree's ``groups`` argument, in the root's
        # fold order. The crc contract replays over THIS, so a degraded
        # round (dead subtree, re-homed contributors) stays bit-exactly
        # checkable.
        self.last_assignment: dict | None = None
        self._cur_rnd: _Round | None = None
        self._h_round = m.histogram(
            "fedtpu_server_round_seconds",
            help="aggregation round wall-clock, failed rounds included "
            "(the round-duration SLO's burn-rate source, obs/slo.py)",
            buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0),
        )

    # -------------------------------------------------------------- strategy
    @property
    def strategy(self) -> "strategies.Strategy":
        return self._strategy

    def set_strategy(self, spec) -> "strategies.Strategy":
        """Swap the aggregation strategy BETWEEN rounds (per-round
        selection: a controller reads the round-START meta, decides, and
        swaps before calling ``serve_round``). Same compatibility rules
        as the constructor; optimizer state starts fresh — a strategy's
        server-optimizer memory is meaningless across a rule change."""
        strat = strategies.make_strategy(spec)
        if strat.name != "fedavg" and (self.secure_agg or self.dp_clip > 0.0):
            raise ValueError(
                f"strategy {strat.name!r} is incompatible with "
                "secure-agg/DP rounds (see the constructor's rationale)"
            )
        self._strategy = strat
        return strat

    # ------------------------------------------------------------- lifecycle
    def close(self) -> None:
        self._stop.set()
        self._sock.close()
        # Shed the current round's registered connections as EXPLICIT
        # failures, promptly: shutdown(SHUT_RDWR) interrupts both ends'
        # blocked recvs (the child waiting on its reply, the handler
        # mid-stream) where a bare close() is deferred by the
        # interpreter while a sibling thread sits in a syscall on the fd
        # (the faults-layer prompt-close discipline, PR 6). Without
        # this, a relay torn down mid-round left its children blocked
        # until their own socket timeouts — exactly the window client
        # re-homing needs to be short.
        rnd = self._cur_rnd
        shed: list[socket.socket] = []
        if rnd is not None:
            with rnd.lock:
                shed += list(rnd.conns.values()) + list(
                    rnd.skip_conns.values()
                )
        with self._conn_lock:
            # Mid-upload connections too: their handlers are still
            # reading the payload, so they are not registered yet — but
            # their clients are equally blocked and must fail now.
            shed += list(self._open_conns)
        for c in shed:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        # Queued-but-unstarted handler tasks are abandoned (their
        # connections close with the process); running ones are daemons
        # of the pool and drop out on their socket errors.
        self._pool.shutdown(wait=False, cancel_futures=True)
        # Drain the history writer: a clean shutdown must leave the
        # NEWEST resync window on disk, or a restart would re-strand
        # exactly the clients persistence exists to heal.
        with self._dp_persist_lock:
            t = self._dp_persist_thread
        if t is not None:
            t.join(timeout=60.0)
        # Same drain for the strategy-state writer: a clean shutdown is
        # exactly the restart this persistence exists to survive.
        with self._strategy_persist_lock:
            t = self._strategy_persist_thread
        if t is not None:
            t.join(timeout=60.0)

    def __enter__(self) -> "AggregationServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ----------------------------------------------------------------- round
    def _handle_upload(
        self, conn: socket.socket, rnd: _Round, deadline: float | None = None
    ) -> None:
        if deadline is None:
            deadline = time.monotonic() + self.timeout
        with self._conn_lock:
            self._open_conns.add(conn)
        try:
            conn.settimeout(self.timeout)
            nonce_hex = None
            if self.auth_key is not None:
                # Freshness + direction binding: a per-connection challenge
                # the client must echo inside its authenticated header, so a
                # captured upload can't be replayed into a later round, and
                # the reply (which echoes the same nonce with role=server)
                # can't be reflected. Without a key, the wire is the
                # reference-style open protocol and no challenge is sent.
                nonce_hex = os.urandom(wire.NONCE_LEN).hex()
                framing.send_frame(
                    conn, wire.NONCE_MAGIC + bytes.fromhex(nonce_hex)
                )
            dpid = None
            if self.dp_clip > 0.0:
                import struct as _dstruct

                # DP handshake, server-first: the mode advert lets a
                # mis-configured plain client diagnose the mismatch; the
                # client then identifies itself so the round's Poisson
                # cohort verdict can be made (and told) before any model
                # bytes move.
                framing.send_frame(
                    conn,
                    wire.DP_MAGIC
                    + _dstruct.pack(
                        "<ddd",
                        self.dp_clip,
                        self.dp_noise_multiplier,
                        self.dp_participation,
                    ),
                )
                idf = framing.recv_frame(conn)
                if len(idf) != len(wire.DPID_MAGIC) + 8 or (
                    not idf.startswith(wire.DPID_MAGIC)
                ):
                    raise wire.WireError("bad DP id hello")
                dpid = _dstruct.unpack("<q", idf[len(wire.DPID_MAGIC) :])[0]
                if not 0 <= dpid < self.num_clients:
                    raise wire.WireError(f"DP id hello from unknown client {dpid}")
                with rnd.lock:
                    sampled = rnd.cohort is None or dpid in rnd.cohort
                framing.send_frame(
                    conn,
                    wire.DPCOHORT_MAGIC + bytes([1 if sampled else 0]),
                )
                if not sampled:
                    # Sitting out: no upload, but the client still gets
                    # the round's reply (its base must track the fleet's).
                    if self.auth_key is not None:
                        # The contributor path authenticates via its
                        # HMAC'd upload; a sitting-out client must prove
                        # key knowledge too, or anyone could claim a
                        # non-sampled id, evict the real registration,
                        # and collect the aggregate.
                        import hmac as _hmac

                        ack = framing.recv_frame(conn)
                        want = wire.DPSKIP_MAGIC + _hmac.new(
                            self.auth_key,
                            wire.DPSKIP_DOMAIN
                            + bytes.fromhex(nonce_hex)
                            + _dstruct.pack("<q", dpid),
                            "sha256",
                        ).digest()
                        if not _hmac.compare_digest(ack, want):
                            raise wire.WireError(
                                f"sit-out ack for client {dpid} failed "
                                "its authenticity check"
                            )
                    with rnd.lock:
                        if rnd.closed:
                            conn.close()
                            return
                        old = rnd.skip_conns.pop(dpid, None)
                        if old is not None and old is not conn:
                            old.close()
                        rnd.skip_conns[dpid] = conn
                        if nonce_hex is not None:
                            rnd.nonces[dpid] = nonce_hex
                        done = self._round_done(rnd)
                    log.info(
                        f"[SERVER] client {dpid} sits out round "
                        f"{rnd.round_no} (cohort sampling "
                        f"q={self.dp_participation})"
                    )
                    if done:
                        rnd.complete.set()
                    return
            if self.secure_agg:
                # Advertise (round, session, protocol) so every participant
                # keys its mask streams identically — and freshly — for
                # this round, and speaks the same recovery protocol. The
                # client REFUSES a protocol differing from its own config
                # (a malicious advert can't downgrade double -> reveal).
                import struct as _struct

                proto = (
                    secure.PROTO_DOUBLE
                    if self.secure_protocol == "double"
                    else secure.PROTO_REVEAL
                )
                framing.send_frame(
                    conn,
                    wire.ROUND_MAGIC
                    + _struct.pack("<Q", rnd.round_no)
                    + self._session
                    + bytes([proto]),
                )
                # DH relay: collect this client's ephemeral public key,
                # wait for the full fleet's, then hand everyone the whole
                # set. The server only forwards public values — it cannot
                # derive any pair's mask secret.
                hello = framing.recv_frame(conn)
                tag_len = wire.AUTH_TAG_LEN if self.auth_key is not None else 0
                want_len = len(wire.PUBKEY_MAGIC) + 8 + secure.DH_PUB_LEN + tag_len
                if len(hello) != want_len or not hello.startswith(wire.PUBKEY_MAGIC):
                    raise wire.WireError("bad DH pubkey hello")
                off = len(wire.PUBKEY_MAGIC)
                hello_id = _struct.unpack("<q", hello[off : off + 8])[0]
                pub_and_tag = hello[off + 8 :]
                pub = pub_and_tag[: secure.DH_PUB_LEN]
                secure.check_dh_public(pub)
                if self.auth_key is not None:
                    if self.client_keys is not None:
                        # Identity binding: the tag must verify under the
                        # CLAIMED id's own key — a member holding only its
                        # own key (and the group key) cannot forge it.
                        hello_key = self.client_keys.get(hello_id)
                        if hello_key is None:
                            raise wire.WireError(
                                f"DH hello from client {hello_id} with no "
                                "registered per-client key"
                            )
                    else:
                        hello_key = self.auth_key
                    secure.verify_pubkey_tag(
                        hello_key, self._session, rnd.round_no,
                        hello_id, pub,
                        pub_and_tag[secure.DH_PUB_LEN :],
                    )
                    if self.client_keys is not None:
                        # Re-tag under the GROUP key for the relay:
                        # receivers hold the group key, not each other's
                        # identity keys. (The server attests what it
                        # verified — a malicious server could lie, which
                        # is the documented remaining adversary.)
                        pub_and_tag = pub + secure.pubkey_tag(
                            self.auth_key, self._session, rnd.round_no,
                            hello_id, pub,
                        )
                with rnd.lock:
                    if rnd.closed:
                        conn.close()
                        return
                    if not 0 <= hello_id < self.num_clients:
                        raise wire.WireError(
                            f"DH hello from unknown client id {hello_id}"
                        )
                    prev_hello = rnd.pubkeys.get(hello_id)
                    if prev_hello is not None and prev_hello != pub_and_tag:
                        # First registration wins. A DIFFERENT key for an
                        # already-registered id is either an impersonation
                        # attempt or a client that lost its per-round
                        # keypair — after distribution a new key could
                        # never cancel, and before it, honoring the swap
                        # would let a group member evict the honest holder.
                        log.info(
                            f"[SERVER] conflicting DH hello for client "
                            f"{hello_id}; dropping connection"
                        )
                        conn.close()
                        return
                    if prev_hello is None and rnd.keys_ready.is_set():
                        # Keys already relayed: a NEW participant key now
                        # would break mask cancellation for everyone who
                        # already derived pair secrets.
                        log.info(
                            f"[SERVER] late DH hello from client {hello_id} "
                            "after key distribution; dropping connection"
                        )
                        conn.close()
                        return
                    # Fresh registration, or an idempotent re-hello (same
                    # pubkey — a retrying client reuses its per-round
                    # keypair) which re-binds the connection.
                    old = rnd.conns.pop(hello_id, None)
                    if old is not None and old is not conn:
                        old.close()
                    rnd.pubkeys[hello_id] = pub_and_tag
                    # Register now so a failed round's cleanup closes this
                    # socket instead of leaving the client blocked on the
                    # keys frame until its own timeout.
                    rnd.conns[hello_id] = conn
                    if len(rnd.pubkeys) >= rnd.expected:
                        rnd.key_set = sorted(rnd.pubkeys)
                        rnd.keys_ready.set()
                log.info(
                    f"[SERVER] DH pubkey from client {hello_id} "
                    f"({len(rnd.pubkeys)}/{rnd.expected})"
                )
                # Wait for the full fleet's hellos — but after key_grace
                # without completion, close the key set at the quorum that
                # did arrive (dropout-before-keys recovery): the round
                # proceeds over the subset instead of failing outright.
                grace_end = time.monotonic() + self.key_grace
                while not rnd.keys_ready.is_set():
                    now = time.monotonic()
                    if now >= deadline:
                        raise wire.WireError(
                            "round deadline passed waiting for the "
                            "remaining participants' DH public keys"
                        )
                    # Before grace expiry, wake at grace_end to try the
                    # quorum close; after (quorum not met yet), sleep
                    # until the deadline — another hello's handler will
                    # close the set and wake everyone if a quorum forms.
                    wait_until = grace_end if now < grace_end else deadline
                    if rnd.keys_ready.wait(
                        timeout=max(0.0, wait_until - now)
                    ):
                        break
                    with rnd.lock:
                        if (
                            not rnd.keys_ready.is_set()
                            and time.monotonic() >= grace_end
                            and len(rnd.pubkeys) >= max(2, self.min_clients)
                        ):
                            rnd.key_set = sorted(rnd.pubkeys)
                            rnd.keys_ready.set()
                            log.info(
                                f"[SERVER] key grace expired; closing the "
                                f"key set at quorum {rnd.key_set}"
                            )
                            break
                with rnd.lock:
                    key_set = list(rnd.key_set or [])
                    entries = b"".join(
                        _struct.pack("<q", cid) + rnd.pubkeys[cid]
                        for cid in key_set
                    )
                if hello_id not in key_set:
                    # Arrived during finalization but after the cut: a key
                    # outside the distributed set could never cancel.
                    log.info(
                        f"[SERVER] client {hello_id} missed the key set "
                        f"{key_set}; dropping connection"
                    )
                    conn.close()
                    return
                framing.send_frame(conn, wire.KEYS_MAGIC + entries)
                if self.secure_protocol == "double":
                    if not self._shares_exchange(
                        conn, rnd, hello_id, key_set, deadline
                    ):
                        return
            payload = framing.recv_frame(conn)
            self._m_bytes_in.inc(float(len(payload)))
            if bytes(payload[:4]) == wire.STREAM_MAGIC:
                # Streamed upload (wire.py "Streamed uploads"): header
                # now, leaves folded into the running mean as chunks
                # arrive. Only plain/DP rounds aggregate incrementally.
                self._handle_stream_upload(
                    conn, payload, rnd, nonce_hex=nonce_hex, dpid=dpid
                )
                return
            flat, meta = wire.decode(payload, auth_key=self.auth_key)
            # Cohort enforcement needs no separate membership check here:
            # a non-sampled dpid already returned on the sit-out path
            # (its upload frame is never read as a model), and this id
            # binding stops a sampled connection smuggling another id.
            client_id = self._validate_upload_identity(
                meta, nonce_hex=nonce_hex, dpid=dpid
            )
            flat = wire.flatten_params(flat)
            is_delta = bool(meta.get("delta", False))
            if is_delta:
                if self.secure_agg:
                    raise wire.WireError(
                        "sparse-delta upload in secure-aggregation mode"
                    )
                base = self._last_agg
                try:
                    base_round = int(meta.get("base_agg_round", -2))
                except (TypeError, ValueError):
                    raise wire.WireError(
                        f"malformed base_agg_round "
                        f"{meta.get('base_agg_round')!r} in delta upload"
                    ) from None
                if base is None or base_round != self._last_agg_round:
                    raise wire.WireError(
                        f"delta upload against base round "
                        f"{meta.get('base_agg_round')} but server base is "
                        f"{self._last_agg_round if base is not None else 'absent'} "
                        "(restart or stale client) — client will resend dense"
                    )
                if not wire.shapes_compatible(flat, base):
                    raise wire.WireError(
                        "delta upload's tensor set/shapes do not match the base"
                    )
            if bool(meta.get("secure", False)) != self.secure_agg:
                raise wire.WireError(
                    f"secure-aggregation mode mismatch: server "
                    f"secure_agg={self.secure_agg}, upload "
                    f"secure={meta.get('secure', False)}"
                )
            dp_mode, dp_crc = self._validate_dp_meta(meta, is_delta=is_delta)
            if dp_mode:
                if not self.secure_agg:
                    # ENFORCED clipping (not just trusted): a client that
                    # skipped its clip cannot widen the mechanism's
                    # sensitivity for anyone. (Masked uploads can't be
                    # re-clipped; there the guarantee assumes honest
                    # clients clip, as standard for secure-agg DP.)
                    norm = wire.flat_l2_norm(flat)
                    if norm > self.dp_clip * (1.0 + 1e-5):
                        flat, _, _ = wire.clip_flat(flat, self.dp_clip)
                        log.info(
                            f"[SERVER] re-clipped client "
                            f"{meta.get('client_id')}'s delta "
                            f"({norm:.4g} -> {self.dp_clip})"
                        )
            if self.secure_agg:
                if int(meta.get("fp_bits", -1)) != self.fp_bits:
                    raise wire.WireError(
                        f"secure upload fp_bits={meta.get('fp_bits')} != server "
                        f"fp_bits={self.fp_bits}: de-quantization would be wrong"
                    )
                with rnd.lock:
                    mask_set = (
                        rnd.share_set
                        if self.secure_protocol == "double"
                        else rnd.key_set
                    )
                    n_mask = len(mask_set or [])
                if int(meta.get("participants", -1)) != n_mask:
                    # A client masking against a different participant set
                    # would carry uncancelled pair masks — the sum would
                    # silently de-quantize to ring noise.
                    raise wire.WireError(
                        f"secure upload masked for "
                        f"{meta.get('participants')} participants, server "
                        f"distributed the round's mask set to {n_mask}"
                    )
                if int(meta.get("round", -1)) != rnd.round_no:
                    raise wire.WireError(
                        f"secure upload keyed to round {meta.get('round')}, "
                        f"server round is {rnd.round_no}"
                    )
            with rnd.lock:
                if rnd.closed:
                    # Round already snapshotted (deadline hit mid-upload):
                    # close so the client fails fast and retries next round
                    # instead of blocking on a reply that will never come.
                    log.info(
                        f"[SERVER] late upload from client {client_id} after "
                        "round close; dropping connection"
                    )
                    conn.close()
                    return
                if not self._register_tree_meta(
                    rnd, conn, client_id, meta
                ):
                    return
                dup_folded = False
                if client_id in rnd.models or (
                    # A still-in-flight STREAM from this client (intent
                    # registered, trailer not yet processed) is a
                    # duplicate too: a dense retry must not stack a
                    # second intent/leaf set on top of it (the stalled
                    # handler's cleanup would then poison the round or
                    # strip the retry's state out from under it).
                    rnd.stream is not None
                    and client_id in rnd.stream.intents
                ):
                    # Replace the first upload — unless aggregation folds
                    # already consumed it (streaming path): then the
                    # folded original STANDS, and only the connection is
                    # adopted so the (usually retrying, dead-socketed)
                    # client still gets the round's reply.
                    dup_folded = rnd.stream is not None and (
                        not rnd.stream.drop_client(client_id, poison=False)
                    )
                    log.info(
                        f"[SERVER] duplicate upload from client "
                        f"{client_id}; "
                        + (
                            "keeping the already-aggregated original"
                            if dup_folded
                            else "replacing"
                        )
                    )
                    old = rnd.conns.pop(client_id, None)
                    if old is not None and old is not conn:
                        old.close()
                    if dup_folded and client_id not in rnd.models:
                        # The folded original is an in-flight stream that
                        # never reached its trailer (its socket is dead);
                        # mark the client complete from its intent so the
                        # round doesn't barrier on a connection that will
                        # never finish.
                        it = rnd.stream.intents.get(client_id, {})
                        rnd.models[client_id] = {}
                        rnd.deltas[client_id] = bool(it.get("delta", False))
                        if it.get("dp_crc") is not None:
                            rnd.dp_crcs[client_id] = it["dp_crc"]
                        rnd.n_samples[client_id] = float(
                            it.get("n_samples", 1.0)
                        )
                        if set(flat) == set(it.get("keys", ())) and bool(
                            is_delta
                        ) == bool(it.get("delta", False)):
                            # Folds consumed the original's early leaves
                            # and its socket will never deliver the rest;
                            # the retry re-sends the same upload, so its
                            # (validated, re-clipped) leaves complete the
                            # remaining folds to the exact barrier mean.
                            # A diverging retry (key set / mode mismatch)
                            # skips this and fails the ROUND at finalize,
                            # never the server.
                            rnd.stream.add_dense(client_id, flat)
                if not dup_folded:
                    # In a plain/DP round the StreamAgg owns the upload's
                    # arrays (registered below) and frees each leaf as it
                    # folds — keep only the completion sentinel here, so
                    # dense clients reach the O(model + in-flight) peak
                    # too. The secure path has no StreamAgg and
                    # aggregates from rnd.models directly.
                    rnd.models[client_id] = (
                        {} if rnd.stream is not None else flat
                    )
                    rnd.deltas[client_id] = is_delta
                    if dp_crc is not None:
                        rnd.dp_crcs[client_id] = dp_crc
                    rnd.n_samples[client_id] = float(meta.get("n_samples", 1.0))
                if is_delta or bool(meta.get("wants_delta", False)):
                    rnd.wants_delta = True
                if bool(meta.get(wire.STREAM_REPLY_META_KEY, False)):
                    rnd.stream_replies.add(client_id)
                    encs = meta.get(wire.REPLY_DTYPE_META_KEY)
                    if isinstance(encs, (list, tuple)):
                        rnd.reply_dtype_encs[client_id] = tuple(
                            str(e) for e in encs
                        )
                if (
                    self.stream_chunk_bytes > 0
                    and not self.secure_agg
                    and not dup_folded
                ):
                    # Upload arrived dense while streaming was advertised:
                    # a fallback (old peer, topk, a retry, or round 1
                    # before the client saw the advert). The client logs
                    # its one-line reason; this side just counts.
                    with self._totals_lock:
                        self.stream_totals["stream_fallbacks"] += 1
                    self._m_stream_fallbacks.inc()
                rnd.conns[client_id] = conn
                if nonce_hex is not None:
                    rnd.nonces[client_id] = nonce_hex
                if rnd.stream is not None and not dup_folded:
                    # Single-frame uploads join the same incremental fold
                    # as streamed ones (mixed fleets fold in one pass).
                    rnd.stream.register(
                        client_id,
                        keys=tuple(flat),
                        n_samples=float(meta.get("n_samples", 1.0)),
                        delta=is_delta,
                        dp_crc=dp_crc,
                    )
                    rnd.stream.add_dense(client_id, flat)
                    self._try_freeze_stream(rnd)
                done = self._round_done(rnd)
            self._m_uploads.inc()
            log.info(
                f"[SERVER] received model from client {client_id} "
                f"({len(rnd.models)}/{rnd.expected})"
            )
            if done:
                rnd.complete.set()
        except (
            OSError,
            wire.WireError,
            secure.SecureAggError,
            ConnectionError,
            # Defense in depth: meta fields are attacker-controlled, and a
            # parse slipping through as ValueError/TypeError must still
            # close the connection instead of killing the thread and
            # leaving the client blocked until its socket timeout.
            ValueError,
            TypeError,
            # A decode that survives the size caps but still overcommits
            # (many large-claiming tensors in one message) must close the
            # connection, not kill the handler thread.
            MemoryError,
        ) as e:
            log.info(f"[SERVER] upload failed: {e}")
            conn.close()
        finally:
            with self._conn_lock:
                self._open_conns.discard(conn)

    def _round_done(self, rnd: _Round) -> bool:
        """Round completion test (caller holds ``rnd.lock``): every
        expected upload arrived — the full fleet, the secure keyed subset,
        or the sampled cohort — AND, under cohort sampling, every
        non-sampled client has connected to collect the round's reply
        (their bases must track the fleet's). Adopted (re-homed) ids
        never count toward ``expected`` — adoption must not let a
        stranger's upload mask a missing local child — but every adopted
        upload must itself complete before the round does (it widened
        the fold set)."""
        own = len(rnd.models) - len(rnd.adopted & set(rnd.models))
        uploads_done = (
            own >= rnd.expected and rnd.adopted <= set(rnd.models)
        ) or (
            # Secure subset round (dropout before keys): complete as soon
            # as every KEYED participant uploaded — the unkeyed never will.
            self.secure_agg
            and rnd.key_set is not None
            and set(rnd.key_set).issubset(rnd.models)
        )
        if rnd.cohort is None:
            return uploads_done
        skips_done = (
            len(rnd.skip_conns) >= self.num_clients - len(rnd.cohort)
        )
        return uploads_done and skips_done

    def _try_freeze_stream(self, rnd: _Round) -> None:
        """Freeze the round's fold set once every expected client's
        upload intent has arrived (caller holds ``rnd.lock``). Mirrors
        the close-time contributor logic — DP staleness partition
        included — over the SAME inputs, so the frozen set always equals
        the set ``serve_round`` later aggregates over (``_dp_history`` is
        only mutated in the agg phase, after the wait ends). A DP fleet
        whose current-base clients disagree on their crc is left
        unfrozen: nothing folds, and the close-time path raises the
        usual base-mismatch error."""
        st = rnd.stream
        if (
            st is None
            or not st.eager
            or st.fold_ids is not None
            or st.poisoned
        ):
            return
        have = set(st.intents)
        if rnd.cohort is not None:
            if not set(rnd.cohort).issubset(have):
                return
            ids_all = sorted(rnd.cohort)
        else:
            # Adopted (re-homed) intents join the fold set but do not
            # satisfy the expected count — freezing over strangers while
            # a local child is still dialing would fix the weights
            # without it.
            if len(have - rnd.adopted) < rnd.expected:
                return
            ids_all = sorted(have)
        if self.dp_clip > 0.0:
            crcs = {c: st.intents[c].get("dp_crc") for c in ids_all}
            hist = {crc for crc, _ in self._dp_history}
            stale = [c for c in ids_all if crcs[c] in hist]
            current = [c for c in ids_all if c not in stale]
            if not current and stale and len({crcs[c] for c in stale}) == 1:
                # Fleet-wide missed reply: the consensus IS the base
                # (same rule as the close-time resync logic).
                current, stale = stale, []
            if not current or len({crcs[c] for c in current}) != 1:
                return
            ids = current
        else:
            ids = ids_all
        # Same weight rule as serve_round's close-time aggregation —
        # n_samples weights whenever the server is weighted, DP or not.
        weights = (
            [st.intents[c]["n_samples"] for c in ids]
            if self.weighted
            else None
        )
        st.freeze(ids, weights)

    def _validate_upload_identity(
        self, meta, *, nonce_hex: str | None, dpid: int | None
    ) -> int:
        """Freshness + identity binding every upload shape shares. The
        single-frame and streamed wire paths MUST apply identical
        security checks, so both call this one helper — a check added to
        only one path would open a validation gap between the two
        shapes. Returns the bound client id."""
        if self.auth_key is not None and (
            meta.get("role") != "client" or meta.get("nonce") != nonce_hex
        ):
            raise wire.WireError(
                "authenticated upload failed the freshness check "
                "(stale nonce or wrong role) — possible replay"
            )
        client_id = int(meta.get("client_id", -1))
        if dpid is not None and client_id != dpid:
            raise wire.WireError(
                f"upload claims client {client_id} but the DP id "
                f"hello said {dpid}"
            )
        return client_id

    def _validate_dp_meta(self, meta, *, is_delta: bool) -> tuple[bool, int | None]:
        """Central-DP mode agreement + base-crc parse, shared by both
        upload shapes (see _validate_upload_identity). Returns
        ``(dp_mode, dp_crc)``."""
        dp_mode = self.dp_clip > 0.0
        if bool(meta.get("dp", False)) != dp_mode:
            raise wire.WireError(
                f"central-DP mode mismatch: server dp={dp_mode}, "
                f"upload dp={meta.get('dp', False)} — run the client "
                f"with --dp iff the server has --dp-clip"
            )
        dp_crc = None
        if dp_mode:
            if is_delta:
                raise wire.WireError(
                    "sparse-delta upload in central-DP mode"
                )
            try:
                dp_crc = int(meta["dp_base_crc"])
            except (KeyError, TypeError, ValueError):
                raise wire.WireError(
                    "DP upload missing its dp_base_crc"
                ) from None
        return dp_mode, dp_crc

    def _register_tree_meta(
        self, rnd: _Round, conn: socket.socket, client_id: int, meta
    ) -> bool:
        """Survivable-fold-tree meta handling shared by the dense and
        streamed upload paths (caller holds ``rnd.lock``; the two wire
        shapes MUST treat the tree meta identically, same rationale as
        ``_validate_upload_identity``). Records a relay upload's
        contributor list (the round's assignment record) and adopts a
        re-homed NEW id as an extra contributor — widening a frozen-but-
        unfolded fold set, refusing once folds consumed it. Returns
        False when the adoption was refused: the connection is closed
        and the client retries against its next parent or next round."""
        sub = meta.get(wire.SUBTREE_IDS_META_KEY)
        if sub is not None:
            try:
                rnd.subtree_ids[client_id] = [int(c) for c in sub]
            except (TypeError, ValueError):
                raise wire.WireError(
                    f"malformed {wire.SUBTREE_IDS_META_KEY} meta {sub!r} "
                    "(want a list of client ids)"
                ) from None
        # Strategy agreement (strategies/): a relay stamps the strategy
        # id it believes the fleet runs on its upward upload. A mismatch
        # means a split-brain fleet — two aggregation rules folding into
        # one global — so the ROOT refuses the upload loudly instead of
        # silently folding it. Absent stamp = old peer, accepted as-is.
        claimed = meta.get(wire.STRATEGY_META_KEY)
        if claimed is not None:
            name = (
                claimed.get("name") if isinstance(claimed, dict) else claimed
            )
            if str(name) != self._strategy.name:
                raise wire.WireError(
                    f"relay {client_id} fans down strategy {name!r} but "
                    f"this root runs {self._strategy.name!r}; refusing "
                    "the split-brain round (restart the relay with the "
                    "root's --strategy)"
                )
        if not bool(meta.get(wire.REHOME_META_KEY, False)):
            return True
        if self.secure_agg or self.dp_clip > 0.0:
            # Single-aggregator modes never sit behind a fold tree
            # (reply_via refuses them); the marker is ignored and the
            # upload faces those modes' own validation.
            return True
        known = client_id in rnd.models or (
            rnd.stream is not None and client_id in rnd.stream.intents
        )
        if known or client_id in rnd.adopted:
            # An adopted client's retry: the duplicate path's rules
            # apply (supersede pre-fold, keep the folded original).
            return True
        if rnd.stream is not None and not rnd.stream.admit(client_id):
            log.info(
                f"[SERVER] re-homed client {client_id} arrived after "
                "folds began; refusing the adoption (it retries against "
                "its next parent or the next round)"
            )
            conn.close()
            return False
        rnd.adopted.add(client_id)
        log.info(
            f"[SERVER] adopted re-homed client {client_id} into round "
            f"{rnd.round_no} as an extra contributor"
        )
        return True

    def _handle_stream_upload(
        self,
        conn: socket.socket,
        header,
        rnd: _Round,
        *,
        nonce_hex: str | None,
        dpid: int | None,
    ) -> None:
        """Receive one chunk-streamed upload: validate the header's meta
        exactly as a single-frame upload's, register the intent, then
        decode leaves as their bytes complete and hand each to the
        round's StreamAgg — which folds it into the running mean the
        moment every cohort member's copy arrived. The trailer frame is
        the upload-complete handshake; only then does the client count
        toward the round quorum."""
        st = rnd.stream
        if st is None:
            raise wire.WireError(
                "streamed upload refused: this round aggregates masked "
                "uploads (secure-agg), which are single-frame by design"
            )
        tensors, meta, chunk_bytes, payload_nbytes = wire.decode_stream_header(
            header,
            auth_key=self.auth_key,
            max_payload=framing.MAX_FRAME,
            direction="up",
        )
        client_id = self._validate_upload_identity(
            meta, nonce_hex=nonce_hex, dpid=dpid
        )
        if bool(meta.get("delta", False)):
            raise wire.WireError(
                "sparse-delta uploads are single-frame (topk payload "
                "sizes are data-dependent; nothing to stream)"
            )
        if bool(meta.get("secure", False)):
            raise wire.WireError(
                "secure-aggregation mode mismatch: server "
                "secure_agg=False, upload secure=True"
            )
        dp_mode, dp_crc = self._validate_dp_meta(meta, is_delta=False)
        n_samples = float(meta.get("n_samples", 1.0))
        # The upload's wire dtype, from what the header actually encodes
        # (ground truth over any meta claim): the by-dtype /metrics
        # label and the wire-overlap span's wire_dtypes attr.
        encs = {t["enc"] for t in tensors}
        up_dtype = (
            "int8" if "int8c" in encs else "bf16" if "bf16" in encs else "fp32"
        )
        # Duplicate stream after folds consumed the first upload: a
        # COMPLETED original stands and this stream is DRAINED (protocol
        # kept intact, bytes discarded) so the retrying client still gets
        # the round's reply on its fresh connection. A half-folded
        # IN-FLIGHT original (socket died before its trailer) is instead
        # ADOPTED: the retry re-sends the same upload, so its leaves
        # complete the remaining folds — the streamed twin of the
        # dense-retry heal below; a diverging plan is drained (the fold
        # cannot reach a correct mean from it; the round fails at close,
        # never the server).
        discard = False
        adopt = False
        with rnd.lock:
            if rnd.closed:
                conn.close()
                return
            if not self._register_tree_meta(rnd, conn, client_id, meta):
                return
            if client_id in rnd.models or client_id in st.intents:
                folded = not st.drop_client(client_id, poison=False)
                if folded and client_id not in rnd.models:
                    it = st.intents[client_id]
                    adopt = (
                        tuple(t["key"] for t in tensors) == tuple(it["keys"])
                    )
                    if adopt:
                        # The frozen fold weights came from the original
                        # intent; complete the round's bookkeeping with
                        # the SAME values, not the retry's meta.
                        n_samples = float(it["n_samples"])
                        dp_crc = it["dp_crc"]
                discard = folded and not adopt
                log.info(
                    f"[SERVER] duplicate upload from client {client_id}; "
                    + (
                        "draining it and keeping the already-aggregated "
                        "original"
                        if discard
                        else (
                            "adopting it to complete the half-folded "
                            "original"
                            if adopt
                            else "replacing"
                        )
                    )
                )
                old = rnd.conns.pop(client_id, None)
                if old is not None and old is not conn:
                    old.close()
                if not (discard or adopt):
                    rnd.models.pop(client_id, None)
            if not (discard or adopt):
                st.register(
                    client_id,
                    keys=tuple(t["key"] for t in tensors),
                    n_samples=n_samples,
                    delta=False,
                    dp_crc=dp_crc,
                )
            # Register the connection now: a failed round's cleanup must
            # close a mid-stream client too, not leave it blocked.
            rnd.conns[client_id] = conn
            self._try_freeze_stream(rnd)
        self._g_inflight_streams.inc()
        in_flight = True
        nonce = bytes.fromhex(nonce_hex) if nonce_hex else b""
        # Lossy-encoded DP uploads (bf16/int8): the decode can inflate an
        # honestly-clipped norm past the tolerance, and the dense path's
        # answer — silently re-clip — needs the WHOLE upload before any
        # leaf folds (a post-fold re-clip fails the round closed). Hold
        # those leaves and join the fold at trailer time, after the same
        # clip_flat the dense path applies; raw streams fold eagerly
        # (lossless decode — the client-side clip stands).
        dp_hold: dict[str, np.ndarray] | None = (
            {}
            if dp_mode and any(t["enc"] != "raw" for t in tensors)
            else None
        )
        ti = 0
        leaf_buf = bytearray()
        received = 0
        seq = 0
        sumsq = 0.0  # running clip-enforcement norm (header key order =
        # sorted keys = flat_l2_norm's accumulation order, bit-identical)

        def _consume(data) -> None:
            nonlocal ti, leaf_buf, sumsq
            off = 0
            while True:
                while ti < len(tensors) and len(leaf_buf) == int(
                    tensors[ti]["nbytes"]
                ):
                    t = tensors[ti]
                    if not discard:
                        arr = wire.decode_tensor_entry(t, bytes(leaf_buf))
                        if dp_hold is not None:
                            dp_hold[t["key"]] = arr
                        else:
                            if dp_mode:
                                sumsq += float(
                                    np.sum(np.asarray(arr, np.float64) ** 2)
                                )
                            st.add_leaf(client_id, t["key"], arr)
                    leaf_buf = bytearray()
                    ti += 1
                if off >= len(data):
                    return
                if ti >= len(tensors):
                    raise wire.WireError(
                        "stream carries bytes past its last tensor"
                    )
                take = min(
                    int(tensors[ti]["nbytes"]) - len(leaf_buf),
                    len(data) - off,
                )
                leaf_buf += data[off : off + take]
                off += take

        try:
            _consume(b"")  # zero-size leading leaves / empty payloads
            while received < payload_nbytes:
                frame = framing.recv_frame(conn, send_ack=False)
                self._m_bytes_in.inc(float(len(frame)))
                data = wire.decode_stream_chunk(
                    frame,
                    expect_seq=seq,
                    auth_key=self.auth_key,
                    nonce=nonce,
                    direction="up",
                )
                if not data:
                    # A well-formed sender never chunks to zero bytes
                    # (payload_nbytes == 0 skips this loop entirely);
                    # accepting them would let a peer pin this handler
                    # in a no-progress receive loop forever.
                    raise wire.WireError(f"empty stream chunk (seq {seq})")
                seq += 1
                if received + len(data) > payload_nbytes:
                    raise wire.WireError(
                        "stream overruns its declared payload size"
                    )
                received += len(data)
                _consume(data)
            if ti != len(tensors) or leaf_buf:
                raise wire.WireError("stream ended mid-tensor")
            wire.decode_stream_end(
                framing.recv_frame(conn),
                expect_chunks=seq,
                auth_key=self.auth_key,
                nonce=nonce,
                direction="up",
            )
            self._g_inflight_streams.dec()
            in_flight = False
            if not discard and dp_hold is not None:
                # The dense path's exact enforcement (same functions,
                # same accumulation order): re-clip the decoded upload,
                # then join the fold in one piece — add_dense marks the
                # client complete.
                norm = wire.flat_l2_norm(dp_hold)
                if norm > self.dp_clip * (1.0 + 1e-5):
                    dp_hold, _, _ = wire.clip_flat(dp_hold, self.dp_clip)
                    log.info(
                        f"[SERVER] re-clipped client {client_id}'s "
                        f"streamed lossy-encoded delta "
                        f"({norm:.4g} -> {self.dp_clip})"
                    )
                st.add_dense(client_id, dp_hold)
            elif not discard:
                st.mark_complete(client_id)
            if dp_mode and not discard and dp_hold is None:
                # ENFORCED clipping, streamed flavor: the full-upload norm
                # is only known now. While none of this client's leaves
                # have folded, the re-clip is applied bit-identically to
                # the barrier path (wire.clip_flat); once folds consumed
                # unscaled leaves the round fails closed instead — a
                # cheater cannot widen the mechanism's sensitivity either
                # way, and honest clients (which clip client-side) never
                # trigger this.
                norm = float(np.sqrt(sumsq))
                if norm > self.dp_clip * (1.0 + 1e-5):
                    scale = min(1.0, self.dp_clip / max(norm, 1e-12))
                    if not st.scale_client(client_id, scale):
                        raise wire.WireError(
                            f"client {client_id} exceeded its DP clip "
                            f"({norm:.4g} > {self.dp_clip}) after folds "
                            "already consumed its leaves — round fails "
                            "closed"
                        )
                    log.info(
                        f"[SERVER] re-clipped client {client_id}'s "
                        f"streamed delta ({norm:.4g} -> {self.dp_clip})"
                    )
        except BaseException:
            # Mid-stream death: forget the client's unfolded leaves; if
            # folds already consumed any, the StreamAgg is poisoned and
            # the round fails with that reason at close. Skip the drop
            # when a retry already took over this client's slot (the
            # round's registered connection is no longer ours) — the
            # client's state now belongs to that retry, and dropping it
            # here would poison a round the retry just saved.
            if in_flight:
                self._g_inflight_streams.dec()
            if not discard:
                with rnd.lock:
                    if rnd.conns.get(client_id) is conn:
                        st.drop_client(client_id)
                        # A dead ADOPTED stream must also stop gating
                        # round completion (it widened the wait set).
                        rnd.adopted.discard(client_id)
            raise
        with rnd.lock:
            if rnd.closed:
                log.info(
                    f"[SERVER] late upload from client {client_id} after "
                    "round close; dropping connection"
                )
                conn.close()
                return
            if rnd.conns.get(client_id) is not conn:
                # A retry superseded this stream mid-read (duplicate
                # handling adopted a newer connection and owns the
                # client's round state now); finishing here would stamp
                # stale completion info over the retry's.
                log.info(
                    f"[SERVER] stream from client {client_id} superseded "
                    "by a retry; dropping connection"
                )
                conn.close()
                return
            if not discard:
                # Sentinel entry: the StreamAgg holds (or already folded)
                # the actual tensors; rnd.models only tracks WHO completed.
                rnd.models[client_id] = {}
                rnd.deltas[client_id] = False
                rnd.wire_dtypes[client_id] = up_dtype
                if dp_crc is not None:
                    rnd.dp_crcs[client_id] = dp_crc
                rnd.n_samples[client_id] = n_samples
            if bool(meta.get("wants_delta", False)):
                rnd.wants_delta = True
            if bool(meta.get(wire.STREAM_REPLY_META_KEY, False)):
                rnd.stream_replies.add(client_id)
                encs = meta.get(wire.REPLY_DTYPE_META_KEY)
                if isinstance(encs, (list, tuple)):
                    rnd.reply_dtype_encs[client_id] = tuple(
                        str(e) for e in encs
                    )
            rnd.conns[client_id] = conn
            if nonce_hex is not None:
                rnd.nonces[client_id] = nonce_hex
            if not discard:
                # Drained duplicates contributed nothing — the counters
                # (and /metrics' "accepted into a round" totals) only
                # count uploads that did.
                with self._totals_lock:
                    self.stream_totals["stream_uploads"] += 1
            done = self._round_done(rnd)
        if discard:
            log.info(
                f"[SERVER] drained duplicate stream from client "
                f"{client_id} ({payload_nbytes / 1e6:.1f} MB discarded)"
            )
        else:
            self._m_uploads.inc()
            self._m_stream_uploads.inc()
            self._m_uploads_by_dtype(up_dtype).inc()
            log.info(
                f"[SERVER] received streamed model from client {client_id} "
                f"({payload_nbytes / 1e6:.1f} MB in {seq} chunk(s); "
                f"{len(rnd.models)}/{rnd.expected})"
            )
        if done:
            rnd.complete.set()

    def _client_wire_key(self, cid: int) -> bytes | None:
        """The key server<->client control frames (reveal/unmask/shares)
        ride for ``cid``: its per-client identity key when provisioned,
        the group key otherwise (comm/secure.py threat model)."""
        if self.client_keys is not None:
            return self.client_keys[cid]
        return self.auth_key

    def _shares_exchange(
        self,
        conn: socket.socket,
        rnd: _Round,
        hello_id: int,
        key_set: list,
        deadline: float,
    ) -> bool:
        """Double-masking share distribution for one connection: collect
        this dealer's encrypted share blobs, wait (grace-bounded) for the
        keyed fleet's, close U2, relay this holder's shareset. Returns
        False when the connection was dropped (late/conflicting dealer or
        a holder outside U2)."""
        frame = framing.recv_frame(conn)
        dealer, dealt_t, commit, blobs = secure.parse_shares_frame(
            frame,
            session=self._session,
            round_index=rnd.round_no,
            auth_key=(
                self._client_wire_key(hello_id)
                if self.auth_key is not None
                else None
            ),
        )
        if dealer != hello_id:
            raise wire.WireError(
                f"shares frame claims dealer {dealer} on client "
                f"{hello_id}'s connection"
            )
        # Both ends derive t from U1 (key_set) — majority by default, or
        # the operator's explicit threshold set identically on both. A
        # mismatched degree could never reconstruct, so fail it now.
        want_t = (
            self.secure_threshold
            if self.secure_threshold is not None
            else secure.majority_threshold(len(key_set))
        )
        if dealt_t != want_t:
            raise wire.WireError(
                f"client {hello_id} dealt shares at threshold {dealt_t}, "
                f"server expects {want_t} (set secure_threshold "
                "identically on both ends)"
            )
        # U2 must stay >= t: fewer dealers than the Shamir threshold could
        # never unmask, so closing such a round would doom it AFTER all
        # the masking/upload work — refuse at the quorum close instead.
        share_floor = max(2, self.min_clients, want_t)
        want = set(key_set) - {hello_id}
        if set(blobs) != want:
            raise wire.WireError(
                f"shares frame covers holders {sorted(blobs)}, expected "
                f"every other keyed participant {sorted(want)}"
            )
        with rnd.lock:
            if rnd.closed:
                conn.close()
                return False
            prev = rnd.share_blobs.get(hello_id)
            if prev is not None and (
                prev != blobs or rnd.share_commits.get(hello_id) != commit
            ):
                # Like a conflicting DH hello: first deal wins — different
                # shares for the same dealer could never reconstruct.
                log.info(
                    f"[SERVER] conflicting shares from client {hello_id}; "
                    "dropping connection"
                )
                conn.close()
                return False
            if prev is None and rnd.shares_ready.is_set():
                log.info(
                    f"[SERVER] late shares from client {hello_id} after "
                    "shareset distribution; dropping connection"
                )
                conn.close()
                return False
            rnd.share_blobs[hello_id] = blobs
            rnd.share_commits[hello_id] = commit
            if set(key_set).issubset(rnd.share_blobs):
                rnd.share_set = sorted(rnd.share_blobs)
                rnd.shares_ready.set()
        # Wait for the fleet's shares — after the grace window, close U2
        # at the quorum that dealt (dropout-after-keys-before-shares
        # recovery: nobody masked against the missing yet, so the round
        # simply proceeds over the dealers).
        grace_end = time.monotonic() + self.key_grace
        while not rnd.shares_ready.is_set():
            now = time.monotonic()
            if now >= deadline:
                raise wire.WireError(
                    "round deadline passed waiting for the remaining "
                    "participants' secret shares"
                )
            wait_until = grace_end if now < grace_end else deadline
            if rnd.shares_ready.wait(timeout=max(0.0, wait_until - now)):
                break
            with rnd.lock:
                if (
                    not rnd.shares_ready.is_set()
                    and time.monotonic() >= grace_end
                    and len(rnd.share_blobs) >= share_floor
                ):
                    rnd.share_set = sorted(rnd.share_blobs)
                    rnd.shares_ready.set()
                    log.info(
                        f"[SERVER] share grace expired; closing U2 at "
                        f"quorum {rnd.share_set}"
                    )
                    break
        with rnd.lock:
            u2 = list(rnd.share_set or [])
            entries = {
                d: rnd.share_blobs[d][hello_id] for d in u2 if d != hello_id
            }
        if hello_id not in u2:
            log.info(
                f"[SERVER] client {hello_id} missed the share set {u2}; "
                "dropping connection"
            )
            conn.close()
            return False
        framing.send_frame(
            conn,
            secure.build_shareset_frame(
                u2,
                entries,
                session=self._session,
                round_index=rnd.round_no,
                auth_key=(
                    self._client_wire_key(hello_id)
                    if self.auth_key is not None
                    else None
                ),
            ),
        )
        return True

    def _aggregate_double(
        self,
        rnd: _Round,
        models: dict[int, dict],
        conns: dict[int, socket.socket],
    ) -> dict:
        """Double-masking round completion: one unmask round (EVERY round
        — self-masks never cancel on their own), Shamir reconstruction of
        contributors' self-mask seeds and dead participants' key seeds,
        then residue subtraction and de-quantization over contributors.

        Tolerates further dropouts during unmasking: any ``t`` responders
        suffice (t = secure_threshold, default majority of U2)."""
        from . import shamir

        with rnd.lock:
            u2 = list(rnd.share_set or [])
            u1 = list(rnd.key_set or [])
            commits = dict(rnd.share_commits)
            pubs = {
                cid: rnd.pubkeys[cid][: secure.DH_PUB_LEN]
                for cid in rnd.pubkeys
            }
        alive = sorted(models)
        extra = [i for i in alive if i not in u2]
        if extra:
            raise RuntimeError(
                f"secure uploads from clients {extra} outside the share "
                f"set {u2}"
            )
        dead = [i for i in u2 if i not in alive]
        # t derives from U1 — the set the shares were DEALT over (their
        # polynomial degree is fixed there); U2 only selects who masked.
        t = (
            self.secure_threshold
            if self.secure_threshold is not None
            else secure.majority_threshold(len(u1))
        )
        if len(alive) < t:
            # Unmask needs t responders and only contributors hold open
            # connections — fail with the real cause before burning an
            # unmask round that cannot succeed.
            raise RuntimeError(
                f"only {len(alive)} secure uploads survived, below the "
                f"Shamir threshold {t} — the self-masks cannot be "
                "reconstructed (dropouts exceeded the double-masking "
                "tolerance)"
            )
        budget = min(self.timeout, 30.0)
        responses: dict[int, tuple] = {}
        errs: dict[int, Exception] = {}

        def _ask(cid: int) -> None:
            conn = conns[cid]
            try:
                conn.settimeout(budget)
                framing.send_frame(
                    conn,
                    secure.build_unmask_request(
                        alive,
                        dead,
                        session=self._session,
                        round_index=rnd.round_no,
                        auth_key=(
                            self._client_wire_key(cid)
                            if self.auth_key is not None
                            else None
                        ),
                    ),
                )
                responses[cid] = secure.parse_unmask_response(
                    framing.recv_frame(conn),
                    session=self._session,
                    round_index=rnd.round_no,
                    client_id=cid,
                    expect_alive=alive,
                    expect_dead=dead,
                    auth_key=(
                        self._client_wire_key(cid)
                        if self.auth_key is not None
                        else None
                    ),
                )
                conn.settimeout(self.timeout)
            except (
                OSError,
                ConnectionError,
                wire.WireError,
                secure.SecureAggError,
            ) as e:
                errs[cid] = e

        threads = [
            threading.Thread(target=_ask, args=(cid,), daemon=True)
            for cid in alive
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=budget + 5.0)
        if len(responses) < t:
            raise RuntimeError(
                f"unmask round got {len(responses)} responses "
                f"(clients {sorted(responses)}), need the Shamir "
                f"threshold {t}; failures: "
                f"{ {c: str(e) for c, e in errs.items()} }"
            )
        # Reconstruct contributors' self-mask seeds, verified against the
        # dealt commitments (corrupted shares fail loudly, not silently).
        b_seeds: dict[int, bytes] = {}
        for d in alive:
            shares = {
                secure.share_x(h): responses[h][0][d] for h in responses
            }
            seed = shamir.combine(shares)
            if (
                secure.b_seed_commitment(
                    seed, self._session, rnd.round_no, d
                )
                != commits[d]
            ):
                raise RuntimeError(
                    f"reconstructed self-mask seed for client {d} fails "
                    "its commitment — inconsistent shares"
                )
            b_seeds[d] = seed
        # Reconstruct dead participants' key seeds, verified against their
        # registered DH public keys; regenerate the uncancelled pair masks.
        revealed: dict[int, dict[int, bytes]] = {}
        for d in dead:
            shares = {
                secure.share_x(h): responses[h][1][d] for h in responses
            }
            sk_seed = shamir.combine(shares)
            priv, pub = secure.dh_keypair(entropy=sk_seed)
            if pub != pubs.get(d):
                raise RuntimeError(
                    f"reconstructed key seed for dead client {d} does not "
                    "match its registered public key — inconsistent shares"
                )
            for s in alive:
                revealed.setdefault(s, {})[d] = secure.dh_pair_secret(
                    priv, pubs[s]
                )
        summed = secure.sum_masked([models[i] for i in alive])
        self_res = secure.self_mask_sum(
            summed, b_seeds, session=self._session, round_index=rnd.round_no
        )
        out = {k: summed[k] - self_res[k] for k in summed}
        if revealed:
            pair_res = secure.residual_mask_sum(
                summed,
                revealed,
                session=self._session,
                round_index=rnd.round_no,
            )
            out = {k: out[k] - pair_res[k] for k in out}
        log.info(
            f"[SERVER] double-mask unmasked {len(alive)} uploads with "
            f"{len(responses)}/{len(alive)} responders (threshold {t})"
            + (f", {len(dead)} dropout(s) recovered" if dead else "")
        )
        return secure.dequantize_sum(out, len(alive), self.fp_bits)

    def _load_dp_history(self) -> None:
        """Reload the persisted resync window (``dp_history_path``). A
        missing file is a fresh deployment; a corrupt one is logged and
        ignored (the server must come up — clients staler than the
        recoverable window fail their rounds exactly as before)."""
        import json as _json
        import zipfile as _zipfile

        try:
            with np.load(self.dp_history_path, allow_pickle=False) as z:
                index = _json.loads(bytes(z["__index__"].tobytes()).decode())
                self._dp_history = [
                    (
                        int(entry["crc"]),
                        {
                            k: np.asarray(z[f"e{i}_{j}"], np.float32)
                            for j, k in enumerate(entry["keys"])
                        },
                    )
                    for i, entry in enumerate(index)
                ]
            log.info(
                f"[SERVER] reloaded {len(self._dp_history)} retained DP "
                f"round delta(s) from {self.dp_history_path}"
            )
        except FileNotFoundError:
            pass
        except (
            OSError,
            ValueError,
            KeyError,
            # A truncated write that kept the zip magic: np.load raises
            # BadZipFile, which is neither OSError nor ValueError.
            _zipfile.BadZipFile,
        ) as e:
            log.warning(
                f"[SERVER] could not reload DP resync history from "
                f"{self.dp_history_path} ({e}); starting with an empty "
                "window"
            )
            self._dp_history = []

    def _persist_dp_history(self) -> None:
        """Queue the current window for the background writer (see the
        constructor comment): serve_round never blocks on history I/O.
        Coalescing is by design — only the NEWEST snapshot matters, so
        a slow disk skips intermediate windows instead of queueing
        them."""
        if not self.dp_history_path:
            return
        snap = list(self._dp_history)
        with self._dp_persist_lock:
            self._dp_persist_pending = snap
            if (
                self._dp_persist_thread is None
                or not self._dp_persist_thread.is_alive()
            ):
                self._dp_persist_thread = threading.Thread(
                    target=self._dp_persist_loop, daemon=True
                )
                self._dp_persist_thread.start()

    def _dp_persist_loop(self) -> None:
        while True:
            with self._dp_persist_lock:
                snap = self._dp_persist_pending
                self._dp_persist_pending = None
                if snap is None:
                    self._dp_persist_thread = None
                    return
            self._write_dp_history(snap)

    def _write_dp_history(self, history: list[tuple[int, dict]]) -> None:
        """Write one window snapshot atomically (tmp + replace).
        Layout: a JSON index array (per entry: base crc + leaf key
        order) plus positionally-named fp32 arrays — leaf keys can
        contain any character without fighting npz member naming."""
        import json as _json

        index = [
            {"crc": int(crc), "keys": list(d)} for crc, d in history
        ]
        arrays: dict[str, np.ndarray] = {
            "__index__": np.frombuffer(
                _json.dumps(index).encode(), dtype=np.uint8
            )
        }
        for i, (_, d) in enumerate(history):
            for j, k in enumerate(d):
                arrays[f"e{i}_{j}"] = np.asarray(d[k], np.float32)
        tmp = self.dp_history_path + ".tmp"
        try:
            # makedirs INSIDE the guard: an unwritable parent is the
            # same best-effort failure as a full disk — persistence
            # must never fail a round that already released its delta.
            os.makedirs(
                os.path.dirname(os.path.abspath(tmp)) or ".",
                exist_ok=True,
            )
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, self.dp_history_path)
        except OSError as e:
            log.warning(
                f"[SERVER] could not persist DP resync history to "
                f"{self.dp_history_path}: {e}"
            )

    # ------------------------------------------- strategy-state persistence
    def _load_strategy_state(self) -> None:
        """Reload the persisted server state (``strategy_state_path``):
        the last post-strategy global + round index, and the strategy's
        optimizer-state leaves. Missing file = fresh deployment; corrupt
        file or a strategy mismatch = logged and ignored (the server
        must come up; a fresh optimizer memory is merely the pre-PR
        behavior, never wrong)."""
        import json as _json
        import zipfile as _zipfile

        try:
            with np.load(self.strategy_state_path, allow_pickle=False) as z:
                index = _json.loads(bytes(z["__index__"].tobytes()).decode())
                agg = {
                    k: np.asarray(z[f"a{j}"], np.float32)
                    for j, k in enumerate(index["keys"])
                }
                opt_leaves = [
                    np.asarray(z[f"o{j}"])
                    for j in range(int(index.get("n_opt", 0)))
                ]
        except FileNotFoundError:
            return
        except (OSError, ValueError, KeyError, _zipfile.BadZipFile) as e:
            log.warning(
                f"[SERVER] could not reload server strategy state from "
                f"{self.strategy_state_path} ({e}); starting fresh"
            )
            return
        if index.get("strategy") != self._strategy.describe():
            log.warning(
                f"[SERVER] persisted strategy state is for "
                f"{index.get('strategy')}, this server runs "
                f"{self._strategy.describe()}; starting fresh"
            )
            return
        self._last_agg = agg
        self._last_agg_round = int(index["round"])
        # Continue the round numbering monotonically: the restored base
        # is keyed by its round index on both ends of the wire (delta
        # uploads declare base_round; replies advertise agg_round).
        self._round_counter = self._last_agg_round + 1
        restored_opt = False
        if opt_leaves:
            restored_opt = self._strategy.restore_state(opt_leaves, agg)
            if not restored_opt:
                log.warning(
                    "[SERVER] persisted optimizer-state leaves do not "
                    "match this strategy/model; optimizer memory starts "
                    "fresh"
                )
        log.info(
            f"[SERVER] reloaded round {self._last_agg_round} global"
            + (" + optimizer state" if restored_opt else "")
            + f" from {self.strategy_state_path} "
            f"(strategy {self._strategy.name})"
        )

    def _persist_strategy_state(self) -> None:
        """Queue the current global + optimizer state for the background
        writer (the dp-history pattern: latest-snapshot slot, coalescing
        writes — serve_round never blocks on model-sized disk I/O)."""
        if not self.strategy_state_path or self._last_agg is None:
            return
        opt = self._strategy.export_state()
        snap = (
            int(self._last_agg_round),
            {
                k: np.asarray(v, np.float32)
                for k, v in self._last_agg.items()
            },
            self._strategy.describe(),
            [np.asarray(a) for a in (opt or [])],
        )
        with self._strategy_persist_lock:
            self._strategy_persist_pending = snap
            if (
                self._strategy_persist_thread is None
                or not self._strategy_persist_thread.is_alive()
            ):
                self._strategy_persist_thread = threading.Thread(
                    target=self._strategy_persist_loop, daemon=True
                )
                self._strategy_persist_thread.start()

    def _strategy_persist_loop(self) -> None:
        while True:
            with self._strategy_persist_lock:
                snap = self._strategy_persist_pending
                self._strategy_persist_pending = None
                if snap is None:
                    self._strategy_persist_thread = None
                    return
            self._write_strategy_state(snap)

    def _write_strategy_state(self, snap: tuple) -> None:
        """One atomic snapshot (tmp + replace): a JSON index (round,
        strategy describe, agg key order, opt leaf count) plus
        positionally-named arrays — same layout discipline as the DP
        history file, and the same best-effort failure contract."""
        import json as _json

        round_no, agg, described, opt_leaves = snap
        index = {
            "round": int(round_no),
            "strategy": described,
            "keys": list(agg),
            "n_opt": len(opt_leaves),
        }
        arrays: dict[str, np.ndarray] = {
            "__index__": np.frombuffer(
                _json.dumps(index).encode(), dtype=np.uint8
            )
        }
        for j, k in enumerate(agg):
            arrays[f"a{j}"] = agg[k]
        for j, leaf in enumerate(opt_leaves):
            arrays[f"o{j}"] = leaf
        tmp = self.strategy_state_path + ".tmp"
        try:
            os.makedirs(
                os.path.dirname(os.path.abspath(tmp)) or ".",
                exist_ok=True,
            )
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, self.strategy_state_path)
        except OSError as e:
            log.warning(
                f"[SERVER] could not persist server strategy state to "
                f"{self.strategy_state_path}: {e}"
            )

    def _heal_stale_clients(
        self,
        rnd: _Round,
        stale_resync: dict[int, int],
        conns: dict[int, socket.socket],
        nonces: dict[int, str],
    ) -> None:
        """Serve catch-up sequences of RETAINED deltas to stale clients of
        a round that is about to FAIL (quorum miss after their exclusion):
        no new delta exists, but the retained rounds alone land them on
        the fleet's current base so the retried round can succeed. Send
        failures are logged and ignored — the round is failing anyway."""
        for cid, j in stale_resync.items():
            conn = conns.get(cid)
            entries = [d for _, d in self._dp_history[j:]]
            if conn is None or not entries:
                continue
            if not all(
                wire.shapes_compatible(d, entries[0]) for d in entries
            ):
                continue
            try:
                conn.settimeout(min(self.timeout, 30.0))
                framing.send_frame(
                    conn,
                    self._encode_reply(
                        {
                            str(i): wire.unflatten_params(d)
                            for i, d in enumerate(entries)
                        },
                        {
                            "agg_round": rnd.round_no,
                            "trace": rnd.trace,
                            "dp_reply": "resync",
                            "dp_resync_rounds": len(entries),
                        },
                        nonces.get(cid),
                    ),
                )
                log.info(
                    f"[SERVER] client {cid} healed with a catch-up "
                    f"sequence of {len(entries)} retained round delta(s) "
                    "(round itself failed quorum)"
                )
            except (OSError, ConnectionError, wire.WireError) as e:
                log.info(f"[SERVER] catch-up to client {cid} failed: {e}")

    def _round_quorum(self, cohort: set[int] | None) -> int:
        """Upload quorum for one round.

        A sampled round can't demand more uploads than the cohort it drew
        (the draw is data-independent; gating on it would only hurt
        liveness, not privacy) — but the cohort clamp must never lower
        the secure-agg floor below 2: a 1-member cohort's "sum" IS that
        client's raw update, so aggregating it defeats the masking
        outright. Clients enforce their own min_participants floor; the
        server must not construct the degenerate round either:
        ``quorum = max(2, min(min_clients, |cohort|))`` under secure
        aggregation (the constructor already pins min_clients >= 2
        there, so only the cohort clamp can drive the value below 2)."""
        quorum = self.min_clients
        if cohort is not None:
            quorum = min(quorum, len(cohort))
        if self.secure_agg:
            quorum = max(2, quorum)
        return quorum

    def serve_round(
        self, *, deadline: float | None = None, round_index: int | None = None
    ) -> dict | None:
        """Accept uploads until all clients arrive (or deadline), aggregate,
        reply to every contributor. Returns the aggregated flat params.

        ``round_index`` overrides the internal monotonic round counter
        (secure clients key their mask streams off the advertised value)."""
        if self.reply_via is not None and (
            self.dp_clip > 0.0 or self.secure_agg
        ):
            raise ValueError(
                "reply_via (the relay tier's parent-forward hook) is "
                "incompatible with central DP (the subtree partial would "
                "be an un-noised release) and secure aggregation (the "
                "unmask protocol needs the single-aggregator shape)"
            )
        rnd = _Round(
            expected=self.num_clients,
            round_no=self._round_counter if round_index is None else round_index,
        )
        self._round_counter = rnd.round_no + 1
        # close() mid-round sheds THIS round's registered connections
        # promptly (explicit failures, not timeouts).
        self._cur_rnd = rnd
        # Round trace identity (obs/): minted here, stamped into every
        # reply's meta — clients adopt it for their own spans, so the
        # obs timeline can correlate both sides of the wire. Old clients
        # simply ignore the extra meta key (free-form JSON).
        rnd.trace = new_trace_id()
        self.last_trace = (rnd.trace, rnd.round_no)
        self._m_rounds.inc()
        t_round_unix = time.time()
        t_round0 = time.monotonic()
        wait_s = 0.0
        if self.dp_clip > 0.0 and self.dp_participation < 1.0:
            # Per-round Poisson cohort from OS entropy: each registered
            # client independently with probability q — exactly the
            # sampler the subsampled-Gaussian accountant assumes. An
            # empty draw is a legitimate sample: the round becomes a
            # clean no-op (no release, no privacy spent beyond the
            # accountant's bound, which already covers this branch).
            rnd.cohort = {
                i
                for i in range(self.num_clients)
                if self._dp_rng.random() < self.dp_participation
            }
            rnd.expected = len(rnd.cohort)
            log.info(
                f"[SERVER] round {rnd.round_no} Poisson cohort "
                f"(q={self.dp_participation}): {sorted(rnd.cohort)}"
            )
        if not self.secure_agg:
            # Incremental fold state for every plain/DP upload, streamed
            # or single-frame. eager=False (streaming disabled) holds all
            # uploads and folds only at close — the exact barrier shape.
            # Quorum deployments (min_clients < num_clients) also fold at
            # close: an eager fold commits to the full contributor set,
            # so one mid-stream death after folds began would fail a
            # round that the barrier shape completes over the survivors
            # — eager folding must not silently change those failure
            # semantics. Full-participation rounds (the default) lose
            # nothing: a death fails them under either shape.
            rnd.stream = StreamAgg(
                eager=self.stream_chunk_bytes > 0
                and self.min_clients >= self.num_clients,
                base=self._last_agg,
            )
        deadline = time.monotonic() + (self.timeout if deadline is None else deadline)
        futures: list = []
        listener_closed = False
        # Sitting-out liveness bound: once every cohort upload has landed,
        # missing non-sampled clients get a short grace to connect for
        # their reply, not the whole round deadline (one crashed skip
        # client must not stall every sampled round).
        uploads_done_at = None
        skip_grace = min(self.key_grace, 10.0)
        while not rnd.complete.is_set() and time.monotonic() < deadline:
            if rnd.cohort is not None:
                with rnd.lock:
                    up_done = len(rnd.models) >= rnd.expected
                    all_done = self._round_done(rnd)
                if up_done and not all_done:
                    if uploads_done_at is None:
                        uploads_done_at = time.monotonic()
                    elif time.monotonic() - uploads_done_at > skip_grace:
                        log.info(
                            "[SERVER] cohort uploads complete; proceeding "
                            "without the missing sitting-out client(s) "
                            f"after {skip_grace:.0f}s grace"
                        )
                        # Set the event too: the post-loop complete.wait
                        # must not re-stall for the full round deadline.
                        rnd.complete.set()
                        break
                else:
                    uploads_done_at = None
            try:
                # settimeout inside the guard: close() mid-round (a test
                # or operator shutdown) invalidates the fd and must end
                # the loop, not crash the round thread.
                self._sock.settimeout(
                    max(0.05, min(1.0, deadline - time.monotonic()))
                )
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                # Only a real close() (the _stop event) takes the prompt
                # shutdown path below; any other accept() OSError (e.g.
                # EMFILE) keeps the original deadline-bounded wait so an
                # in-flight final upload can still complete the round.
                listener_closed = self._stop.is_set()
                break
            try:
                # Bounded pool, not thread-per-dial: a 256-client cohort
                # (or a retry storm) queues beyond 2*num_clients + 8
                # concurrent handlers instead of spawning without limit.
                futures.append(
                    self._pool.submit(self._handle_upload, conn, rnd, deadline)
                )
            except RuntimeError:
                # close() shut the pool between accept and submit.
                conn.close()
                listener_closed = True
                break
        import concurrent.futures as _cf

        if listener_closed:
            # No new connection can ever arrive: waiting out the full round
            # deadline would just stall shutdown (and leak the round thread
            # past the caller's join window). In-flight handlers may still
            # legitimately complete the round — give them a short bound.
            _cf.wait(futures, timeout=1.0)
        else:
            rnd.complete.wait(timeout=max(0.0, deadline - time.monotonic()))
            _cf.wait(futures, timeout=max(0.1, deadline - time.monotonic()))

        # Everything up to here — accept loop, straggler wait, upload
        # reads — is the round's "wait" phase; aggregation compute and
        # the reply fan-out are timed separately below. Leaf folds that
        # already ran (handler threads, overlapped with the wire) were
        # hidden inside it — that overlap is what the wire-overlap span
        # and comm_overlap_frac report.
        wait_s = time.monotonic() - t_round0
        if rnd.stream is not None:
            rnd.stream.mark_wait_end()

        with rnd.lock:
            rnd.closed = True
            models = dict(rnd.models)
            deltas = dict(rnd.deltas)
            conns = dict(rnd.conns)
            skip_conns = dict(rnd.skip_conns)
            n_samples = dict(rnd.n_samples)
            nonces = dict(rnd.nonces)
            dp_crcs = dict(rnd.dp_crcs)
            adopted = set(rnd.adopted)
            subtree_ids = {k: list(v) for k, v in rnd.subtree_ids.items()}
        # Failure cleanup must cover every registered connection,
        # contributors and sitting-out clients alike.
        all_conns = {**skip_conns, **conns}
        t_agg_unix = time.time()
        t_agg0 = time.monotonic()
        try:
            if rnd.cohort is not None and len(rnd.cohort) == 0:
                # Empty Poisson cohort: a clean no-op round. No model is
                # aggregated and nothing is released; connected clients
                # get a "noop" reply telling them to keep their base.
                log.info(
                    f"[SERVER] round {rnd.round_no}: empty Poisson "
                    "cohort — no-op round, replying noop to "
                    f"{len(skip_conns)} client(s)"
                )
                noop_meta = {
                    "round_clients": [],
                    "agg_round": rnd.round_no,
                    "dp_reply": "noop",
                    "trace": rnd.trace,
                }
                if self.stream_chunk_bytes > 0 and not self.secure_agg:
                    noop_meta[wire.STREAM_META_KEY] = self.stream_chunk_bytes
                    noop_meta[wire.WIRE_DTYPE_META_KEY] = sorted(
                        set(wire.WIRE_DTYPE_ENCS.values())
                    )
                self._reply_all(
                    {
                        cid: self._encode_reply(
                            {}, noop_meta, nonces.get(cid)
                        )
                        for cid in skip_conns
                    },
                    skip_conns,
                )
                self._finish_round(
                    rnd, t_round_unix, t_round0, wait_s,
                    time.monotonic() - t_agg0, 0.0,
                )
                return None
            quorum = self._round_quorum(rnd.cohort)
            if len(models) < quorum:
                raise RuntimeError(
                    f"only {len(models)}/{self.num_clients} clients arrived "
                    f"(min_clients={self.min_clients}"
                    + (
                        f", cohort {sorted(rnd.cohort)}"
                        if rnd.cohort is not None
                        else ""
                    )
                    + ")"
                )
            ids = sorted(models)
            # Survivable fold trees: missing expected contributors (the
            # degraded-round accounting below distinguishes dropped
            # SUBTREES — this server parents relays, some uploads carry
            # contributor records — from locally shed leaf stragglers),
            # the round's ACTUAL assignment, and the double-count
            # tripwire: one client id claimed by two subtree partials
            # means a re-homed upload was also folded by a surviving old
            # parent — no renormalization can fix that mean, so the
            # round fails loudly and the fleet retries.
            missing_n = max(
                0, rnd.expected - (len(models) - len(adopted & set(models)))
            )
            listed = [c for i in ids for c in subtree_ids.get(i, [])]
            if len(listed) != len(set(listed)):
                dup_claims = sorted(
                    {c for c in listed if listed.count(c) > 1}
                )
                raise RuntimeError(
                    f"clients {dup_claims} appear in more than one "
                    "subtree's contributor record — a re-homed upload "
                    "was double-counted; failing the round"
                )
            dp_mode = self.dp_clip > 0.0
            stale_resync: dict[int, int] = {}  # client id -> history index
            resync_payloads: dict[int, tuple[dict, int]] = {}
            if dp_mode:
                if not self.secure_agg and self.compression == "none":
                    # Resyncable stale clients: base crc matches a retained
                    # round (latest entry wins on the impossible collision).
                    # Lossless replies only: under bf16/int8 the bases the
                    # fleet adopted are the DECODED (lossy) deltas, which
                    # the fp32 retention cannot reproduce bit-exactly — a
                    # "resynced" base would miss the crc agreement anyway.
                    hist_index = {
                        crc: j for j, (crc, _) in enumerate(self._dp_history)
                    }
                    stale_resync = {
                        i: hist_index[dp_crcs[i]]
                        for i in ids
                        if dp_crcs[i] in hist_index
                    }
                current = [i for i in ids if i not in stale_resync]
                if not current and stale_resync:
                    group_crcs = {dp_crcs[i] for i in stale_resync}
                    if len(group_crcs) == 1:
                        # EVERY upload agrees on a RETAINED base: the
                        # previously released delta(s) past it were never
                        # adopted by anyone (fleet-wide reply loss), so
                        # the consensus IS the fleet base. Proceed
                        # normally from it — exactly what the pre-resync
                        # server did — instead of misclassifying the
                        # whole fleet as stale; the orphaned history
                        # entries are shadowed by this round's re-release
                        # (hist_index keeps the latest entry per crc).
                        log.info(
                            "[SERVER] all uploads share a retained base "
                            "crc (fleet-wide missed reply); treating the "
                            "consensus as current"
                        )
                        current = sorted(stale_resync)
                        stale_resync = {}
                crc_set = {dp_crcs[i] for i in current}
                if not current or len(crc_set) != 1:
                    # A stale client outside the resync window (or a
                    # different init) would shift the mean by an unbounded
                    # base gap.
                    raise RuntimeError(
                        "DP round base mismatch: clients disagree on the "
                        f"round base (crcs per client: "
                        f"{ {i: f'{dp_crcs[i]:#010x}' for i in ids} }) — "
                        "every client must start the round from the same "
                        "adopted aggregate / shared init (stale clients "
                        f"resync only within the last {self.dp_resync_rounds} "
                        "retained round(s) of this server process)"
                    )
                if stale_resync:
                    if len(current) < quorum:
                        # The round cannot proceed — but the stale clients
                        # must STILL be healed now, with the retained
                        # rounds alone (this round produced no delta).
                        # Under the default quorum (min_clients ==
                        # num_clients) this is the ONLY path that ever
                        # engages: excluding the stale upload always drops
                        # the round below quorum, so without healing here
                        # the fleet would wedge forever — the exact
                        # deadlock the resync exists to close. Healed
                        # clients rejoin current next round, which then
                        # meets quorum.
                        self._heal_stale_clients(
                            rnd, stale_resync, all_conns, nonces
                        )
                        raise RuntimeError(
                            f"only {len(current)} current-base clients "
                            f"uploaded (stale: {sorted(stale_resync)}, "
                            "served catch-up sequences), below the quorum "
                            f"of {quorum} — retrying clients complete the "
                            "next round from the common base"
                        )
                    log.info(
                        f"[SERVER] clients {sorted(stale_resync)} declared "
                        "stale round bases; excluding their uploads and "
                        "serving composed catch-up deltas "
                        f"(contributors: {current})"
                    )
                    ids = current
            if self.secure_agg and self.secure_protocol == "double":
                agg = self._aggregate_double(rnd, models, conns)
                log.info(
                    f"[SERVER] secure-aggregated {len(ids)} masked models "
                    "(double-masking; server never saw raw weights)"
                )
            elif self.secure_agg:
                key_set = list(rnd.key_set or [])
                extra = [i for i in ids if i not in key_set]
                if extra:
                    # Can't happen via the protocol (uploads require the
                    # keys frame) but a forged upload must not poison the
                    # ring sum.
                    raise RuntimeError(
                        f"secure uploads from clients {extra} outside the "
                        f"key set {key_set}"
                    )
                dead = [i for i in key_set if i not in models]
                if dead:
                    # Reveal round (secure.py "dropout recovery"):
                    # survivors disclose their pair secrets with the dead,
                    # and the uncancelled mask halves are subtracted from
                    # the ring sum before de-quantizing over survivors.
                    log.info(
                        f"[SERVER] secure round lost clients {dead}; "
                        f"asking {ids} to reveal their pair secrets"
                    )
                    # Reveal frames are tagged under each survivor's OWN
                    # identity key when per-client keys are provisioned
                    # (group key otherwise, _client_wire_key): an in-group
                    # adversary holding only the group key can then
                    # neither forge a REVEAL_REQ naming a victim that
                    # actually uploaded nor spoof a survivor's response
                    # (secure.py threat model).
                    # Parallel per-survivor exchange with a bounded budget
                    # (same rationale as the reply fan-out below): a
                    # stalled survivor must neither block the others'
                    # requests nor extend the round by a full socket
                    # timeout. Healthy survivors are already blocked in
                    # recv and answer in milliseconds.
                    reveal_budget = min(self.timeout, 30.0)
                    revealed: dict[int, dict] = {}
                    reveal_errs: dict[int, Exception] = {}

                    def _reveal_from(cid: int) -> None:
                        conn = conns[cid]
                        try:
                            conn.settimeout(reveal_budget)
                            framing.send_frame(
                                conn,
                                secure.build_reveal_request(
                                    dead,
                                    session=self._session,
                                    round_index=rnd.round_no,
                                    auth_key=self._client_wire_key(cid),
                                ),
                            )
                            revealed[cid] = secure.parse_reveal_response(
                                framing.recv_frame(conn),
                                session=self._session,
                                round_index=rnd.round_no,
                                client_id=cid,
                                expect_dead=dead,
                                auth_key=self._client_wire_key(cid),
                            )
                            conn.settimeout(self.timeout)
                        except (
                            OSError,
                            ConnectionError,
                            wire.WireError,
                            secure.SecureAggError,
                        ) as e:
                            reveal_errs[cid] = e

                    rthreads = [
                        threading.Thread(
                            target=_reveal_from, args=(cid,), daemon=True
                        )
                        for cid in ids
                    ]
                    for t in rthreads:
                        t.start()
                    for t in rthreads:
                        t.join(timeout=reveal_budget + 5.0)
                    if reveal_errs or set(revealed) != set(ids):
                        # A dropout DURING the reveal is unrecoverable
                        # without Shamir shares (secure.py threat model).
                        raise RuntimeError(
                            f"reveal round failed for clients "
                            f"{sorted(set(ids) - set(revealed))}: "
                            f"{ {c: str(e) for c, e in reveal_errs.items()} }"
                        )
                    summed = secure.sum_masked([models[i] for i in ids])
                    residue = secure.residual_mask_sum(
                        summed,
                        revealed,
                        session=self._session,
                        round_index=rnd.round_no,
                    )
                    agg = secure.dequantize_sum(
                        {k: summed[k] - residue[k] for k in summed},
                        len(ids),
                        self.fp_bits,
                    )
                else:
                    agg = secure.aggregate_masked(
                        [models[i] for i in ids], self.fp_bits
                    )
                log.info(
                    f"[SERVER] secure-aggregated {len(ids)} masked models "
                    + (f"after revealing {len(dead)} dropout(s) " if dead else "")
                    + "(server never saw raw weights)"
                )
            else:
                weights = [n_samples[i] for i in ids] if self.weighted else None
                # Incremental fold (comm/stream_agg.py): leaves already
                # folded during the wait phase — overlapped with the wire
                # — are reused; whatever remains folds here. Sparse-delta
                # uploads become absolute models against the last
                # aggregate at fold time (validated at upload time), so
                # dense, sparse, and streamed clients mix freely in one
                # round. The result is BIT-EXACT with the barrier
                # aggregate_flat (same fp32 ops, same ascending-id order
                # per leaf — pinned by the parity tests).
                try:
                    agg = rnd.stream.finalize(ids, weights)
                except wire.WireError as e:
                    # Incomplete fold input (a superseded stream whose
                    # retry diverged, a key-set mismatch): serve()'s
                    # contract is that this fails the ROUND, not the
                    # server — WireError is a ValueError and would
                    # otherwise escape serve()'s RuntimeError guard.
                    raise RuntimeError(
                        f"streamed aggregation failed: {e}"
                    ) from e
                n_sparse = sum(bool(deltas.get(i)) for i in ids)
                s_stats = rnd.stream.stats()
                log.info(
                    f"[SERVER] aggregated {len(ids)} models (clients {ids}"
                    + (f", {n_sparse} sparse-delta" if n_sparse else "")
                    + (
                        f"; {s_stats['overlap_frac']:.0%} of fold input "
                        "consumed during the wire phase"
                        if s_stats["early_bytes"]
                        else ""
                    )
                    + ")"
                )
            if self.reply_via is not None:
                # Hierarchical fold tree (comm/relay.py): hand the
                # subtree's partial weighted mean to the parent exchange;
                # what comes back — the ROOT's aggregate — is what this
                # subtree's clients receive, adopt, and (sparse tier)
                # difference their next deltas against. A parent failure
                # raises here and the BaseException cleanup below fails
                # the round for the whole subtree (clients retry).
                agg = {
                    k: np.asarray(v, np.float32)
                    for k, v in self.reply_via(
                        agg,
                        {
                            "ids": list(ids),
                            "n_samples": {i: n_samples[i] for i in ids},
                            "round": rnd.round_no,
                            "trace": rnd.trace,
                        },
                    ).items()
                }
            if dp_mode:
                # agg is the uniform mean of CLIPPED DELTAS (plain mode:
                # aggregate_flat over re-clipped uploads; secure mode: the
                # de-quantized masked sum of client-clipped deltas). Add
                # the Gaussian mechanism's noise and reply with the noised
                # mean delta — no absolute weights ever exist server-side,
                # and the sparse-tier base bookkeeping does not apply.
                n = len(ids)
                sigma = self.dp_noise_multiplier * self.dp_clip / n
                if sigma > 0.0:
                    # fp32 draws: Generator.normal would materialize a
                    # float64 model-sized array per tensor first.
                    agg = {
                        k: np.asarray(v, np.float32)
                        + self._dp_rng.standard_normal(
                            np.shape(v), dtype=np.float32
                        )
                        * np.float32(sigma)
                        for k, v in agg.items()
                    }
                log.info(
                    f"[SERVER] central DP: mean of {n} clipped deltas "
                    f"(clip {self.dp_clip}) + Gaussian noise "
                    f"std {sigma:.3g}/coordinate"
                )
                reply_meta = {
                    "agg_round": rnd.round_no,
                    "trace": rnd.trace,
                    "dp_reply": "delta",
                    # The base this delta applies to. A receiver whose own
                    # base differs (a STALE client sitting a sampled round
                    # out) must NOT apply it — compounding a foreign delta
                    # onto a stale base would create a base the retained
                    # history never saw, making the client permanently
                    # unresyncable. It keeps its base instead and resyncs
                    # on its next contributing round.
                    "dp_base_crc": next(iter(crc_set)),
                }
                if rnd.cohort is None:
                    # Under cohort sampling the sampled set stays OUT of
                    # the replies: privacy amplification by subsampling
                    # assumes the adversary cannot condition on who was
                    # sampled. With full participation the "cohort" is
                    # public knowledge anyway.
                    reply_meta["round_clients"] = ids
                if not self.secure_agg and self.compression == "none":
                    # Retain this round's released delta for the resync
                    # window (post-noise: a DP output, so retaining and
                    # re-releasing compositions of it is free
                    # post-processing), keyed by the base crc the round's
                    # current uploads agreed on. An EXACTLY-ZERO delta
                    # (noiseless round, all clients at their base) is NOT
                    # retained: the new base equals the old one, so the
                    # retained crc would collide with every current
                    # client's next declaration and misclassify the whole
                    # fleet as stale — and a zero delta contributes
                    # nothing to any composition anyway.
                    if any(np.any(np.asarray(v)) for v in agg.values()):
                        self._dp_history.append(
                            (
                                next(iter(crc_set)),
                                {
                                    k: np.asarray(v, np.float32)
                                    for k, v in agg.items()
                                },
                            )
                        )
                    for cid, j in stale_resync.items():
                        # Catch-up: every retained delta from the client's
                        # base forward — the tail INCLUDES the entry just
                        # appended. Shipped as the SEQUENCE (keys "0","1",
                        # ...), never pre-summed: the client replays each
                        # round's fp32 addition in order, which is the
                        # only arithmetic that reproduces the fleet's base
                        # BIT-EXACTLY (fp32 addition is not associative —
                        # a server-side sum would land ulps away and fail
                        # the next round's crc agreement for everyone).
                        entries = [d for _, d in self._dp_history[j:]]
                        if not all(
                            wire.shapes_compatible(d, agg) for d in entries
                        ):
                            log.info(
                                f"[SERVER] client {cid} cannot resync: "
                                "retained deltas changed shape mid-window"
                            )
                            continue
                        resync_payloads[cid] = (
                            {
                                str(i): wire.unflatten_params(d)
                                for i, d in enumerate(entries)
                            },
                            len(entries),
                        )
                    # Trim AFTER composing: stale_resync indices address
                    # the pre-trim list (append only extends the tail).
                    if len(self._dp_history) > self.dp_resync_rounds:
                        del self._dp_history[
                            : len(self._dp_history) - self.dp_resync_rounds
                        ]
                    self._persist_dp_history()
            else:
                if self.reply_via is None:
                    # Aggregation strategy (strategies/): a pure transform
                    # of (previous global, folded mean) — the fold above
                    # stays bit-exact, fedavg's transform is the identity,
                    # and relays never transform (the root already did;
                    # a subtree partial is not a global). The per-client
                    # fold stats ride along for telemetry.
                    agg = self._strategy.apply(
                        self._last_agg,
                        agg,
                        round_no=rnd.round_no,
                        client_stats=(
                            rnd.stream.client_stats()
                            if rnd.stream is not None
                            else None
                        ),
                    )
                    self._m_strategy_rounds(self._strategy.name).inc()
                # The new base for next round's sparse deltas, advertised
                # in every reply. Secure mode tracks it too (harmless), but
                # delta uploads are refused there (mask streams carry no
                # sparsity). Under a non-fedavg strategy the base is the
                # POST-transform global — exactly what clients adopt, so
                # next round's deltas difference against the right tree.
                self._last_agg = agg
                self._last_agg_round = rnd.round_no
                # Persist the post-strategy global + optimizer state so
                # a restarted server resumes instead of re-adopting the
                # mean (no-op without strategy_state_path; background
                # writer keeps the fan-out off the disk's latency).
                self._persist_strategy_state()
                # agg_crc: the base-agreement contract. Clients only adopt
                # the decoded reply as their next delta base when it hashes
                # to the server's exact fp32 aggregate — under a lossy
                # reply compression (bf16/int8) it never will, and they
                # stay dense. Lazily computed: it is a full fp32 pass over
                # the model, paid only when a delta-capable client showed
                # up this round (and never in secure mode, where delta
                # uploads are refused).
                reply_meta = {
                    "round_clients": ids,
                    "agg_round": rnd.round_no,
                    "trace": rnd.trace,
                }
                if self.reply_via is None:
                    # Strategy stamp (wire.STRATEGY_META_KEY): which
                    # strategy produced THIS global, doubling as the
                    # round-START advert for the next round — a fedprox
                    # stamp carries the mu clients should anchor their
                    # local loss with. Plain meta: old clients ignore it.
                    reply_meta[wire.STRATEGY_META_KEY] = (
                        self._strategy.describe()
                    )
                if rnd.wants_delta and not self.secure_agg:
                    reply_meta["agg_crc"] = wire.flat_crc32(agg)
            if self.stream_chunk_bytes > 0 and not self.secure_agg:
                # Streamed-upload capability advert (same pattern as the
                # trace field): capable clients chunk-stream their NEXT
                # upload; old peers ignore the extra meta key.
                reply_meta[wire.STREAM_META_KEY] = self.stream_chunk_bytes
                # Wire-dtype advert: the stream leaf encodings this
                # server decodes. A --wire-dtype client quantizes its
                # NEXT streamed upload only after seeing its encoding
                # here (old servers never advertise -> clients stay
                # fp32; old clients ignore the key — interop unchanged
                # both ways).
                reply_meta[wire.WIRE_DTYPE_META_KEY] = sorted(
                    set(wire.WIRE_DTYPE_ENCS.values())
                )
            # Sitting-out clients (cohort sampling) receive the identical
            # reply: the aggregate is the round's public output and their
            # bases must track the fleet's.
            reply_targets = ids + sorted(skip_conns)
            # Streamed replies (wire.py "Streamed replies"): contributors
            # that advertised the capability get STRH/STRC/STRT frames.
            # The payload chunks are built ONCE and shared across the
            # fan-out; resync sequences and sit-out replies stay dense
            # (rare / not advertised).
            stream_ids: list[int] = []
            stream_plan = None
            quant_plan = None
            quant_ids: set[int] = set()
            if self.stream_chunk_bytes > 0 and not self.secure_agg:
                stream_ids = [
                    cid for cid in ids if cid in rnd.stream_replies
                ]
            if stream_ids:
                # Quantized replies (--reply-dtype): only clients whose
                # upload meta advertised the configured encoding get the
                # lossy plan; the rest share the base (self.compression)
                # plan. At most two payload encodes per round, each
                # shared across its cohort.
                quant_enc = wire.WIRE_DTYPE_ENCS[self.reply_dtype]
                if self.reply_dtype != "fp32":
                    quant_ids = {
                        cid
                        for cid in stream_ids
                        if quant_enc in rnd.reply_dtype_encs.get(cid, ())
                    }
                if quant_ids:
                    quant_plan = self._plan_reply_stream(
                        agg, compression=quant_enc
                    )
                if any(cid not in quant_ids for cid in stream_ids):
                    stream_plan = self._plan_reply_stream(agg)
            dense_targets = [c for c in reply_targets if c not in stream_ids]
            if not dense_targets:
                # All-streaming fleet: no dense blob to build — skipping
                # the encode saves a model-sized copy + CRC pass per
                # round in exactly the shape this PR optimizes for.
                replies = {}
            elif self.auth_key is None:
                # One shared reply blob, referenced by every client.
                shared = wire.encode(
                    agg, meta=reply_meta, compression=self.compression
                )
                replies = {cid: shared for cid in dense_targets}
            else:
                # Auth mode: each reply echoes that client's challenge nonce
                # with role=server, so it can't be replayed or reflected.
                # (Per-client encode costs one extra payload memcpy each.)
                replies = {
                    cid: self._encode_reply(agg, reply_meta, nonces.get(cid))
                    for cid in dense_targets
                }
            stream_jobs = {
                cid: (
                    self._encode_stream_reply_header(
                        quant_plan if cid in quant_ids else stream_plan,
                        reply_meta,
                        nonces.get(cid),
                    ),
                    bytes.fromhex(nonces[cid]) if cid in nonces else b"",
                    quant_plan if cid in quant_ids else stream_plan,
                )
                for cid in stream_ids
            }
            # Stale-but-resyncable DP clients: the reply is the catch-up
            # SEQUENCE of retained round deltas (applied in order to
            # their base) — their excluded uploads already cost them the
            # round's contribution; this puts them back on the fleet's
            # exact base for the next one.
            for cid, (sequence, n_rounds) in resync_payloads.items():
                replies[cid] = self._encode_reply(
                    sequence,
                    {
                        **reply_meta,
                        "dp_reply": "resync",
                        "dp_resync_rounds": n_rounds,
                    },
                    nonces.get(cid),
                )
                log.info(
                    f"[SERVER] client {cid} resynced with a catch-up "
                    f"sequence of {n_rounds} retained round delta(s)"
                )
            for cid in stale_resync:
                if cid not in resync_payloads:
                    # Unresyncable after all (shape drift mid-window):
                    # close now so the client fails fast instead of
                    # blocking on a reply that will never come.
                    c = all_conns.get(cid)
                    if c is not None:
                        c.close()
        except BaseException:
            # A failed round must not leave clients blocked in recv_frame
            # until their timeouts — drop every connection so they fail fast.
            for c in all_conns.values():
                c.close()
            self._finish_round(
                rnd, t_round_unix, t_round0, wait_s,
                time.monotonic() - t_agg0, 0.0, failed=True,
            )
            raise
        agg_s = time.monotonic() - t_agg0
        # The round's ACTUAL aggregation assignment (fold order at this
        # tier, each relay contributor expanded to the client ids its
        # partial folded) — what the crc contract replays over.
        self.last_assignment = {
            "round": rnd.round_no,
            "groups": [
                list(subtree_ids[i]) if i in subtree_ids else int(i)
                for i in ids
            ],
        }
        degraded = (
            missing_n > 0 and rnd.cohort is None and not self.secure_agg
        )
        if degraded:
            # Quorum semantics, one level up: the round COMPLETED over
            # the survivors. At a parent of relays the missing children
            # are whole subtrees — stamp the event, count it, and
            # preserve the evidence (subtree-failure flight bundle); at
            # a leaf tier they are stragglers shed at this aggregator's
            # local deadline. Known coarseness: a plain round's expected
            # count carries no per-child identity, so a MIXED tier (some
            # children relays, some direct leaves) attributes every
            # missing child to the dominant shape — subtrees whenever
            # any upload carried a contributor record. Keep tiers
            # homogeneous (the documented topology) for exact counts.
            tree_key = (
                "subtree_failures" if subtree_ids else "stragglers_shed"
            )
            with self._totals_lock:
                self.tree_totals[tree_key] += missing_n
                self.tree_totals["degraded_rounds"] += 1
            if subtree_ids:
                self._m_subtree_failures.inc(float(missing_n))
                log.warning(
                    f"[SERVER] round {rnd.round_no} completed DEGRADED: "
                    f"{missing_n} expected subtree(s) never uploaded "
                    f"within the deadline; folded the surviving "
                    f"contributors {ids} (mean renormalized over their "
                    "mass)"
                )
                recorder = obs_flight.get_global_recorder()
                if recorder is not None:
                    try:
                        recorder.maybe_dump(
                            "subtree-failure",
                            extra={
                                "round": rnd.round_no,
                                "trace": rnd.trace,
                                "expected": rnd.expected,
                                "missing_subtrees": missing_n,
                                "survivors": [int(i) for i in ids],
                            },
                        )
                    except OSError as e:
                        log.warning(
                            "[SERVER] subtree-failure postmortem dump "
                            f"failed (non-fatal): {e}"
                        )
            else:
                self._m_stragglers_shed.inc(float(missing_n))
                log.info(
                    f"[SERVER] round {rnd.round_no}: shed {missing_n} "
                    "straggler(s) at the local deadline; proceeding "
                    f"over {ids}"
                )
        if self.tracer is not None:
            extra = {}
            if degraded and subtree_ids:
                extra["missing_subtrees"] = missing_n
            elif degraded:
                extra["stragglers_shed"] = missing_n
            if adopted:
                extra["adopted"] = sorted(int(i) for i in adopted)
            if subtree_ids:
                extra["assignment"] = self.last_assignment["groups"]
            if self.reply_via is None:
                # Which strategy produced this round's global (+ its
                # hyperparams): the postmortem flight bundle / obs watch
                # answer to "what aggregation rule was live here".
                extra["strategy"] = self._strategy.name
                s_params = self._strategy.params()
                if s_params:
                    extra["strategy_params"] = {
                        k: s_params[k] for k in sorted(s_params)
                    }
            self.tracer.record(
                "agg",
                t_start=t_agg_unix,
                dur_s=agg_s,
                trace=rnd.trace,
                round=rnd.round_no,
                clients=len(models),
                # The round's CONTRIBUTOR set (post staleness exclusion):
                # the obs timeline's drop attribution — who was actually
                # aggregated vs who uploaded-but-was-excluded vs who
                # never arrived (faults/scenario.py consumes this).
                contributors=[int(i) for i in ids],
                **extra,
            )
        t_rep_unix = time.time()
        t_rep0 = time.monotonic()
        self._reply_all(replies, all_conns, stream_jobs)
        reply_s = time.monotonic() - t_rep0
        out_bytes = float(sum(len(b) for b in replies.values()))
        if stream_jobs:
            out_bytes += sum(
                len(hdr) + plan["payload_nbytes"]
                for hdr, _, plan in stream_jobs.values()
            )
            with self._totals_lock:
                self.stream_totals["stream_replies"] += len(stream_jobs)
            self._m_stream_replies.inc(float(len(stream_jobs)))
        self._m_bytes_out.inc(out_bytes)
        if self.tracer is not None:
            self.tracer.record(
                "wire-reply",
                t_start=t_rep_unix,
                dur_s=reply_s,
                trace=rnd.trace,
                round=rnd.round_no,
                replies=len(replies),
            )
        self._finish_round(
            rnd, t_round_unix, t_round0, wait_s, agg_s, reply_s
        )
        return agg

    def _finish_round(
        self,
        rnd: _Round,
        t_unix: float,
        t0: float,
        wait_s: float,
        agg_s: float,
        reply_s: float,
        *,
        failed: bool = False,
    ) -> None:
        """Close a round's observability: accumulate the wait/agg/reply
        phase seconds (process totals AND /metrics counters), fold the
        round's streaming stats into the cross-round totals (plus the
        ``wire-overlap`` span when any fold overlapped the wire), and
        emit the round span."""
        round_wall = time.monotonic() - t0
        for name, dur in (("wait", wait_s), ("agg", agg_s), ("reply", reply_s)):
            self.phase_seconds[name] += dur
            self._m_phase[name].inc(max(dur, 0.0))
        self._h_round.observe(max(round_wall, 0.0))
        # Device-memory watermark at the round's aggregation boundary
        # (obs/profile.py): meaningful on accelerator-backed server
        # hosts, a graceful no-op on the host-only numpy tier.
        note_memory("post-aggregate")
        if failed:
            self._m_round_failures.inc()
        if rnd.stream is not None:
            s = rnd.stream.stats()
            with self._totals_lock:
                tot = self.stream_totals
                tot["early_bytes"] += s["early_bytes"]
                tot["late_bytes"] += s["late_bytes"]
                tot["early_s"] += s["early_s"]
                tot["late_s"] += s["late_s"]
                tot["peak_agg_bytes"] = max(
                    tot["peak_agg_bytes"], s["peak_bytes"]
                )
                # Last ROUND's peak separately: a mixed campaign's first
                # (dense, pre-advert) round peaks at O(clients x model)
                # and would mask the streamed rounds' O(model +
                # in-flight) in the cross-round max.
                tot["last_round_peak_bytes"] = s["peak_bytes"]
                # Compiled-fold telemetry (ops/fold.py): which engine
                # folded and at what throughput — the bench's
                # fold_throughput_gbps headline source.
                tot["fold_engine"] = s["fold_engine"]
                tot["last_fold_throughput_gbps"] = s[
                    "fold_throughput_gbps"
                ]
            self._g_peak_agg.set(float(s["peak_bytes"]))
            if s["fold_s"] > 0.0:
                self._g_fold_throughput.set(
                    float(s["fold_throughput_gbps"])
                )
            if self.tracer is not None and s["early_s"] > 0.0:
                # Overlapped-vs-exposed wire attribution: how much fold
                # work ran DURING the wait phase (hidden behind other
                # clients' transfers) — the obs timeline's overlap row.
                wire_dtypes = sorted(set(rnd.wire_dtypes.values()))
                self.tracer.record(
                    "wire-overlap",
                    t_start=s["first_fold_unix"] or t_unix,
                    dur_s=s["early_s"],
                    trace=rnd.trace,
                    round=rnd.round_no,
                    folded_bytes=s["early_bytes"],
                    overlap_frac=round(s["overlap_frac"], 4),
                    peak_agg_bytes=s["peak_bytes"],
                    fold_engine=s["fold_engine"],
                    fold_throughput_gbps=round(
                        s["fold_throughput_gbps"], 3
                    ),
                    wire_dtypes=wire_dtypes or None,
                )
        if self.tracer is not None:
            self.tracer.record(
                "round",
                t_start=t_unix,
                dur_s=round_wall,
                trace=rnd.trace,
                round=rnd.round_no,
                failed=True if failed else None,
            )
        if failed:
            # Flight recorder (obs/flight.py): a failed round is exactly
            # the moment whose surrounding spans + metric state an
            # operator wants preserved. After the round span above so
            # the bundle's ring includes the failure itself. Rate-
            # limited; never fatal to the round path.
            recorder = obs_flight.get_global_recorder()
            if recorder is not None:
                try:
                    recorder.maybe_dump(
                        "round-failure",
                        extra={
                            "round": rnd.round_no,
                            "trace": rnd.trace,
                            "expected": rnd.expected,
                            "wall_s": round(round_wall, 3),
                        },
                    )
                except OSError as e:
                    log.warning(
                        f"[SERVER] postmortem dump failed (non-fatal): {e}"
                    )

    def _encode_reply(self, agg: dict, meta: dict, nonce: str | None) -> bytes:
        """One reply blob, auth-aware (echoes the client's nonce with
        role=server in auth mode)."""
        if self.auth_key is None:
            return wire.encode(agg, meta=meta, compression=self.compression)
        return wire.encode(
            agg,
            meta={**meta, "role": "server", "nonce": nonce},
            compression=self.compression,
            auth_key=self.auth_key,
        )

    def _plan_reply_stream(self, agg: dict, compression: str | None = None) -> dict:
        """Build the round's shared streamed-reply payload ONCE: the
        tensor plan plus the chunk payload list every advertised client's
        fan-out references. Per-client state (header meta, auth tags) is
        layered on in :meth:`_encode_stream_reply_header` and
        :meth:`_send_stream_reply` — a 256-client fan-out never holds
        more than one encoded copy of the model payload. ``compression``
        overrides the server's reply compression for the QUANTIZED reply
        plan (``--reply-dtype``): at most two plans exist per round — this
        one for capability-advertising clients, the base plan for the
        rest — each still shared across its cohort."""
        if compression is None:
            compression = self.compression
        flat = wire.flatten_lazy(agg)
        tensors, payload_nbytes = wire.plan_stream(flat, compression)
        chunks: list[bytes] = []
        buf = bytearray()
        for t in tensors:
            buf += wire.encode_stream_leaf(flat[t["key"]], t["enc"])
            while len(buf) >= self.stream_chunk_bytes:
                chunks.append(bytes(buf[: self.stream_chunk_bytes]))
                del buf[: self.stream_chunk_bytes]
        if buf:
            chunks.append(bytes(buf))
        return {
            "tensors": tensors,
            "chunks": chunks,
            "payload_nbytes": payload_nbytes,
        }

    def _encode_stream_reply_header(
        self, plan: dict, meta: dict, nonce: str | None
    ) -> bytes:
        """One client's STRH reply header (auth mode echoes its nonce
        with role=server, exactly like the dense reply's meta)."""
        if self.auth_key is not None:
            meta = {**meta, "role": "server", "nonce": nonce}
        return wire.encode_stream_header(
            plan["tensors"],
            meta=meta,
            chunk_bytes=self.stream_chunk_bytes,
            payload_nbytes=plan["payload_nbytes"],
            auth_key=self.auth_key,
            direction="down",
        )

    def _send_stream_reply(
        self,
        conn: socket.socket,
        header: bytes,
        plan: dict,
        nonce: bytes,
    ) -> None:
        """Ship one streamed reply: ACKed header, fire-and-forget chunk
        frames (the client reads them without interleaving ACK writes),
        ACKed trailer — the mirror of the upload direction's shape. Chunk
        envelopes (seq + per-connection tag under the reply-direction
        domain) are built per send, so the shared payload is never
        duplicated per client."""
        framing.send_frame(conn, header)
        for seq, chunk in enumerate(plan["chunks"]):
            framing.send_frame(
                conn,
                wire.encode_stream_chunk(
                    seq,
                    chunk,
                    auth_key=self.auth_key,
                    nonce=nonce,
                    direction="down",
                ),
                await_ack=False,
            )
        framing.send_frame(
            conn,
            wire.encode_stream_end(
                len(plan["chunks"]),
                auth_key=self.auth_key,
                nonce=nonce,
                direction="down",
            ),
        )

    def _reply_all(
        self,
        replies: dict[int, bytes],
        conns_map: dict[int, socket.socket],
        stream_jobs: dict[int, tuple[bytes, bytes, dict]] | None = None,
    ) -> None:
        """Parallel reply fan-out: send_frame blocks on the client's ACK,
        so a sequential loop would let one dead client stall every healthy
        one behind it for a full socket timeout. ``stream_jobs`` clients
        get the chunk-streamed shape instead of their ``replies`` blob;
        each job carries its own plan (base vs ``--reply-dtype`` quantized
        — the plan OBJECTS are still shared per cohort)."""
        stream_jobs = stream_jobs or {}

        def _reply(cid: int, conn: socket.socket) -> None:
            try:
                if cid in stream_jobs:
                    header, nonce, plan = stream_jobs[cid]
                    self._send_stream_reply(conn, header, plan, nonce)
                else:
                    framing.send_frame(conn, replies[cid])
            except (OSError, wire.WireError, ConnectionError) as e:
                log.info(f"[SERVER] reply to client {cid} failed: {e}")
            finally:
                conn.close()

        reply_threads = [
            threading.Thread(
                target=_reply, args=(cid, conns_map[cid]), daemon=True
            )
            for cid in {*replies, *stream_jobs}
        ]
        for t in reply_threads:
            t.start()
        for t in reply_threads:
            t.join(timeout=self.timeout)

    def comm_overlap_frac(self) -> float:
        """Bytes-weighted fraction of this server's aggregation input
        folded while the round's wire phase was still active (0.0 on a
        pure barrier run) — the bench's ``comm_overlap_frac`` headline."""
        with self._totals_lock:
            early = self.stream_totals["early_bytes"]
            tot = early + self.stream_totals["late_bytes"]
        return early / tot if tot else 0.0

    def serve(self, rounds: int = 1) -> None:
        """Multi-round loop: one failed round (quorum missed, DP base
        mismatch, reveal dropout) must not kill the server for every
        remaining round — the reference hangs forever in this situation
        (server.py:124-132); here the round is logged and the next one
        proceeds, so retrying clients can still complete it."""
        for r in range(rounds):
            log.info(f"[SERVER] round {r + 1}/{rounds}")
            try:
                self.serve_round()
            except RuntimeError as e:
                log.info(f"[SERVER] round {r + 1} failed: {e}")
