"""Chunked, acknowledged frame transport over a socket.

Capability parity with the reference's hand-rolled protocol (reference
client1.py:246-273, server.py:29-55) — chunked transfer of ~250 MB payloads
with an end-to-end ACK — minus its failure modes: the ASCII ``len\\n`` header
becomes a fixed binary header with a magic and a CRC-32, so a desynced
stream fails loudly instead of reading garbage lengths, and receivers can
pre-validate size before allocating.

Frame layout::

    MAGIC 'FTPF' | u64 payload length | u32 payload CRC-32 | payload

The receiver replies ``b"FTPK"`` after a verified read (the reference's
``"RECEIVED"`` handshake, client1.py:252-254).
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time

from . import native
from .wire import WireError

FRAME_MAGIC = b"FTPF"
ACK = b"FTPK"
SEND_CHUNK = 1 << 20  # 1 MB, as the reference (client1.py:250-251)
RECV_CHUNK = 4 << 20  # 4 MB cap per recv (client1.py:266)
MAX_FRAME = 8 << 30  # sanity bound before allocating


def recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly n bytes or raise ConnectionError. Returns the bytearray
    itself — frames run to ~250 MB and a bytes() conversion would copy."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], min(n - got, RECV_CHUNK))
        if r == 0:
            raise ConnectionError(f"peer closed after {got}/{n} bytes")
        got += r
    return buf


def send_frame(
    sock: socket.socket, payload: bytes, *, await_ack: bool = True
) -> None:
    """Send one CRC'd frame in 1 MB chunks; wait for the receiver's ACK.

    ``await_ack=False`` sends fire-and-forget (the scoring service's
    request/reply exchange — serving/protocol.py — where the reply itself
    is the acknowledgment and a blocking ACK read per small frame would
    serialize the batching hot path on the slowest client)."""
    crc = native.crc32(payload)
    sock.sendall(FRAME_MAGIC + struct.pack("<QI", len(payload), crc))
    view = memoryview(payload)
    for start in range(0, len(view), SEND_CHUNK):
        sock.sendall(view[start : start + SEND_CHUNK])
    if not await_ack:
        return
    ack = recv_exact(sock, len(ACK))
    if ack != ACK:
        raise WireError(f"bad ACK {ack!r}")


def recv_frame(
    sock: socket.socket,
    *,
    send_ack: bool = True,
    max_frame: int = MAX_FRAME,
) -> bytearray:
    """Receive one frame, verify its CRC, ACK it, return the payload.

    ``send_ack=False`` matches a peer's ``await_ack=False`` send (both
    directions of the scoring protocol): no ACK bytes ever ride the
    socket, so a reply frame written by another thread can never
    interleave with an ACK write from this one. ``max_frame`` lets a
    receiver expecting small frames (one scoring request, not a 250 MB
    model) bound the pre-validated allocation."""
    header = recv_exact(sock, len(FRAME_MAGIC) + 12)
    if header[:4] != FRAME_MAGIC:
        raise WireError(f"bad frame magic {bytes(header[:4])!r}")
    length, crc = struct.unpack("<QI", header[4:])
    if length > min(max_frame, MAX_FRAME):
        raise WireError(
            f"frame length {length} exceeds {min(max_frame, MAX_FRAME)}"
        )
    payload = recv_exact(sock, length)
    got = native.crc32(payload)
    if got != crc:
        raise WireError(f"frame CRC mismatch (got {got:#010x}, want {crc:#010x})")
    if send_ack:
        sock.sendall(ACK)
    return payload


class PipelinedSender:
    """Background frame writer: the streamed upload's wire half.

    The producer enqueues frame payloads; a dedicated thread drains the
    (bounded) queue through :func:`send_frame`, so packing chunk k+1 —
    the host gather + encode work — overlaps chunk k's socket write
    instead of alternating with it. The queue depth bounds how far the
    packer can run ahead (memory: ``depth`` chunks), and the first send
    error is re-raised to the producer on its next ``send`` or on
    ``close`` — a dead socket stops the pipeline within one chunk, not
    after packing the whole model.
    """

    def __init__(self, sock: socket.socket, *, depth: int = 4):
        self._sock = sock
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._err: BaseException | None = None
        self._send_s = 0.0  # seconds spent inside send_frame (wire time)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            payload, await_ack = item
            if self._err is not None:
                continue  # drain so the producer never blocks on put()
            t0 = time.monotonic()
            try:
                send_frame(self._sock, payload, await_ack=await_ack)
            except (OSError, WireError, ConnectionError) as e:
                self._err = e
            finally:
                self._send_s += time.monotonic() - t0

    def send(self, payload: bytes, *, await_ack: bool = False) -> None:
        """Enqueue one frame (blocks when ``depth`` frames are pending);
        raises the wire thread's first error, if any."""
        if self._err is not None:
            raise self._err
        self._q.put((payload, await_ack))

    def close(self) -> float:
        """Flush the queue, join the thread, re-raise any send error.
        Returns the wire thread's cumulative send seconds (the overlap
        accounting the upload span reports)."""
        self._q.put(None)
        self._thread.join()
        if self._err is not None:
            raise self._err
        return self._send_s
